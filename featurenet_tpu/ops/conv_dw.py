"""Pallas TPU kernel for the conv *weight gradient* — the pod64 bottleneck.

Round-1 profiling (BASELINE.md "where the milliseconds go") pinned ~18 ms of
the 53 ms pod64 step on one contraction: conv2's 5³ weight grad,

    dW[t, ci, co] = Σ_{b,z,y,x}  Xp[b, (z,y,x)+t, ci] · G[b, z, y, x, co]

which XLA lowers as a ``[K³·Cin, B·D·H·W, Cout]`` matmul. With Cout=32 the
MXU's 128 output lanes are 25 % occupied — a *shape* ceiling (~60 TF/s
measured), not a lowering failure.

This kernel changes the shape instead of fighting the schedule — **tap
folding**: move the x-axis taps onto the output-column side by contracting
against shifted copies of the cotangent. With reduction index r = (b, z, y,
kx) over the padded x extent:

    A[r, (tz,ty,ci)] = Xp[b, z+tz, y+ty, kx, ci]         (z/y-shifted views)
    B[r, (tx,co)]    = G [b, z,    y,    kx-tx, co]      (x-shifted, 0-padded)
    dWf = Aᵀ B        — one [k²·Cin, R, k·Cout] matmul

Both matmul dims are now MXU-scale (5³ conv, 32→32: M=800, N=160 vs the
naive N=32), and the shifted-copy construction costs O(R·(M+N)) VPU moves
against O(R·M·N) MXU MACs — noise. ``dWf`` un-folds to ``[k,k,k,Cin,Cout]``
outside the kernel. Equivalence to the XLA weight grad is exact (same sums,
fp32 accumulation); tested against ``lax.conv`` VJP in ``tests/test_ops.py``.

Memory plan (hard-won; the dead ends live in git history):
- VMEM tiling pads the lane (minor) dim to 128, so a whole-sample block
  with Cin=32 lanes costs 4× its nominal bytes — 42 MB against the 16 MB
  core. Blocks must therefore be (z, y)-chunked.
- Chunking z needs overlapping windows (the k-tap halo), which BlockSpec
  index maps cannot express and the DMA engine refuses for a 32-lane minor
  (manual ``make_async_copy`` requires 8/128-aligned slice extents). The
  halo is instead materialized host-side: ``Xp`` is restacked into
  ``[B, D/tz, tz+2p, Hp, Wp, Cin]`` z-windows — ~(tz+2p)/tz× extra HBM
  traffic on x, amortized against the 4× MXU-occupancy win.
- The y-halo stays inside the block (blocks span full Hp; the y-chunk
  offset is a dynamic ``pl.ds`` on a free dim, which is unconstrained).
- An unrolled python chunk loop would give every iteration its own scoped
  stack slot; the grid plays that role instead (one (b, zc, yc) chunk per
  grid step), with the fp32 dWf output block revisited as the accumulator.

Used by ``ops.conv3d.HybridConv`` (conv_backend="hybrid_dw"): XLA forward
and input grad (already near ceiling), this kernel for the weight grad only.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _dw_folded_kernel(k: int, tz: int, hy: int, h: int, w: int,
                      cin: int, cout: int):
    """Grid = (B, D/tz, H/hy); one (z,y)-chunk of one sample per step."""
    p = (k - 1) // 2
    wp = -(-(w + 2 * p) // 8) * 8  # 8-aligned: sublane-aligned row merges

    def kernel(xw_ref, g_ref, dwf_ref):
        first = (
            (pl.program_id(0) == 0)
            & (pl.program_id(1) == 0)
            & (pl.program_id(2) == 0)
        )

        @pl.when(first)
        def _():
            dwf_ref[...] = jnp.zeros_like(dwf_ref)

        yc = pl.program_id(2)
        gs = g_ref[0, 0]  # [tz, hy, w, cout]
        # A: lane-concat of the k² (dz,dy) shifted views of x. z/y are free
        # dims (x is the sublane dim, channels the lane dim), so these are
        # relayout-free loads; the y offset rides a dynamic pl.ds into the
        # full-height block.
        a = jnp.concatenate(
            [
                xw_ref[0, 0, dz:dz + tz, pl.ds(yc * hy + dy, hy)]
                for dz in range(k)
                for dy in range(k)
            ],
            axis=-1,
        )  # [tz, hy, wp, k²·cin]
        # B: lane-concat of the k x-shifted, zero-padded copies of g; kx
        # runs over the padded x extent, copy tx holds G[kx - tx].
        bm = jnp.concatenate(
            [
                jnp.pad(gs, ((0, 0), (0, 0), (tx, wp - w - tx), (0, 0)))
                for tx in range(k)
            ],
            axis=-1,
        )  # [tz, hy, wp, k·cout]
        # Mosaic's tpu.matmul wants a single contracting dim: collapse
        # (z, y, kx) to rows; the relayout is amortized over the
        # [k²·Cin, rows, k·Cout] MXU contraction.
        rows = tz * hy * wp
        dwf_ref[...] = dwf_ref[...] + jax.lax.dot_general(
            a.reshape(rows, k * k * cin),
            bm.reshape(rows, k * cout),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    return kernel


def _tiled_bytes(shape, itemsize) -> int:
    """VMEM cost of ``shape``: lane (minor) dim padded to 128, sublane
    (second-minor) to 8 — what Mosaic actually allocates."""
    s = list(shape)
    s[-1] = -(-s[-1] // 128) * 128
    s[-2] = -(-s[-2] // 8) * 8
    n = itemsize
    for v in s:
        n *= v
    return n


def _pick_chunks(d, h, w, k, cin, cout, itemsize) -> tuple[int, int] | None:
    """(tz, hy) whose tiled VMEM plan fits the core."""
    p = (k - 1) // 2
    hp, wp = h + 2 * p, -(-(w + 2 * p) // 8) * 8
    budget = 12 * 1024 * 1024
    out = _tiled_bytes((k * k * cin, k * cout), 4)
    for tz in (4, 2, 8):
        if d % tz:
            continue
        for hy in (8, 4, 2):
            if h % hy:
                continue
            plan = (
                2 * _tiled_bytes((tz + 2 * p, hp, wp, cin), itemsize)  # xw
                + 2 * _tiled_bytes((tz, hy, w, cout), itemsize)        # g
                + out
                # A/B concats + their reshaped matmul operands (~2× each).
                + 2 * _tiled_bytes((tz, hy, wp, k * k * cin), itemsize)
                + 2 * _tiled_bytes((tz, hy, wp, k * cout), itemsize)
            )
            if plan <= budget:
                return tz, hy
    return None


def dw_folded_supported(x_shape, k: int, cout: int, dtype) -> bool:
    if len(x_shape) != 5 or k % 2 == 0:
        return False
    _, d, h, w, cin = x_shape
    return (
        _pick_chunks(d, h, w, k, cin, cout, jnp.dtype(dtype).itemsize)
        is not None
    )


@functools.partial(jax.jit, static_argnames=("k",))
def conv_dw_folded(x: jax.Array, g: jax.Array, k: int) -> jax.Array:
    """Weight grad of a stride-1 SAME odd-K conv via the tap-folded matmul.

    ``x``: [B, D, H, W, Cin] activations (bf16 or fp32);
    ``g``: [B, D, H, W, Cout] cotangent (same dtype);
    returns [k, k, k, Cin, Cout] fp32 — the same sums as the XLA conv VJP's
    weight grad (fp32 accumulation either way).
    """
    b, d, h, w, cin = x.shape
    cout = g.shape[-1]
    p = (k - 1) // 2
    chunks = _pick_chunks(d, h, w, k, cin, cout, x.dtype.itemsize)
    if chunks is None:
        raise ValueError(f"conv_dw_folded: {x.shape} exceeds the VMEM plan")
    tz, hy = chunks
    wp = -(-(w + 2 * p) // 8) * 8  # extra zero x-columns contribute 0
    xp = jnp.pad(x, ((0, 0), (p, p), (p, p), (p, wp - w - p), (0, 0)))
    # Overlapping z-windows, materialized (see memory plan in the module
    # docstring): window zc covers padded-z rows [zc·tz, zc·tz + tz + 2p).
    xw = jnp.stack(
        [xp[:, i * tz: i * tz + tz + 2 * p] for i in range(d // tz)], axis=1
    )  # [B, D/tz, tz+2p, Hp, Wp, Cin]
    dwf = pl.pallas_call(
        _dw_folded_kernel(k, tz, hy, h, w, cin, cout),
        grid=(b, d // tz, h // hy),
        in_specs=[
            pl.BlockSpec(
                (1, 1, tz + 2 * p, h + 2 * p, wp, cin),
                lambda b_, zc, yc: (b_, zc, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, tz, hy, w, cout),
                # g viewed as [B, D/tz, tz, H, W, C] z-chunks via reshape.
                lambda b_, zc, yc: (b_, zc, 0, yc, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (k * k * cin, k * cout),
            lambda b_, zc, yc: (0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((k * k * cin, k * cout), jnp.float32),
        interpret=_interpret(),
    )(xw, g.reshape(b, d // tz, tz, h, w, cout))
    # Un-fold: [(tz,ty,ci), (tx,co)] → [tz,ty,tx,ci,co].
    dw = dwf.reshape(k, k, cin, k, cout)
    return dw.transpose(0, 1, 3, 2, 4)
