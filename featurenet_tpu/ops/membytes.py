"""Analytic HBM byte model for the compiled train step (the bytes sibling of
``ops/flops.py``).

Why this exists: the best seg64 model silently trained 8× slower than its
dispatch amortization allowed — the 8-step fused executable exceeded HBM, the
failure surfaced as a compile-time OOM, and the fix was an operator hand-edit
to ``steps_per_dispatch=1`` (BASELINE.md round 4). ``max_feasible_k`` makes
that decision analytic and automatic at Trainer build time, mirroring
``parallel.mesh.clamp_model_axis``'s degrade-don't-crash pattern.

Calibration (XLA's own ``compiled.memory_analysis()`` on the real TPU v5e,
round 5 — the probe lowers the HBM-resident fused step with abstract args):

| config  | batch | k | temp bytes | args bytes |
|---|---|---|---|---|
| seg64 (combined) | 32 | 1 | 13.16 G | 1.185 G |
| seg64 | 32 | 2 | 14.70 G | 1.185 G |
| seg64 | 32 | 4 | 16.80 G | 1.185 G |
| seg64 | 32 | 8 | compile refused (remote helper OOM) | — |
| warp64 | 256 | 1 | 1.267 G | 0.685 G |
| warp64 | 256 | 8 | 1.817 G | 0.685 G |
| sprint64 | 256 | 8 | 1.820 G | 0.685 G |

Two facts drive the model: (1) per-step activation peak dominates temp at
k=1; (2) XLA retains roughly 6–12 % of that peak per additional fused step
(seg 0.092, warp 0.062 measured) — fused steps are sequenced, but buffer
assignment still overlaps across step boundaries. Coefficients below are
fit so the analytic seg64 k=1 activation estimate lands within ~5 % of the
measured 13.16 G; the per-step retention uses the conservative end (0.12).

This is a FIRST-ORDER model: it exists to pick ``k``, not to replace the
compiler's buffer assignment. ``k=1`` is always allowed (the proven
fallback); the question the model answers is whether ``k>1`` is safe.
"""

from __future__ import annotations

import math

# TPU v5e: 16 GB HBM, of which XLA reported 15.75 G usable in the seg64
# compile-OOM incident message (BASELINE.md round 4).
HBM_BYTES = 15.75e9
# Reject k>1 unless the estimate fits in this fraction of the budget —
# absorbs the model's first-order error (measured within ~±10 % on the
# calibration points, headroom for shapes it has not seen).
SAFETY = 0.85
# Live tensors per ConvBNRelu block, in units of the block's output size at
# bf16: conv out (pre-BN, kept for the BN backward), BN/relu out (kept for
# the next conv's backward), plus BN-stat and fusion residue. Fit to the
# seg64 k=1 measurement (2.5 from first principles underestimated by ~20 %).
CONV_BLOCK_TENSORS = 3.2
# Fraction of the per-step activation peak XLA retains per extra fused step.
FUSED_STEP_RETENTION = 0.12


def state_bytes(params_n: int, optimizer: str = "adamw",
                precision: str = "fp32") -> int:
    """Persistent training-state bytes: fp32 master params + optimizer
    slots + the gradient tree live during the update.

    ``precision`` is the training precision policy
    (``train/precision.py``). Under the master/working split policies
    (``bf16_master`` and ``fp16_scaled`` — bfloat16 and float16 are both
    2 bytes, so the byte model is identical) the step additionally holds
    a 2-byte WORKING copy of the params and stores the backward's
    gradients at 2 bytes — but the fp32 upcast of those gradients (4) is
    live through the optimizer update, so first-order both gradient
    trees are counted alongside the fp32 masters. (fp16_scaled's
    loss-scale state is two scalars — not a term.) Net: the master
    split trades activation-side casts for ~1.25x the state-side bytes
    (20 vs 16 bytes/param with adamw; 16 vs 12 with sgd) — negligible
    against activations for these ~4M-param configs, but the model must
    say it, not hide it."""
    slots = {"adamw": 2, "adam": 2, "sgd": 1}.get(optimizer, 2)
    if precision in ("bf16_master", "fp16_scaled"):
        # masters(4) + working(2) + 2-byte grads(2) + fp32 grads(4) + slots
        return int(params_n * (12 + 4 * slots))
    return int(params_n * 4 * (2 + slots))  # params + grads + slots


def wire_batch_bytes(cfg) -> int:
    """One bit-packed wire batch (what each fused step holds as input)."""
    b, r = cfg.global_batch, cfg.resolution
    vox = b * r * r * (r // 8)  # uint8 packed
    tgt = b * r * r * r if cfg.task == "segment" else b * 4
    return vox + tgt


def resident_split_bytes(cfg, n_rows: int) -> int:
    """The HBM-resident packed train split (hbm_cache mode)."""
    if not n_rows:
        return 0
    r = cfg.resolution
    vox = n_rows * r * r * (r // 8)
    tgt = n_rows * r * r * r if cfg.task == "segment" else n_rows * 4
    return vox + tgt


def classifier_act_bytes_per_sample(arch, resolution: int) -> int:
    """Per-sample activation bytes of one FeatureNet train step (bf16
    conv stack + fp32 input/loss edges), the same walk as
    ``flops.classifier_forward_flops``."""
    total = 4 * resolution**3  # unpacked fp32 input
    d = resolution
    for f, s, p in zip(arch.features, arch.strides, arch.pool_after):
        d = math.ceil(d / s)
        total += int(CONV_BLOCK_TENSORS * 2 * f * d**3)
        if p:
            d //= 2
    flat = arch.features[-1] if arch.head_gap else arch.features[-1] * d**3
    # Dense-land: flatten/GAP out, hidden (+ dropout mask), logits + softmax.
    total += 4 * flat + 3 * 4 * arch.hidden + 3 * 4 * arch.num_classes
    return total


def segmenter_act_bytes_per_sample(
    features, resolution: int, num_classes: int,
    input_context: str = "none", decoder_blocks: int = 1,
    bottleneck_blocks: int = 1,
) -> int:
    """Per-sample activation bytes of one U-Net segmenter train step,
    walking encoder/bottleneck/decoder exactly as ``FeatureNetSegmenter``
    composes them (models/segmenter.py)."""
    R = resolution
    in_ch = {"none": 1, "proj": 4, "proj_coords": 7}[input_context]
    total = 4 * R**3 + 2 * in_ch * R**3  # fp32 input + bf16 concat
    blk = CONV_BLOCK_TENSORS * 2  # bytes per (channel · voxel) per block

    d = R
    for f in features:
        total += int(blk * f * d**3)  # refine (also the saved skip)
        d //= 2
        total += int(blk * f * d**3)  # strided downsample
    for _ in range(bottleneck_blocks):
        total += int(blk * features[-1] * 2 * d**3)
    for f in reversed(features):
        d *= 2
        total += 2 * f * d**3  # transposed-conv out
        total += 2 * 2 * f * d**3  # skip concat
        total += int(blk * f * d**3) * decoder_blocks
    # Loss land at fp32 over num_classes+1 channels: logits, softmax probs,
    # one-hot target, per-voxel CE (ce_dice keeps probs and one-hot live
    # through the Dice reduction's backward).
    total += 3 * 4 * (num_classes + 1) * R**3 + 4 * R**3
    return total


def act_bytes_per_sample(cfg) -> int:
    if cfg.task == "segment":
        from featurenet_tpu.data.synthetic import NUM_CLASSES

        return segmenter_act_bytes_per_sample(
            tuple(cfg.seg_features), cfg.resolution, NUM_CLASSES,
            cfg.seg_input_context, cfg.seg_decoder_blocks,
            cfg.seg_bottleneck_blocks,
        )
    act = classifier_act_bytes_per_sample(cfg.arch, cfg.resolution)
    if cfg.augment_affine:
        # Trilinear resample temporaries: source-coordinate grid + warped
        # fp32 output + gather intermediates (~3 input-size fp32 tensors).
        act += 3 * 4 * cfg.resolution**3
    return act


def fused_step_bytes(cfg, k: int, params_n: int, n_rows: int = 0) -> int:
    """Estimated peak HBM bytes of the k-fused train-step executable.
    The state term follows ``cfg.train_precision`` (master/working split
    under ``bf16_master``), so the dispatch-k clamp sees the policy the
    executable will actually compile under."""
    act = act_bytes_per_sample(cfg) * cfg.global_batch
    temp = int(act * (1.0 + FUSED_STEP_RETENTION * (k - 1)))
    return (
        state_bytes(params_n, cfg.optimizer,
                    getattr(cfg, "train_precision", "fp32"))
        + resident_split_bytes(cfg, n_rows)
        + k * wire_batch_bytes(cfg)
        + temp
    )


def max_feasible_k(
    cfg, params_n: int, n_rows: int = 0, budget: float | None = None,
    requested: int | None = None,
) -> int:
    """Largest ``steps_per_dispatch`` ≤ ``requested`` whose estimated fused
    executable fits ``SAFETY × budget``. ``k=1`` is always allowed: it is
    the incident-proven fallback, and refusing to train at all on a model
    estimate would be worse than trusting the compiler's own OOM error."""
    if budget is None:
        budget = HBM_BYTES  # late-bound so tests can shrink the budget
    want = cfg.steps_per_dispatch if requested is None else requested
    k = max(1, want)
    while k > 1 and fused_step_bytes(cfg, k, params_n, n_rows) > SAFETY * budget:
        k -= 1
    return k
