"""Space-to-depth reformulation of strided 3D convolutions.

Why this exists (measured on TPU v5e, see BASELINE.md): the paper-shape stem —
7³ kernel, stride 2, **one** input channel on a 64³ grid (SURVEY.md §3.3) — is
the worst possible shape for XLA:TPU's conv lowering. The channel dimension is
the MXU contraction axis, and with C_in=1 the systolic array runs at 1/128th
occupancy: measured 10 TF/s vs 60–140 TF/s for the later C_in≥32 layers.
SURVEY.md §7 flagged exactly this ("7×7×7 stride-2 conv lowering on TPU",
hard part #4).

The fix is algebraic, not a hand-written kernel: a stride-``s`` convolution
over ``x`` equals a stride-1 convolution over the space-to-depth transform of
``x`` (each s³ block of voxels becomes s³ channels) with a re-indexed weight
tensor. The transform multiplies the contraction axis by s³ (1 → 8 for the
stem) and shrinks the spatial extent by s per axis, which XLA lowers at far
better MXU occupancy — measured 5.3x faster than the direct stride-2 conv
(slope-timed; BASELINE.md), the same math to rounding error.

Derivation. With SAME padding, ``out[o] = Σ_k x[s·o + k - p_lo] · w[k]`` per
axis, ``p_lo = (K - s) // 2``. Write ``k - p_lo = s·a + r`` with ``r ∈ [0,s)``:
the input index becomes ``s·(o + a) + r`` — i.e. tap ``a`` of a stride-1 conv
over the parity-``r`` subgrid. Taps ``a`` span ``[a_min, a_max]`` with
``a_min = floor(-p_lo / s)``, so the transformed conv has kernel size
``a_max - a_min + 1`` and asymmetric padding ``(-a_min, a_max)``.

The parameter stays in the reference's shape ``[K, K, K, C_in, C_out]``; the
scatter into the transformed weight ``w2`` is traced and differentiable, so
autodiff produces exact gradients in the original parametrization. Leaf
*shapes* match the direct formulation, but the Flax module (and hence the
checkpoint tree path) differs — a checkpoint restores only under the
``stem_s2d`` setting it was trained with.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from flax import linen as nn


def _plan(resolution: int, kernel: int, stride: int):
    """Static plan: tap index maps for the transformed weight.

    Returns (k2, pads, src_idx, dst_idx): transformed kernel size, stride-1
    padding (lo, hi), and flat scatter indices mapping original-weight taps
    into the transformed weight (computed per axis, combined over 3 axes by
    the caller).
    """
    if resolution % stride:
        raise ValueError(f"resolution {resolution} not divisible by stride {stride}")
    if kernel < stride:
        raise ValueError("space-to-depth needs kernel >= stride")
    pad_lo = (kernel - stride) // 2
    a = np.arange(kernel)  # original tap index k per axis
    shifted = a - pad_lo
    tap = shifted // stride          # stride-1 tap index a (floor div)
    parity = shifted - tap * stride  # r in [0, stride)
    a_min, a_max = int(tap.min()), int(tap.max())
    k2 = a_max - a_min + 1
    return k2, (-a_min, a_max), tap - a_min, parity


def space_to_depth(x: jnp.ndarray, s: int) -> jnp.ndarray:
    """[B, D, H, W, C] → [B, D/s, H/s, W/s, s³·C]; channel = ((rz·s+ry)·s+rx)·C + c."""
    b, d, h, w, c = x.shape
    x = x.reshape(b, d // s, s, h // s, s, w // s, s, c)
    x = x.transpose(0, 1, 3, 5, 2, 4, 6, 7)
    return x.reshape(b, d // s, h // s, w // s, s * s * s * c)


def transform_weights(w: jnp.ndarray, resolution: int, stride: int) -> tuple:
    """Scatter ``w[K,K,K,Cin,Cout]`` into the stride-1 weight ``w2``.

    Returns (w2, pads) where ``w2`` has shape [K2, K2, K2, s³·Cin, Cout] and
    ``pads`` is the per-axis asymmetric (lo, hi) padding for the stride-1 conv.
    Differentiable: ``w2`` is a traced scatter of ``w``.
    """
    k = w.shape[0]
    cin, cout = w.shape[3], w.shape[4]
    s = stride
    k2, pads, tap, parity = _plan(resolution, k, s)
    # Flat index arithmetic in numpy (static): for each original tap
    # (kz, ky, kx) find its slot (az, ay, ax, parity-channel) in w2.
    kz, ky, kx = np.meshgrid(np.arange(k), np.arange(k), np.arange(k), indexing="ij")
    az, ay, ax = tap[kz], tap[ky], tap[kx]
    pz, py, px = parity[kz], parity[ky], parity[kx]
    pchan = (pz * s + py) * s + px  # parity block within the s³·Cin channels
    w2 = jnp.zeros((k2, k2, k2, s * s * s, cin, cout), w.dtype)
    w2 = w2.at[az.ravel(), ay.ravel(), ax.ravel(), pchan.ravel()].set(
        w.reshape(k * k * k, cin, cout)
    )
    w2 = w2.reshape(k2, k2, k2, s * s * s * cin, cout)
    return w2, (pads, pads, pads)


def space_to_depth_conv(
    x: jnp.ndarray, w: jnp.ndarray, stride: int
) -> jnp.ndarray:
    """Stride-``s`` SAME conv computed as a stride-1 conv on s2d(x).

    ``x``: [B, R, R, R, Cin]; ``w``: [K, K, K, Cin, Cout] (the reference
    parametrization). Matches ``lax.conv_general_dilated(..., (s,s,s),
    "SAME")`` to rounding error, at MXU-friendly contraction size s³·Cin.
    """
    r = x.shape[1]
    w2, pads = transform_weights(w, r, stride)
    x2 = space_to_depth(x, stride)
    return _conv_s1(x2, w2, pads)


def _conv_s1(x2, w2, pads):
    import jax

    return jax.lax.conv_general_dilated(
        x2,
        w2,
        window_strides=(1, 1, 1),
        padding=list(pads),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )


class SpaceToDepthConv(nn.Module):
    """Drop-in strided conv block (no bias) using the s2d reformulation.

    Parameter ``kernel`` has the same [K,K,K,Cin,Cout] shape and init as
    ``nn.Conv``'s, so arch configs and param counts match the direct path.
    """

    features: int
    kernel_size: int
    stride: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        k = self.kernel_size
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(batch_axis=(), in_axis=(0, 1, 2, 3)),
            (k, k, k, cin, self.features),
            jnp.float32,
        )
        return space_to_depth_conv(
            x.astype(self.dtype), kernel.astype(self.dtype), self.stride
        )
