"""Device-side pose augmentation: the cube rotation group, inside the step.

The paper augments training parts over the 24 axis-aligned orientations
(SURVEY.md §2 C3); the host-side version (``data/offline.py`` ``augment=True``)
rotates uint8 grids in the data workers. This module moves that work into the
compiled train step: rotations are transposes+flips — pure layout ops that
cost ~nothing on-device — so host workers only gather and cast, and the
augmentation never bottlenecks the input pipeline.

Batched-``switch`` caveat: a per-sample rotation code under ``vmap`` would
lower to computing all 24 branches and selecting (24x the memory traffic).
Instead the batch is split into ``groups`` contiguous slices, each rotated by
one scalar-code ``lax.switch`` (single branch executed). Group count trades
per-batch pose diversity against trace size; across steps every sample still
sees uniformly-random poses.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp

# The 24 rotations of the cube: axis permutations of (D, H, W) combined with
# axis flips whose overall determinant is +1 (proper rotations only).
CUBE_GROUP: list[tuple[tuple[int, int, int], tuple[bool, bool, bool]]] = []
for _perm in itertools.permutations((0, 1, 2)):
    _inv = sum(
        1 for i in range(3) for j in range(i + 1, 3) if _perm[i] > _perm[j]
    )
    _perm_sign = -1 if _inv % 2 else 1
    for _flips in itertools.product((False, True), repeat=3):
        _flip_sign = -1 if sum(_flips) % 2 else 1
        if _perm_sign * _flip_sign == 1:
            CUBE_GROUP.append((_perm, _flips))
assert len(CUBE_GROUP) == 24


def apply_rotation(x: jnp.ndarray, perm, flips, spatial_start: int = 1):
    """Apply one cube rotation to spatial dims [s, s+3) of ``x``."""
    s = spatial_start
    order = (
        tuple(range(s))
        + tuple(s + p for p in perm)
        + tuple(range(s + 3, x.ndim))
    )
    x = jnp.transpose(x, order)
    flip_axes = [s + i for i, f in enumerate(flips) if f]
    return jnp.flip(x, flip_axes) if flip_axes else x


def rotate_grids(x: jnp.ndarray, code, spatial_start: int = 1):
    """Rotate ``x`` (spatial dims must be equal-length) by group element
    ``code`` (scalar int in [0, 24)). Safe under jit; one branch executes."""
    branches = [
        (lambda g, p=p, f=f: apply_rotation(g, p, f, spatial_start))
        for p, f in CUBE_GROUP
    ]
    return jax.lax.switch(code, branches, x)


def random_rotate_batch(
    voxels: jnp.ndarray, rng: jax.Array, groups: int = 8
) -> jnp.ndarray:
    """Rotate ``[B, R, R, R, C]`` voxels, one random pose per batch group."""
    return random_rotate_batch_paired(voxels, None, rng, groups)[0]


def random_rotate_batch_paired(
    voxels: jnp.ndarray,
    seg: jnp.ndarray | None,
    rng: jax.Array,
    groups: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """Rotate voxels and (optionally) a per-voxel target with SHARED poses.

    Segmentation targets must rotate with the part — ``seg`` is
    ``[B, R, R, R]`` (any integer dtype; rotations are pure layout ops) and
    each batch group gets the same group element applied to both arrays.
    """
    b = voxels.shape[0]
    while b % groups:
        groups -= 1
    codes = jax.random.randint(rng, (groups,), 0, len(CUBE_GROUP))
    step = b // groups

    def rot(x):
        return jnp.concatenate(
            [
                rotate_grids(x[i * step : (i + 1) * step], codes[i])
                for i in range(groups)
            ],
            axis=0,
        )

    return rot(voxels), (rot(seg) if seg is not None else None)
