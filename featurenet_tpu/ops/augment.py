"""Device-side pose augmentation: the cube rotation group, inside the step.

The paper augments training parts over the 24 axis-aligned orientations
(SURVEY.md §2 C3); the host-side version (``data/offline.py`` ``augment=True``)
rotates uint8 grids in the data workers. This module moves that work into the
compiled train step: rotations are transposes+flips — pure layout ops that
cost ~nothing on-device — so host workers only gather and cast, and the
augmentation never bottlenecks the input pipeline.

Batched-``switch`` caveat: a per-sample rotation code under ``vmap`` would
lower to computing all 24 branches and selecting (24x the memory traffic).
Instead the batch is split into ``groups`` contiguous slices, each rotated by
one scalar-code ``lax.switch`` (single branch executed). Group count trades
per-batch pose diversity against trace size; across steps every sample still
sees uniformly-random poses.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp

# The 24 rotations of the cube: axis permutations of (D, H, W) combined with
# axis flips whose overall determinant is +1 (proper rotations only).
CUBE_GROUP: list[tuple[tuple[int, int, int], tuple[bool, bool, bool]]] = []
for _perm in itertools.permutations((0, 1, 2)):
    _inv = sum(
        1 for i in range(3) for j in range(i + 1, 3) if _perm[i] > _perm[j]
    )
    _perm_sign = -1 if _inv % 2 else 1
    for _flips in itertools.product((False, True), repeat=3):
        _flip_sign = -1 if sum(_flips) % 2 else 1
        if _perm_sign * _flip_sign == 1:
            CUBE_GROUP.append((_perm, _flips))
assert len(CUBE_GROUP) == 24


def apply_rotation(x: jnp.ndarray, perm, flips, spatial_start: int = 1):
    """Apply one cube rotation to spatial dims [s, s+3) of ``x``."""
    s = spatial_start
    order = (
        tuple(range(s))
        + tuple(s + p for p in perm)
        + tuple(range(s + 3, x.ndim))
    )
    x = jnp.transpose(x, order)
    flip_axes = [s + i for i, f in enumerate(flips) if f]
    return jnp.flip(x, flip_axes) if flip_axes else x


def rotate_grids(x: jnp.ndarray, code, spatial_start: int = 1):
    """Rotate ``x`` (spatial dims must be equal-length) by group element
    ``code`` (scalar int in [0, 24)). Safe under jit; one branch executes."""
    branches = [
        (lambda g, p=p, f=f: apply_rotation(g, p, f, spatial_start))
        for p, f in CUBE_GROUP
    ]
    return jax.lax.switch(code, branches, x)


def _quat_to_matrix(q: jnp.ndarray) -> jnp.ndarray:
    """Unit quaternion [4] → rotation matrix [3,3] (uniform over SO(3)
    when q is a normalized iid-normal draw)."""
    q = q / jnp.linalg.norm(q)
    w, x, y, z = q[0], q[1], q[2], q[3]
    return jnp.array([
        [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
        [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
        [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
    ])


def random_affine_batch(
    voxels: jnp.ndarray,
    rng: jax.Array,
    groups: int = 8,
    scale_range: tuple[float, float] = (0.7, 1.05),
) -> jnp.ndarray:
    """SO(3) rotation + uniform scale, inside the step (classify wrapper
    over ``random_affine_batch_paired`` — see there for the full story)."""
    return random_affine_batch_paired(
        voxels, None, rng, groups=groups, scale_range=scale_range
    )[0]


def random_affine_batch_paired(
    voxels: jnp.ndarray,
    seg: jnp.ndarray | None,
    rng: jax.Array,
    groups: int = 8,
    scale_range: tuple[float, float] = (0.7, 1.05),
    rotate: bool = True,
    translate_vox: float = 0.0,
    prob=1.0,
) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """Arbitrary-angle SO(3) rotation + uniform scale + translation, inside
    the compiled step, optionally warping a per-voxel target with SHARED
    transforms.

    The cube group (``random_rotate_batch``) covers only the 24 axis-
    aligned poses; round 4's OOD harness measured the flagship collapsing
    to chance at a 5° off-axis rotation, and a *statically* rotated
    training cache (one pose per part) overfits instead of generalizing —
    pose diversity must be infinite, i.e. drawn per step on device. Each
    batch group gets one random rotation (uniform SO(3) via quaternion)
    composed with one uniform scale and translation draw; voxels are
    trilinearly resampled (``jax.scipy.ndimage.map_coordinates``) through
    the inverse affine about the grid center. The scale range defaults to
    [0.7, 1.05] because the eval-side mesh pipeline refits a rotated
    part's grown AABB back into the unit cube — rotated eval parts are
    *smaller* by up to ~1/√3 — and because it doubles as margin-shift
    (scale family) robustness.

    Round-5 levers (the robust64 recipe search — BASELINE.md):
    - ``prob``: per-group probability of applying the warp (clean/affine
      batch mixing — the rest of the group passes through untouched,
      matching the normalized serving distribution). May be a traced
      scalar, so the Trainer can ramp it over the schedule.
    - ``rotate=False``: scale+translate only — parameter-extrapolation
      augmentation (feature size/position jitter) without buying the much
      harder rotation-invariance problem.
    - ``translate_vox``: uniform per-axis translation draw in [-t, +t]
      voxels (position extrapolation; 0 disables).
    - ``seg``: ``[B, D, H, W]`` integer per-voxel target warped with the
      SAME group transforms, nearest-neighbor (order-0) resampled so
      labels never blend.

    Gather-heavy VPU work, roughly comparable to one small conv. Voxels
    stay float in [0, 1] (interpolated occupancy — the model consumes
    float voxels either way).
    """
    b = voxels.shape[0]
    while b % groups:
        groups -= 1
    D, H, W = voxels.shape[1:4]
    keys = jax.random.split(rng, groups)
    c = jnp.array([(D - 1) / 2.0, (H - 1) / 2.0, (W - 1) / 2.0])
    grid = jnp.stack(
        jnp.meshgrid(
            jnp.arange(D, dtype=jnp.float32),
            jnp.arange(H, dtype=jnp.float32),
            jnp.arange(W, dtype=jnp.float32),
            indexing="ij",
        )
    ).reshape(3, -1)  # [3, D*H*W]

    def src_coords(key):
        kq, ks, kt = jax.random.split(key, 3)
        s = jax.random.uniform(
            ks, (), minval=scale_range[0], maxval=scale_range[1]
        )
        t = (
            jax.random.uniform(
                kt, (3,), minval=-translate_vox, maxval=translate_vox
            )
            if translate_vox > 0.0
            else jnp.zeros(3)
        )
        # Inverse map: output voxel p samples input at
        # R^T (p - c - t) / s + c.
        shifted = (grid - (c + t)[:, None]) / s
        if rotate:
            rot = _quat_to_matrix(jax.random.normal(kq, (4,)))
            shifted = rot.T @ shifted
        return shifted + c[:, None]

    def warp_group(vox, seg_g, key):
        kc, kp = jax.random.split(key)
        src = src_coords(kc)

        def sample_one(v, order):  # v: [D, H, W]
            return jax.scipy.ndimage.map_coordinates(
                v, [src[0], src[1], src[2]], order=order, mode="constant",
                cval=0.0,
            ).reshape(D, H, W)

        def apply(args):
            vox, seg_g = args
            # [n, D, H, W, C] → vmap over batch then channels.
            warped = jax.vmap(
                lambda g: jnp.stack(
                    [sample_one(g[..., ch], 1) for ch in range(g.shape[-1])],
                    axis=-1,
                )
            )(vox)
            if seg_g is None:
                return warped, None
            # Nearest-neighbor for labels: order-0 gather, exact values.
            wseg = jax.vmap(
                lambda g: sample_one(g.astype(jnp.float32), 0)
            )(seg_g).astype(seg_g.dtype)
            return warped, wseg

        take = jax.random.bernoulli(kp, prob)
        return jax.lax.cond(
            take, apply, lambda args: args, (vox, seg_g)
        )

    step = b // groups
    vox_parts, seg_parts = [], []
    for i in range(groups):
        sl = slice(i * step, (i + 1) * step)
        v, s = warp_group(
            voxels[sl], None if seg is None else seg[sl], keys[i]
        )
        vox_parts.append(v)
        seg_parts.append(s)
    out_vox = jnp.concatenate(vox_parts, axis=0)
    out_seg = (
        None if seg is None else jnp.concatenate(seg_parts, axis=0)
    )
    return out_vox, out_seg


def random_rotate_batch(
    voxels: jnp.ndarray, rng: jax.Array, groups: int = 8
) -> jnp.ndarray:
    """Rotate ``[B, R, R, R, C]`` voxels, one random pose per batch group."""
    return random_rotate_batch_paired(voxels, None, rng, groups)[0]


def random_rotate_batch_paired(
    voxels: jnp.ndarray,
    seg: jnp.ndarray | None,
    rng: jax.Array,
    groups: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray | None]:
    """Rotate voxels and (optionally) a per-voxel target with SHARED poses.

    Segmentation targets must rotate with the part — ``seg`` is
    ``[B, R, R, R]`` (any integer dtype; rotations are pure layout ops) and
    each batch group gets the same group element applied to both arrays.
    """
    b = voxels.shape[0]
    while b % groups:
        groups -= 1
    codes = jax.random.randint(rng, (groups,), 0, len(CUBE_GROUP))
    step = b // groups

    def rot(x):
        return jnp.concatenate(
            [
                rotate_grids(x[i * step : (i + 1) * step], codes[i])
                for i in range(groups)
            ],
            axis=0,
        )

    return rot(voxels), (rot(seg) if seg is not None else None)
