"""Analytic FLOP model for the FeatureNet conv stacks (MFU accounting).

Reference parity note: the reference publishes no FLOPs/MFU accounting at all
(SURVEY.md §6 — throughput was never even reported); this exists so the
rebuild's headline samples/sec/chip can be stated *with* its model-flops
utilization, making "within X% of ceiling" claims checkable from the bench
artifact alone (round-1 verdict asked for exactly this).

Counting convention — the standard "2·MACs" model:
- conv: 2 · K³ · C_in · C_out · out_voxels per sample (SAME padding:
  out = ceil(in / stride); the count includes padded taps, matching how the
  MXU actually spends cycles on a SAME conv).
- dense: 2 · in · out.
- train step ≈ 3× forward (backward = input-grad + weight-grad, each the
  same contraction volume as forward). BN, pooling, bias, softmax are
  bandwidth-bound elementwise work and excluded, as is the optimizer
  (AdamW on ~3M params is sub-ms — BASELINE.md profile).
"""

from __future__ import annotations

import math

# TPU v5e (v5 lite) peak dense bf16 matmul throughput per chip — imported
# from the ONE device peak table (obs/perf.py, stdlib-only) that also
# feeds the measured-MFU windows and the roofline verdicts; this module
# keeps the historical name for its callers (benchmark.py, bench.py).
from featurenet_tpu.obs.perf import PEAK_FLOPS_BY_KIND

PEAK_BF16_FLOPS = PEAK_FLOPS_BY_KIND["TPU v5e"]


def conv_stack_forward_flops(
    features, kernels, strides, pool_after, resolution: int, c_in: int = 1
) -> int:
    """Forward matmul FLOPs per sample for a ConvBNRelu stack."""
    total = 0
    d = resolution
    for f, k, s, p in zip(features, kernels, strides, pool_after):
        d = math.ceil(d / s)  # SAME
        total += 2 * k**3 * c_in * f * d**3
        if p:
            d //= 2
        c_in = f
    return total


def classifier_forward_flops(arch, resolution: int) -> int:
    """Forward FLOPs per sample for ``FeatureNet(arch)`` at ``resolution``."""
    total = conv_stack_forward_flops(
        arch.features, arch.kernels, arch.strides, arch.pool_after, resolution
    )
    d = resolution
    for s, p in zip(arch.strides, arch.pool_after):
        d = math.ceil(d / s)
        if p:
            d //= 2
    flat = arch.features[-1] if arch.head_gap else arch.features[-1] * d**3
    total += 2 * flat * arch.hidden
    total += 2 * arch.hidden * arch.num_classes
    return total


def train_step_flops_per_sample(arch, resolution: int) -> int:
    """fwd + input-grad + weight-grad ≈ 3× forward."""
    return 3 * classifier_forward_flops(arch, resolution)


def mfu(samples_per_sec_per_chip: float, flops_per_sample: float,
        peak: float = PEAK_BF16_FLOPS) -> float:
    """Model-flops utilization of one chip at the measured throughput."""
    return samples_per_sec_per_chip * flops_per_sample / peak
