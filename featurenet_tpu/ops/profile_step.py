"""Layer-wise timing breakdown of the pod64 train step.

Run on TPU:  python -m featurenet_tpu.ops.profile_step [--batch 128]

Answers "where do the milliseconds of the flagship step go" without XProf
(the tunneled backend exposes no trace viewer): slope-times, at the pod64
shapes, (a) prefix stacks of the conv tower forward, (b) the full forward,
(c) the full fwd+bwd, and (d) the complete train step (fwd+bwd+opt+BN+
unpack). Differences between consecutive prefixes attribute forward time to
individual blocks; (c)-(b) is the backward cost; (d)-(c) is optimizer +
wire-unpack + augmentation overhead. Results drive backend defaults the same
way `ops/bench_ops.py` does (BASELINE.md).

Timing method matches the repo-root ``bench.py`` (NOT ops/bench_ops.py,
which scan-chains): the measured fn is jitted to return ONE
scalar; wall(k) = time for k sequential dispatches + a readback of the last
scalar (block_until_ready returns early through the tunnel — a readback is
the honest sync); per-call time = (wall(N+1) - wall(1)) / N, which cancels
the constant dispatch/round-trip latency. One compile per measured shape —
no scan chaining (compiling scans of full conv stacks proved pathologically
slow on this toolchain).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _slope_time(fn, args, iters: int = 12, repeats: int = 3) -> float:
    """Per-call seconds of a jitted scalar-returning fn via slope timing."""
    float(fn(*args))  # compile + warm

    def wall(k: int) -> float:
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = None
            for _ in range(k):
                out = fn(*args)
            float(out)
            best = min(best, time.perf_counter() - t0)
        return best

    return (wall(1 + iters) - wall(1)) / iters


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch", type=int, default=128)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from featurenet_tpu.config import get_config
    from featurenet_tpu.data.synthetic import generate_batch, to_wire
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.models.featurenet import FeatureNetArch
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, make_train_step

    cfg = get_config("pod64")
    B, R = args.batch, cfg.resolution
    rng = np.random.default_rng(0)
    voxels = jnp.asarray(rng.random((B, R, R, R, 1)) < 0.5, jnp.float32)
    rows = []

    def record(name, sec, flops=None):
        row = {"metric": name, "value": round(sec * 1e3, 3), "unit": "ms"}
        if flops:
            row["tflops"] = round(flops / sec / 1e12, 1)
        rows.append(row)
        print(json.dumps(row))

    # --- (a) forward prefix stacks: attribute fwd time per conv block -------
    # Tower-only prefixes (no flatten/Dense head — on a truncated stack the
    # head would flatten a huge activation and dominate the measurement).
    from flax import linen as nn

    from featurenet_tpu.models.featurenet import ConvBNRelu

    a = cfg.arch

    class Tower(nn.Module):
        arch: FeatureNetArch
        blocks: int

        @nn.compact
        def __call__(self, x, train: bool = False):
            t = self.arch
            x = x.astype(jnp.bfloat16)
            for f, k_, s, p in list(
                zip(t.features, t.kernels, t.strides, t.pool_after)
            )[: self.blocks]:
                x = ConvBNRelu(f, k_, s, stem_s2d=t.stem_s2d,
                               conv_backend=t.conv_backend)(x, train)
                if p:  # pool at the call site, same as FeatureNet
                    x = nn.max_pool(
                        x, window_shape=(2, 2, 2), strides=(2, 2, 2)
                    )
            return x

    prev = 0.0
    spatial = R
    flops_prefix = 0.0
    for k in range(1, len(a.features) + 1):
        spatial //= a.strides[k - 1]  # output spatial of this block
        cin = 1 if k == 1 else a.features[k - 2]
        flops_prefix += (
            2 * B * spatial**3 * a.kernels[k - 1] ** 3 * cin * a.features[k - 1]
        )
        if a.pool_after[k - 1]:
            spatial //= 2

        model_k = Tower(arch=a, blocks=k)
        vs = model_k.init({"params": jax.random.key(0)}, voxels, train=False)

        @jax.jit
        def fwd_sum(vs, x, _m=model_k):
            return jnp.sum(_m.apply(vs, x, train=False)).astype(jnp.float32)

        t = _slope_time(fwd_sum, (vs, voxels))
        record(f"fwd_prefix_{k}blocks", t, flops_prefix)
        record(f"fwd_block_{k}_delta", t - prev)
        prev = t

    # --- (b,c) full forward vs fwd+bwd --------------------------------------
    model = FeatureNet(arch=a)
    variables = model.init({"params": jax.random.key(0)}, voxels, train=False)
    params = variables["params"]
    batch_stats = variables["batch_stats"]
    labels = jnp.asarray(rng.integers(0, a.num_classes, B), jnp.int32)
    drng = jax.random.key(1)

    def loss_fn(params, bs):
        import optax

        logits, new_vars = model.apply(
            {"params": params, "batch_stats": bs}, voxels, train=True,
            mutable=["batch_stats"], rngs={"dropout": drng},
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean(), new_vars

    t_fwd = _slope_time(
        jax.jit(lambda p, bs: loss_fn(p, bs)[0]), (params, batch_stats)
    )
    record("full_fwd_train", t_fwd)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @jax.jit
    def fwdbwd(p, bs):
        (loss, _), grads = grad_fn(p, bs)
        return loss + jax.tree_util.tree_reduce(
            lambda x, y: x + jnp.sum(y).astype(jnp.float32), grads, 0.0
        )

    t_fb = _slope_time(fwdbwd, (params, batch_stats))
    record("full_fwd_bwd", t_fb)
    record("bwd_delta", t_fb - t_fwd)

    # --- (d) complete train step (unpack+augment+opt included) --------------
    tx = make_optimizer(cfg)
    state = create_state(model, tx, voxels, jax.random.key(0))
    wire = to_wire(generate_batch(rng, B, R), "classify")
    batch = {k: jnp.asarray(v) for k, v in wire.items()}
    step = jax.jit(make_train_step(model, "classify", packed=True),
                   donate_argnums=(0,))
    key = jax.random.key(2)

    state, m = step(state, batch, key)  # compile
    float(m["loss"])
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            state, m = step(state, batch, key)
        float(m["loss"])
        best = min(best, (time.perf_counter() - t0) / 10)
    record("train_step_total_incl_dispatch", best)
    record("overhead_opt_unpack_aug_dispatch", best - t_fb)

    print(json.dumps({"summary": rows}))


if __name__ == "__main__":
    main()
