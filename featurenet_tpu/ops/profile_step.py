"""Layer-wise timing breakdown of a classifier train step, any preset.

Run on TPU:  python -m featurenet_tpu.ops.profile_step [--preset turbo64]
                                                       [--batch 256]

Answers "where do the milliseconds of the step go" without XProf (the
tunneled backend exposes no trace viewer). Four attribution methods, all
slope-timed at the preset's real shapes:

  (a) *prefix towers*, eval-mode forward: consecutive deltas attribute
      forward time per conv block (±2-3 ms tunnel noise; ranks blocks);
  (b) *prefix towers, fwd+bwd*: grad-of-sum through each prefix — deltas
      attribute the combined fwd+bwd cost per block, which is what actually
      dominates a train step;
  (c) *isolated blocks*: each ConvBNRelu rebuilt alone at its real input
      shape, timed fwd and fwd+bwd, with conv-only dx/dw drill-down — the
      per-block TF/s against the roofline below;
  (d) *head + full towers*: the flatten/GAP+Dense head isolated, then the
      full forward, full fwd+bwd, and the complete train step (unpack +
      device augmentation + optimizer + dispatch included).

The attribution check the round-2 verdict asked for: (b)'s deltas plus the
head should cover >=90% of the full fwd+bwd; the printed summary states the
attributed fraction explicitly.

Roofline: per block we print FLOPs, bf16 bytes moved (in + out activations
+ weights), arithmetic intensity, and whether the block sits compute- or
bandwidth-bound against TPU v5e's ridge (~197 bf16 TF/s peak / ~819 GB/s
HBM ~= 240 FLOP/byte). MXU shape ceilings (C_out < 128 starves the systolic
array's columns) are flagged per block since they, not bandwidth, bound the
narrow FeatureNet channels (BASELINE.md round-2 conv2 analysis).

Timing method matches the repo-root ``bench.py`` (NOT ops/bench_ops.py,
which scan-chains): the measured fn is jitted to return ONE scalar;
wall(k) = time for k sequential dispatches + a readback of the last scalar
(block_until_ready returns early through the tunnel — a readback is the
honest sync); per-call time = (wall(N+1) - wall(1)) / N, which cancels the
constant dispatch/round-trip latency. One compile per measured shape.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time

import numpy as np

# TPU v5e single-chip roofline constants, derived from the ONE device
# peak table (obs/perf.py) so a spec correction lands everywhere at once.
from featurenet_tpu.obs.perf import (
    PEAK_BYTES_PER_SEC_BY_KIND,
    PEAK_FLOPS_BY_KIND,
)

PEAK_BF16_TFLOPS = PEAK_FLOPS_BY_KIND["TPU v5e"] / 1e12
HBM_GBPS = PEAK_BYTES_PER_SEC_BY_KIND["TPU v5e"] / 1e9
RIDGE_FLOP_PER_BYTE = PEAK_BF16_TFLOPS * 1e12 / (HBM_GBPS * 1e9)  # ~240


def _slope_time(
    fn, args, iters: int = 12, repeats: int = 3
) -> tuple[float, float]:
    """Per-call seconds of a jitted scalar-returning fn via slope timing.

    Returns ``(best, spread_pct)``: the best of ``repeats`` independent
    slopes and their (max-min)/best spread. A contaminated reading (host
    contention, tunnel stall) shows up as a large spread instead of
    silently poisoning a published table — the round-3 turbo64 head line
    shipped a 10x contaminated value precisely because the old API
    returned one anonymous float (BASELINE.md round-3 profiler note)."""
    float(fn(*args))  # compile + warm

    def wall(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = fn(*args)
        float(out)
        return time.perf_counter() - t0

    slopes = [
        (wall(1 + iters) - wall(1)) / iters for _ in range(repeats)
    ]
    best = min(slopes)
    spread = 100.0 * (max(slopes) - best) / best if best > 0 else 0.0
    return best, spread


def _delta_spread(a: float, sp_a: float, b: float, sp_b: float) -> float:
    """Propagated spread of the difference ``a - b`` (percent).

    A delta of two independently noisy slopes carries the *absolute* noise
    of both over a (possibly much smaller) difference — a per-block delta
    can be 100%+ uncertain while each prefix shows single-digit spread, so
    tagging the delta with one input's spread would understate it (the
    round-3 contaminated head reading hid exactly this way)."""
    err = abs(a) * sp_a / 100.0 + abs(b) * sp_b / 100.0
    return 100.0 * err / max(abs(a - b), 1e-9)


@dataclasses.dataclass
class BlockShape:
    """Resolved geometry of one conv block at a given input resolution."""

    index: int  # 1-based
    cin: int
    cout: int
    kernel: int
    stride: int
    s_in: int   # input spatial edge
    s_out: int  # conv output spatial edge (pre-pool)
    pooled: bool

    @property
    def flops(self) -> int:
        """Forward MACs*2 of the conv itself."""
        return 2 * self.s_out**3 * self.kernel**3 * self.cin * self.cout

    def bytes_moved(self, batch: int) -> int:
        """bf16 activation in + out + weights, per batch (fwd only)."""
        return 2 * (
            batch * self.s_in**3 * self.cin
            + batch * self.s_out**3 * self.cout
            + self.kernel**3 * self.cin * self.cout
        )


def resolve_blocks(arch, resolution: int) -> list[BlockShape]:
    blocks = []
    s = resolution
    cin = 1
    for i, (f, k, st, p) in enumerate(
        zip(arch.features, arch.kernels, arch.strides, arch.pool_after), 1
    ):
        s_out = s // st
        blocks.append(BlockShape(i, cin, f, k, st, s, s_out, p))
        s = s_out // 2 if p else s_out
        cin = f
    return blocks


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="pod64")
    parser.add_argument(
        "--batch", type=int, default=None,
        help="default: the preset's global_batch",
    )
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from featurenet_tpu.config import get_config
    from featurenet_tpu.data.synthetic import generate_batch, to_wire
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.models.featurenet import ConvBNRelu, FeatureNetArch
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, make_train_step

    cfg = get_config(args.preset)
    # This profiler builds classifier towers; a segment config under its
    # name would silently profile the wrong model (advisor round-2 note on
    # the same pattern in benchmark.py).
    assert cfg.task == "classify", (
        f"profile_step profiles classifiers; preset {cfg.name!r} is "
        f"task={cfg.task!r}"
    )
    B = args.batch if args.batch is not None else cfg.global_batch
    R = cfg.resolution
    a = cfg.arch
    blocks = resolve_blocks(a, R)
    rng = np.random.default_rng(0)
    voxels = jnp.asarray(rng.random((B, R, R, R, 1)) < 0.5, jnp.float32)
    rows = []

    def record(name, sec, flops=None, extra=None, spread=None):
        row = {"metric": name, "value": round(sec * 1e3, 3), "unit": "ms"}
        if spread is not None:
            row["spread_pct"] = round(spread, 1)
        if flops:
            row["tflops"] = round(flops / sec / 1e12, 1)
        if extra:
            row.update(extra)
        rows.append(row)
        print(json.dumps(row))

    # Session noise header: lever decisions ride on these tables, so the
    # table must describe its own measurement conditions (bench.py policy).
    import os

    load1 = os.getloadavg()[0]
    header = {
        "preset": cfg.name, "batch": B, "resolution": R,
        "load_avg_1m": round(load1, 2),
        "arch": {
            "features": list(a.features), "kernels": list(a.kernels),
            "strides": list(a.strides), "pool_after": list(a.pool_after),
        },
    }
    if load1 > 0.8:
        header["load_warning"] = (
            f"1m loadavg {load1:.2f} on this host: timings may be "
            "contaminated by host contention; prefer an idle host or "
            "distrust rows with large spread_pct"
        )
    print(json.dumps(header))

    # --- roofline table (static analysis, no device) ------------------------
    for b in blocks:
        intensity = b.flops * B / b.bytes_moved(B)
        mxu_cols = min(b.cout, 128) / 128
        print(json.dumps({
            "roofline_block": b.index,
            "shape": f"{b.kernel}^3 {b.cin}->{b.cout} @{b.s_in}^3"
                     + (f"/s{b.stride}" if b.stride > 1 else ""),
            "gflops_batch": round(b.flops * B / 1e9, 1),
            "mbytes_batch": round(b.bytes_moved(B) / 1e6, 1),
            "intensity_flop_per_byte": round(intensity, 1),
            "bound": "compute" if intensity > RIDGE_FLOP_PER_BYTE
                     else "bandwidth",
            "mxu_col_fill": round(mxu_cols, 2),
            "shape_ceiling_tflops": round(PEAK_BF16_TFLOPS * mxu_cols, 0),
        }))

    # --- (a,b) prefix towers: per-block fwd and fwd+bwd deltas --------------
    # Tower-only prefixes (no flatten/Dense head — on a truncated stack the
    # head would flatten a huge activation and dominate the measurement).
    from flax import linen as nn

    class Tower(nn.Module):
        arch: FeatureNetArch
        blocks: int

        @nn.compact
        def __call__(self, x, train: bool = False):
            t = self.arch
            x = x.astype(jnp.bfloat16)
            for f, k_, s, p in list(
                zip(t.features, t.kernels, t.strides, t.pool_after)
            )[: self.blocks]:
                y = ConvBNRelu(f, k_, s, stem_s2d=t.stem_s2d,
                               conv_backend=t.conv_backend)(x, train)
                # Residual adds mirror FeatureNet exactly — a prefix of a
                # different (cheaper) model would corrupt the attribution.
                if t.residual and s == 1 and x.shape[-1] == f:
                    y = y + x
                x = (
                    nn.max_pool(y, window_shape=(2, 2, 2), strides=(2, 2, 2))
                    if p
                    else y
                )
            return x

    def grad_sum_fn(module, variables):
        """Jitted fwd+bwd scalar probe: grad of sum(output) w.r.t. params,
        reduced to one scalar so the readback-sync slope timing applies."""
        params = variables["params"]
        rest = {c: v for c, v in variables.items() if c != "params"}

        @jax.jit
        def fb(p, x):
            def f(p_):
                return jnp.sum(
                    module.apply({"params": p_, **rest}, x, train=False)
                ).astype(jnp.float32)

            val, g = jax.value_and_grad(f)(p)
            return val + jax.tree_util.tree_reduce(
                lambda acc, y: acc + jnp.sum(y).astype(jnp.float32), g, 0.0
            )

        return fb, params

    prev_f, prev_fb = 0.0, 0.0
    prev_sp_f = prev_sp_fb = 0.0
    flops_prefix = 0.0
    for k in range(1, len(a.features) + 1):
        flops_prefix += blocks[k - 1].flops * B
        model_k = Tower(arch=a, blocks=k)
        vs = model_k.init({"params": jax.random.key(0)}, voxels, train=False)

        @jax.jit
        def fwd_sum(vs, x, _m=model_k):
            return jnp.sum(_m.apply(vs, x, train=False)).astype(jnp.float32)

        t, sp = _slope_time(fwd_sum, (vs, voxels))
        record(f"fwd_prefix_{k}blocks", t, flops_prefix, spread=sp)
        record(f"fwd_block_{k}_delta", t - prev_f,
               spread=_delta_spread(t, sp, prev_f, prev_sp_f))
        prev_f, prev_sp_f = t, sp

        # fwd+bwd through the same prefix: grad of sum w.r.t. params. Eval-
        # mode BN (running stats) so no mutable collection threads through
        # grad; the conv/BN-scale backward cost — the expensive part — is
        # identical in train mode.
        fb, params_k = grad_sum_fn(model_k, vs)
        t2, sp2 = _slope_time(fb, (params_k, voxels))
        record(f"fwdbwd_prefix_{k}blocks", t2, 3 * flops_prefix, spread=sp2)
        record(f"fwdbwd_block_{k}_delta", t2 - prev_fb,
               spread=_delta_spread(t2, sp2, prev_fb, prev_sp_fb))
        prev_fb, prev_sp_fb = t2, sp2
    tower_fb_total = prev_fb

    # --- (c) isolated blocks at real shapes, with conv dx/dw drill-down -----
    for b in blocks:
        x_in = jnp.asarray(
            rng.random((B, b.s_in, b.s_in, b.s_in, b.cin)) < 0.5, jnp.bfloat16
        )
        blk = ConvBNRelu(b.cout, b.kernel, b.stride,
                         stem_s2d=a.stem_s2d, conv_backend=a.conv_backend)
        vs = blk.init({"params": jax.random.key(0)}, x_in, train=False)
        params_b = vs["params"]
        rest_b = {c: v for c, v in vs.items() if c != "params"}

        @jax.jit
        def blk_fwd(p, x, _b=blk, _rest=rest_b):
            return jnp.sum(
                _b.apply({"params": p, **_rest}, x, train=False)
            ).astype(jnp.float32)

        t_f, sp_f = _slope_time(blk_fwd, (params_b, x_in))
        record(f"iso_block_{b.index}_fwd", t_f, b.flops * B, spread=sp_f)

        fb_b, _ = grad_sum_fn(blk, vs)
        t_fb, sp_fb = _slope_time(fb_b, (params_b, x_in))
        record(f"iso_block_{b.index}_fwdbwd", t_fb, 3 * b.flops * B,
               spread=sp_fb)

        # Conv-only dx / dw (the MXU contractions, no BN/relu): where the
        # round-2 analysis found the 25%-of-peak dW shape ceiling.
        conv = nn.Conv(
            b.cout, kernel_size=(b.kernel,) * 3, strides=(b.stride,) * 3,
            padding="SAME", use_bias=False, dtype=jnp.bfloat16,
            param_dtype=jnp.float32,
        )
        cvars = conv.init(jax.random.key(0), x_in)

        @jax.jit
        def conv_dx(p, x, _c=conv):
            g = jax.grad(
                lambda x_: jnp.sum(_c.apply(p, x_)).astype(jnp.float32)
            )(x)
            return jnp.sum(g).astype(jnp.float32)

        @jax.jit
        def conv_dw(p, x, _c=conv):
            g = jax.grad(
                lambda p_: jnp.sum(_c.apply(p_, x)).astype(jnp.float32)
            )(p)
            return jax.tree_util.tree_reduce(
                lambda acc, y: acc + jnp.sum(y).astype(jnp.float32), g, 0.0
            )

        t_dx, sp_dx = _slope_time(conv_dx, (cvars, x_in))
        record(f"iso_block_{b.index}_conv_dx", t_dx, b.flops * B,
               spread=sp_dx)
        t_dw, sp_dw = _slope_time(conv_dw, (cvars, x_in))
        record(f"iso_block_{b.index}_conv_dw", t_dw, b.flops * B,
               spread=sp_dw)

    # --- (d) head isolated, then full model ---------------------------------
    last = blocks[-1]
    s_head = last.s_out // 2 if last.pooled else last.s_out
    head_in = jnp.asarray(
        rng.random((B, s_head, s_head, s_head, last.cout)) < 0.5, jnp.bfloat16
    )

    class Head(nn.Module):
        arch: FeatureNetArch

        @nn.compact
        def __call__(self, x, train: bool = False):
            t = self.arch
            if t.head_gap:
                x = jnp.mean(x, axis=(1, 2, 3), dtype=jnp.float32).astype(
                    jnp.bfloat16
                )
            else:
                x = x.reshape((x.shape[0], -1))
            x = nn.Dense(t.hidden, dtype=jnp.bfloat16,
                         param_dtype=jnp.float32)(x)
            x = nn.relu(x)
            x = nn.Dense(t.num_classes, dtype=jnp.bfloat16,
                         param_dtype=jnp.float32)(x)
            return x.astype(jnp.float32)

    head = Head(arch=a)
    hvars = head.init(jax.random.key(0), head_in)
    # Dense-1's contraction is over the GAP vector (cout) for GAP heads and
    # the full flattened activation for paper-shape heads.
    d1_in = last.cout if a.head_gap else s_head**3 * last.cout
    head_flops = 2 * B * (d1_in * a.hidden + a.hidden * a.num_classes)
    head_fb, hparams = grad_sum_fn(head, hvars)
    t_head, sp_head = _slope_time(head_fb, (hparams, head_in))
    record("head_fwdbwd", t_head, 3 * head_flops, spread=sp_head)

    # --- full forward vs fwd+bwd --------------------------------------------
    model = FeatureNet(arch=a)
    variables = model.init({"params": jax.random.key(0)}, voxels, train=False)
    params = variables["params"]
    batch_stats = variables["batch_stats"]
    labels = jnp.asarray(rng.integers(0, a.num_classes, B), jnp.int32)
    drng = jax.random.key(1)

    # Inputs travel as jit arguments, never closures: a closed-over batch
    # becomes a compile-time constant shipped inside the compile request,
    # and at batch 256 x 64^3 that 268 MB body overflows the tunnel's
    # remote-compile length limit (HTTP 413, observed).
    def loss_fn(params, bs, vox, lab):
        import optax

        logits, new_vars = model.apply(
            {"params": params, "batch_stats": bs}, vox, train=True,
            mutable=["batch_stats"], rngs={"dropout": drng},
        )
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, lab
        ).mean(), new_vars

    t_fwd, sp_fwd = _slope_time(
        jax.jit(lambda p, bs, v, l: loss_fn(p, bs, v, l)[0]),
        (params, batch_stats, voxels, labels),
    )
    record("full_fwd_train", t_fwd, spread=sp_fwd)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @jax.jit
    def fwdbwd(p, bs, v, l):
        (loss, _), grads = grad_fn(p, bs, v, l)
        return loss + jax.tree_util.tree_reduce(
            lambda x, y: x + jnp.sum(y).astype(jnp.float32), grads, 0.0
        )

    t_fb, sp_fb = _slope_time(fwdbwd, (params, batch_stats, voxels, labels))
    record("full_fwd_bwd", t_fb, spread=sp_fb)
    record("bwd_delta", t_fb - t_fwd,
           spread=_delta_spread(t_fb, sp_fb, t_fwd, sp_fwd))

    # --- complete train step (unpack+augment+opt included) ------------------
    tx = make_optimizer(cfg)
    state = create_state(model, tx, voxels, jax.random.key(0))
    wire = to_wire(generate_batch(rng, B, R), "classify")
    batch = {k: jnp.asarray(v) for k, v in wire.items()}
    step = jax.jit(make_train_step(model, "classify", packed=True),
                   donate_argnums=(0,))
    key = jax.random.key(2)

    state, m = step(state, batch, key)  # compile
    float(m["loss"])
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            state, m = step(state, batch, key)
        float(m["loss"])
        walls.append((time.perf_counter() - t0) / 10)
    best = min(walls)
    sp_step = 100.0 * (max(walls) - best) / best if best > 0 else 0.0
    record("train_step_total_incl_dispatch", best, spread=sp_step)
    record("overhead_opt_unpack_aug_dispatch", best - t_fb,
           spread=_delta_spread(best, sp_step, t_fb, sp_fb))

    # --- attribution check: how much of fwd+bwd do the parts explain? -------
    attributed = tower_fb_total + t_head
    print(json.dumps({
        "attribution": {
            "tower_fwdbwd_ms": round(tower_fb_total * 1e3, 2),
            "head_fwdbwd_ms": round(t_head * 1e3, 2),
            "sum_parts_ms": round(attributed * 1e3, 2),
            "full_fwdbwd_ms": round(t_fb * 1e3, 2),
            "attributed_pct": round(100 * attributed / t_fb, 1),
            "note": "parts are measured in eval mode (running-stats BN, "
                    "dropout inactive) while the full_fwd_bwd denominator "
                    "runs train mode — its batch-stat computation and "
                    "dropout cost are structurally unattributable here, on "
                    "top of loss/softmax and cross-prefix XLA fusion "
                    "differences; >=90% closes the verdict ask",
            "load_avg_1m_end": round(os.getloadavg()[0], 2),
        }
    }))
    print(json.dumps({"summary": rows}))


if __name__ == "__main__":
    main()
