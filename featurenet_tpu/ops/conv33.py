"""Layout-specialized 3³ stride-1 convolution: tap-unrolled channel
matmuls on the channels-last grid.

Why this exists (the roofline's verdict, not a hunch): PR 9's per-program
cost attribution classifies the serving forwards memory-bound on v5e —
arithmetic intensity under the ridge point, achieved bandwidth the
binding resource — and the 3³ stride-1 blocks are where the bytes go
once the strided stem is out of the way (the ``ops/stem.py`` s2d
reformulation that bought 8.3k→16.7k sps is the precedent for attacking
exactly the block the profile names). XLA's generic conv lowering
materializes its own im2col-ish intermediates for these shapes; this
module lowers the same conv as **27 tap-shifted channel contractions**
instead:

    out = Σ_{kz,ky,kx}  shift(x, kz-1, ky-1, kx-1) @ w[kz, ky, kx]

Each term is a ``[B·D·H·W, Cin] × [Cin, Cout]`` matmul — the MXU's
native shape, consumed directly from the NDHWC (channels-last) layout
with **zero data movement beyond one SAME-pad**: every "shift" is a
static slice view of the padded grid, no patch tensor is ever built, and
XLA fuses the 27 multiply-adds into one accumulation loop over a single
fp32 scratch. Accumulation is explicitly fp32 (``preferred_element_type``)
regardless of the activation dtype, so bf16/fp16 serving precisions keep
fp32-quality sums exactly like the XLA path.

Autodiff is native: the expression is pure ``jnp``/``lax.dot_general``,
so dx lowers to the transposed tap sum and dw to 27 position
contractions — no custom VJP to maintain (contrast ``ops/conv3d.py``).

Selected per-arch via ``FeatureNetArch.conv_backend="fused33"`` (CLI
``--conv-backend fused33``): ConvBNRelu routes its stride-1 kernel-3
blocks here and every other shape falls back to ``nn.Conv`` unchanged.
The backend rides the runtime fingerprint through the arch identity
(``runtime.registry``), so an executable cache can never hand a fused33
run the generic lowering. ``ops/bench_arch.py`` carries the comparison
rows (``fused33`` / ``k3_fused33``) and bench.py measures the flagship
under it (``train_sps_fused33``) — TPU round r06 pins whether the
specialization pays; the numerics are pinned on CPU either way
(tests/test_ops.py, forward AND gradients against ``lax.conv``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn


def fused33_conv(x: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """3³ stride-1 SAME conv as 27 tap-unrolled channel matmuls.

    ``x``: ``[B, D, H, W, Cin]`` (NDHWC); ``w``: ``[3, 3, 3, Cin, Cout]``
    (the reference parametrization — same leaf shape as ``nn.Conv``).
    Matches ``lax.conv_general_dilated(..., (1,1,1), "SAME")`` to
    accumulation-order rounding; accumulates fp32, returns at ``x``'s
    dtype.
    """
    if w.shape[:3] != (3, 3, 3):
        raise ValueError(f"fused33_conv is specialized to 3^3 kernels; "
                         f"got {w.shape}")
    b, d, h, w_, cin = x.shape
    cout = w.shape[-1]
    w = w.astype(x.dtype)
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (1, 1), (0, 0)))
    acc = None
    for kz in range(3):
        for ky in range(3):
            for kx in range(3):
                # Static slice view of the padded grid — the "shift" is
                # free; the contraction below is the only data touch.
                xs = xp[:, kz:kz + d, ky:ky + h, kx:kx + w_, :]
                term = jax.lax.dot_general(
                    xs, w[kz, ky, kx],
                    (((4,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                acc = term if acc is None else acc + term
    return acc.astype(x.dtype)


class Fused33Conv(nn.Module):
    """Stride-1 SAME 3³ conv block backed by ``fused33_conv`` (no bias).

    Parameter ``kernel`` has the same ``[3,3,3,Cin,Cout]`` shape and init
    as ``nn.Conv``'s, and ConvBNRelu instantiates it under nn.Conv's
    param scope name (``name="Conv_0"``) so the param TREE matches the
    xla backend's exactly — a checkpoint trained under either backend
    restores under the other (``config._identity_view`` neutralizes
    ``conv_backend`` for exactly this A/B-one-trained-run use; contrast
    HybridConv/PallasConv, whose auto-named scopes make their trees
    backend-specific). Activations stay in ``dtype``; accumulation is
    fp32 inside the tap loop.
    """

    features: int
    kernel_size: int = 3
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if self.kernel_size != 3:
            raise ValueError(
                f"Fused33Conv is the 3^3 specialization; got kernel "
                f"{self.kernel_size} (ConvBNRelu routes other shapes to "
                "nn.Conv)"
            )
        cin = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(batch_axis=(), in_axis=(0, 1, 2, 3)),
            (3, 3, 3, cin, self.features),
            jnp.float32,
        )
        return fused33_conv(x.astype(self.dtype), kernel)
