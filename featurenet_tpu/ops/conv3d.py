"""Pallas TPU 3D convolution: shift-and-matmul, with a custom VJP.

An alternative backend to XLA's conv lowering for the stride-1 SAME conv
blocks (the FLOPs bulk of FeatureNet — SURVEY.md §3.3). The reference gets
these from cuDNN (SURVEY.md §2 C6, third-party native); XLA's own lowering is
the primary TPU path here, and this kernel is the first-party native
alternative, selectable per-arch (``FeatureNetArch.conv_backend``) and kept
honest by ``featurenet_tpu.ops.bench_ops`` — measured numbers in BASELINE.md
decide the default (XLA today: its conv lowering runs at 60–140 TF/s on the
hot shapes, and this kernel is not yet ahead of it).

Kernel design (per TPU constraints, see /opt/skills/guides/pallas_guide.md):

- Grid over the batch; each program owns one padded sample in VMEM, with
  Pallas' pipeline double-buffering HBM→VMEM behind compute.
- The K³ taps become K³ MXU matmuls ``[TZ·H·W, Cin] @ [Cin, Cout]``
  accumulated in an fp32 VMEM scratch (bf16-style mixed precision is the
  MXU's native mode; here inputs are fp32 — see the dtype note).
- Tap shifts: z rides the fori z-chunk loop (dynamic slice on a free dim),
  y is a static free-dim slice, and x — the sublane dimension, where Mosaic
  requires 8-aligned slice starts — is done with ``pltpu.roll`` (a sublane
  rotate), hoisted to K rolls per z-chunk.
- dw: same structure, contracting over positions instead of channels, with
  the [K,K,K,Cin,Cout] output block accumulated across the whole grid.
- dx: stride-1 SAME with odd K is its own transpose — the forward kernel
  applied to the cotangent with spatially-flipped, channel-transposed
  weights.

Dtype note: Mosaic's sublane rotate is 32-bit only ("Rotate with non-32-bit
data"), so the compiled path requires fp32. bf16 callers fall back to XLA
(``pallas_conv_supported`` gates this); off-TPU the kernel runs in interpret
mode for CI (tests/conftest.py's virtual-CPU platform).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom under ~16 MiB/core


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_tz(d: int, h: int, w: int, k: int, cin: int, cout: int, itemsize: int):
    """Largest z-chunk whose fp32 accumulator keeps the program in VMEM."""
    dp, hp, wp = d + k - 1, h + k - 1, w + k - 1
    fixed = (
        2 * dp * hp * wp * cin * itemsize  # x block, double-buffered
        + 2 * d * h * w * cout * itemsize  # out block, double-buffered
        + k ** 3 * cin * cout * itemsize   # weights
    )
    for tz in range(min(d, 8), 0, -1):
        if d % tz:
            continue
        if fixed + tz * h * w * cout * 4 <= _VMEM_BUDGET:
            return tz
    return None


def _dw_fits(d, h, w, k, cin, cout, itemsize) -> bool:
    dp, hp, wp = d + k - 1, h + k - 1, w + k - 1
    fixed = (
        2 * dp * hp * wp * cin * itemsize  # x block, double-buffered
        + 2 * d * h * w * cout * itemsize  # g block, double-buffered
        + k ** 3 * cin * cout * 4          # dw accumulator (fp32 out)
    )
    return fixed <= _VMEM_BUDGET


def pallas_conv_supported(shape, k: int, cout: int, dtype) -> bool:
    """True when the compiled kernel handles this conv *including its VJP*.

    Training runs three kernels: forward, dx (forward with cin/cout swapped
    — the cotangent has ``cout`` channels), and dw; all three VMEM plans
    must fit, or gradient tracing would crash after the forward gate passed.
    """
    if len(shape) != 5 or k % 2 == 0:
        return False
    _, d, h, w, cin = shape
    if dtype != jnp.float32 and not _interpret():
        return False  # sublane rotate is 32-bit only on real TPU
    itemsize = jnp.dtype(dtype).itemsize
    return (
        _pick_tz(d, h, w, k, cin, cout, itemsize) is not None
        and _pick_tz(d, h, w, k, cout, cin, itemsize) is not None  # dx
        and _dw_fits(d, h, w, k, cin, cout, itemsize)
    )


def _fwd_kernel(k, tz, d, h, w, cin, cout, out_dtype):
    n = tz * h * w
    wp = w + k - 1

    def kernel(x_ref, w_ref, out_ref, acc_ref):
        def chunk(zc, carry):
            xs_full = x_ref[0, pl.ds(zc * tz, tz + k - 1)]
            acc_ref[:] = jnp.zeros_like(acc_ref)
            for kx in range(k):
                xx = (
                    pltpu.roll(xs_full, wp - kx, axis=2) if kx else xs_full
                )[:, :, 0:w, :]
                for kz in range(k):
                    for ky in range(k):
                        xs = xx[kz : kz + tz, ky : ky + h].reshape(n, cin)
                        acc_ref[:] = acc_ref[:] + jnp.dot(
                            xs,
                            w_ref[kz, ky, kx],
                            preferred_element_type=jnp.float32,
                        )
            out_ref[0, pl.ds(zc * tz, tz)] = (
                acc_ref[:].reshape(tz, h, w, cout).astype(out_dtype)
            )
            return carry

        jax.lax.fori_loop(0, d // tz, chunk, 0)

    return kernel


def _dw_kernel(k, tz, d, h, w, cin, cout):
    n = tz * h * w
    wp = w + k - 1

    def kernel(x_ref, g_ref, dw_ref):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            dw_ref[...] = jnp.zeros_like(dw_ref)

        def chunk(zc, carry):
            xs_full = x_ref[0, pl.ds(zc * tz, tz + k - 1)]
            gs = g_ref[0, pl.ds(zc * tz, tz)].reshape(n, cout)
            for kx in range(k):
                xx = (
                    pltpu.roll(xs_full, wp - kx, axis=2) if kx else xs_full
                )[:, :, 0:w, :]
                for kz in range(k):
                    for ky in range(k):
                        xs = xx[kz : kz + tz, ky : ky + h].reshape(n, cin)
                        dw_ref[kz, ky, kx] = dw_ref[kz, ky, kx] + jax.lax.dot_general(
                            xs,
                            gs,
                            (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32,
                        )
            return carry

        jax.lax.fori_loop(0, d // tz, chunk, 0)

    return kernel


def _conv_fwd(x, w):
    b, d, h, w_, cin = x.shape
    k, cout = w.shape[0], w.shape[-1]
    p = (k - 1) // 2
    tz = _pick_tz(d, h, w_, k, cin, cout, x.dtype.itemsize)
    if tz is None:
        raise ValueError(f"conv3d_p: shapes {x.shape} exceed the VMEM plan")
    xp = jnp.pad(x, ((0, 0), (p, p), (p, p), (p, p), (0, 0)))
    return pl.pallas_call(
        _fwd_kernel(k, tz, d, h, w_, cin, cout, x.dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, d + k - 1, h + k - 1, w_ + k - 1, cin),
                lambda i: (i, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (k, k, k, cin, cout),
                lambda i: (0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, d, h, w_, cout), lambda i: (i, 0, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, d, h, w_, cout), x.dtype),
        scratch_shapes=[pltpu.VMEM((tz * h * w_, cout), jnp.float32)],
        interpret=_interpret(),
    )(xp, w.astype(x.dtype))


def _conv_dw(x, g, k):
    b, d, h, w_, cin = x.shape
    cout = g.shape[-1]
    p = (k - 1) // 2
    tz = _pick_tz(d, h, w_, k, cin, cout, x.dtype.itemsize)
    if tz is None or not _dw_fits(d, h, w_, k, cin, cout, x.dtype.itemsize):
        raise ValueError(f"conv3d_p dw: shapes {x.shape} exceed the VMEM plan")
    xp = jnp.pad(x, ((0, 0), (p, p), (p, p), (p, p), (0, 0)))
    return pl.pallas_call(
        _dw_kernel(k, tz, d, h, w_, cin, cout),
        grid=(b,),
        in_specs=[
            pl.BlockSpec(
                (1, d + k - 1, h + k - 1, w_ + k - 1, cin),
                lambda i: (i, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, d, h, w_, cout), lambda i: (i, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (k, k, k, cin, cout),
            lambda i: (0, 0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((k, k, k, cin, cout), jnp.float32),
        interpret=_interpret(),
    )(xp, g)


@jax.custom_vjp
def conv3d_p(x, w):
    """Stride-1 SAME 3D conv, odd K: ``[B,D,H,W,Cin] x [K,K,K,Cin,Cout]``."""
    return _conv_fwd(x, w)


def _vjp_fwd(x, w):
    return _conv_fwd(x, w), (x, w)


def _vjp_bwd(res, g):
    x, w = res
    k = w.shape[0]
    # dx: correlate the cotangent with the spatially-flipped,
    # channel-transposed kernel (stride-1 SAME odd-K is self-transposed).
    w_flip = jnp.flip(w, axis=(0, 1, 2)).swapaxes(3, 4)
    dx = _conv_fwd(g, w_flip.astype(g.dtype))
    dw = _conv_dw(x, g, k).astype(w.dtype)
    return dx, dw


conv3d_p.defvjp(_vjp_fwd, _vjp_bwd)


def _xla_conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (1, 1, 1), "SAME",
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )


@jax.custom_vjp
def conv3d_hybrid(x, w):
    """Stride-1 SAME conv: XLA forward/input-grad, Pallas weight-grad.

    XLA's forward and input-grad lowerings already run near the shape
    ceiling (BASELINE.md microbench); the weight grad is the piece XLA
    leaves 4x on the table for narrow Cout (25 % MXU columns at Cout=32),
    and ``ops.conv_dw.conv_dw_folded`` reshapes exactly that contraction
    onto full MXU tiles. Everything else matches ``lax.conv`` bitwise.
    """
    return _xla_conv(x, w)


def _hybrid_fwd(x, w):
    return _xla_conv(x, w), (x, w)


def _hybrid_bwd(res, g):
    from featurenet_tpu.ops.conv_dw import conv_dw_folded

    x, w = res
    k = w.shape[0]
    # dx: transpose conv = conv of the cotangent with the spatially-flipped,
    # channel-transposed kernel (stride-1 SAME odd-K) — XLA's own lowering.
    w_flip = jnp.flip(w, axis=(0, 1, 2)).swapaxes(3, 4)
    dx = _xla_conv(g, w_flip)
    dw = conv_dw_folded(x, g, k).astype(w.dtype)
    return dx, dw


conv3d_hybrid.defvjp(_hybrid_fwd, _hybrid_bwd)


class HybridConv(nn.Module):
    """Stride-1 SAME conv block backed by ``conv3d_hybrid`` (no bias).

    Same parameter shape/init as ``nn.Conv``; activations stay in ``dtype``
    (bf16 on TPU — the folded dW kernel accumulates fp32 like XLA does).
    Shapes the dW VMEM plan can't hold fall back to the plain XLA conv.
    """

    features: int
    kernel_size: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from featurenet_tpu.ops.conv_dw import dw_folded_supported

        k, cin = self.kernel_size, x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(batch_axis=(), in_axis=(0, 1, 2, 3)),
            (k, k, k, cin, self.features),
            jnp.float32,
        )
        xc = x.astype(self.dtype)
        if dw_folded_supported(xc.shape, k, self.features, xc.dtype):
            return conv3d_hybrid(xc, kernel)
        return _xla_conv(xc, kernel)


class PallasConv(nn.Module):
    """Stride-1 SAME conv block backed by ``conv3d_p`` (no bias).

    Parameter ``kernel`` matches ``nn.Conv``'s shape/init. The compiled
    kernel is fp32 (see module docstring), so activations are computed in
    fp32 through this layer and cast back to ``dtype``; shapes the VMEM plan
    can't hold fall back to XLA's conv with the same parameters.
    """

    features: int
    kernel_size: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        k, cin = self.kernel_size, x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(batch_axis=(), in_axis=(0, 1, 2, 3)),
            (k, k, k, cin, self.features),
            jnp.float32,
        )
        xf = x.astype(jnp.float32)
        if pallas_conv_supported(xf.shape, k, self.features, xf.dtype):
            out = conv3d_p(xf, kernel)
        else:
            out = jax.lax.conv_general_dilated(
                xf,
                kernel,
                (1, 1, 1),
                "SAME",
                dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
            )
        return out.astype(self.dtype)
