"""Shared train-step throughput measurement (the bench core).

Lives inside the package so both the driver's root-level ``bench.py`` and
``featurenet_tpu.ops.bench_arch`` (the architecture sweep) import it without
depending on the repo root being on sys.path.

Method — slope timing: jit the full train step (fwd+bwd+optimizer+BN), warm
up, then wall (1 step + loss transfer) and (N+1 steps + loss transfer);
per-step time = (t_long - t_short)/N. The final scalar transfer is the sync
point — on this environment's tunneled TPU backend, ``block_until_ready``
returns before device execution completes, so only a device→host readback is
an honest wall; the slope subtracts the constant round-trip latency.
"""

from __future__ import annotations

import time

import numpy as np

V100_SAMPLES_PER_SEC_EST = 330.0  # documented estimate, see BASELINE.md
# Per-chip batch: XLA pads the batch dim to multiples of 128 (measured —
# batch 96 and 128 take the same 53 ms step), so bench at the multiple;
# this is also the pod64 preset's training batch.
BATCH = 128
WARMUP, MEASURE = 5, 20

def measure_train_step(
    cfg, batch_per_chip: int = BATCH, warmup: int = WARMUP,
    measure: int = MEASURE,
) -> dict:
    """Slope-time the compiled train step for ``cfg`` on all devices.

    Returns per-chip throughput plus the analytic-MFU fields. Weak scaling:
    the per-chip batch stays fixed regardless of chip count.
    """
    import jax

    if cfg.task != "classify":
        # This path builds a FeatureNet classifier on the classify wire
        # format unconditionally; benchmarking a segment config here would
        # silently measure the wrong model under that config's name.
        raise ValueError(
            f"measure_train_step benchmarks classify configs only; "
            f"{cfg.name!r} has task={cfg.task!r}"
        )

    from featurenet_tpu.data.synthetic import (
        WIRE_KEYS,
        generate_batch,
        to_wire,
    )
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.ops.flops import (
        PEAK_BF16_FLOPS,
        mfu,
        train_step_flops_per_sample,
    )
    from featurenet_tpu.parallel.mesh import (
        batch_shardings,
        make_mesh,
        replicated,
        state_shardings,
    )
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, make_train_step

    n_chips = len(jax.devices())
    mesh = make_mesh()  # all devices on 'data'
    global_batch = batch_per_chip * mesh.shape["data"]
    R = cfg.resolution

    model = FeatureNet(arch=cfg.arch)
    tx = make_optimizer(cfg)

    def init_fn(rng):
        import jax.numpy as jnp

        sample = jnp.zeros((global_batch, R, R, R, 1), jnp.float32)
        return create_state(model, tx, sample, rng)

    abstract = jax.eval_shape(init_fn, jax.random.key(0))
    st_sh = state_shardings(abstract, mesh)
    state = jax.jit(init_fn, out_shardings=st_sh)(jax.random.key(0))

    # The real classify wire format: bit-packed voxels, no per-voxel target,
    # unpacked on device inside the compiled step.
    b_sh = batch_shardings(mesh, keys=WIRE_KEYS["classify"])
    step = jax.jit(
        make_train_step(model, "classify", packed=True),
        in_shardings=(st_sh, b_sh, replicated(mesh)),
        out_shardings=(st_sh, replicated(mesh)),
        donate_argnums=(0,),
    )

    host = to_wire(
        generate_batch(np.random.default_rng(0), global_batch, R), "classify"
    )
    batch = jax.device_put(host, b_sh)
    rng = jax.device_put(jax.random.key(1), replicated(mesh))

    for _ in range(warmup):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # drain the pipe

    def walled(k: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])  # device→host readback = honest sync
        return time.perf_counter() - t0

    t_short = walled(1)
    t_long = walled(1 + measure)
    per_step = (t_long - t_short) / measure
    sps_chip = global_batch / per_step / n_chips
    fps = train_step_flops_per_sample(cfg.arch, R)
    return {
        "batch_per_chip": batch_per_chip,
        "per_step_ms": round(per_step * 1e3, 2),
        "samples_per_sec_per_chip": round(sps_chip, 2),
        "gflops_per_sample": round(fps / 1e9, 2),
        "tflops_per_sec_per_chip": round(sps_chip * fps / 1e12, 1),
        "mfu": round(mfu(sps_chip, fps), 3),
        "mfu_peak_tflops": PEAK_BF16_FLOPS / 1e12,
    }
