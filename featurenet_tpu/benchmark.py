"""Shared train-step throughput measurement (the bench core).

Lives inside the package so both the driver's root-level ``bench.py`` and
``featurenet_tpu.ops.bench_arch`` (the architecture sweep) import it without
depending on the repo root being on sys.path.

Method — slope timing: jit the full train step (fwd+bwd+optimizer+BN), warm
up, then wall (1 step + loss transfer) and (N+1 steps + loss transfer);
per-step time = (t_long - t_short)/N. The final scalar transfer is the sync
point — on this environment's tunneled TPU backend, ``block_until_ready``
returns before device execution completes, so only a device→host readback is
an honest wall; the slope subtracts the constant round-trip latency.
"""

from __future__ import annotations

import time

import numpy as np

V100_SAMPLES_PER_SEC_EST = 330.0  # documented estimate, see BASELINE.md
# Per-chip batch: XLA pads the batch dim to multiples of 128 (measured —
# batch 96 and 128 take the same 53 ms step), so bench at the multiple;
# this is also the pod64 preset's training batch.
BATCH = 128
WARMUP, MEASURE = 5, 20


def _best_slope(walled, measure: int, repeats: int) -> tuple[float, float]:
    """Take ``repeats`` independent slope measurements with ``walled`` (a
    k-calls-plus-readback wall timer) and return (best per-call seconds,
    spread percent). Best-of-N with in-artifact spread is the noise policy
    for every throughput number this module reports — one slope through
    this environment's tunneled backend has shown ±13% under host load."""
    slopes = []
    for _ in range(max(1, repeats)):
        t_short = walled(1)
        t_long = walled(1 + measure)
        slopes.append((t_long - t_short) / measure)
    best = min(slopes)
    return best, (max(slopes) - best) / best * 100.0

def measure_train_step(
    cfg, batch_per_chip: int = BATCH, warmup: int = WARMUP,
    measure: int = MEASURE, repeats: int = 1,
) -> dict:
    """Slope-time the compiled train step for ``cfg`` on all devices.

    Returns per-chip throughput plus the analytic-MFU fields. Weak scaling:
    the per-chip batch stays fixed regardless of chip count.

    ``repeats``: how many independent slope measurements to take. The
    headline is the *best* slope — one slope sample through this
    environment's tunneled backend has shown ±13% spread under host load
    (round-2: 9520 clean vs 8252 loaded) — and ``spread_pct`` reports
    (max-min)/min across repeats so the artifact carries its own noise
    estimate instead of leaving the best-observed number unquotable.
    """
    import jax

    if cfg.task != "classify":
        # This path builds a FeatureNet classifier on the classify wire
        # format unconditionally; benchmarking a segment config here would
        # silently measure the wrong model under that config's name.
        raise ValueError(
            f"measure_train_step benchmarks classify configs only; "
            f"{cfg.name!r} has task={cfg.task!r}"
        )

    from featurenet_tpu.data.synthetic import (
        WIRE_KEYS,
        generate_batch,
        to_wire,
    )
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.ops.flops import (
        PEAK_BF16_FLOPS,
        mfu,
        train_step_flops_per_sample,
    )
    from featurenet_tpu.parallel.mesh import (
        batch_shardings,
        make_mesh,
        replicated,
        state_shardings,
    )
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, make_train_step

    n_chips = len(jax.devices())
    mesh = make_mesh()  # all devices on 'data'
    global_batch = batch_per_chip * mesh.shape["data"]
    R = cfg.resolution

    model = FeatureNet(arch=cfg.arch)
    tx = make_optimizer(cfg)

    def init_fn(rng):
        import jax.numpy as jnp

        sample = jnp.zeros((global_batch, R, R, R, 1), jnp.float32)
        return create_state(model, tx, sample, rng)

    abstract = jax.eval_shape(init_fn, jax.random.key(0))
    st_sh = state_shardings(abstract, mesh)
    state = jax.jit(init_fn, out_shardings=st_sh)(jax.random.key(0))

    # The real classify wire format: bit-packed voxels, no per-voxel target,
    # unpacked on device inside the compiled step.
    b_sh = batch_shardings(mesh, keys=WIRE_KEYS["classify"])
    step = jax.jit(
        make_train_step(model, "classify", packed=True),
        in_shardings=(st_sh, b_sh, replicated(mesh)),
        out_shardings=(st_sh, replicated(mesh)),
        donate_argnums=(0,),
    )

    host = to_wire(
        generate_batch(np.random.default_rng(0), global_batch, R), "classify"
    )
    batch = jax.device_put(host, b_sh)
    rng = jax.device_put(jax.random.key(1), replicated(mesh))

    for _ in range(max(1, warmup)):  # >=1: the readback below drains it
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # drain the pipe

    def walled(k: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])  # device→host readback = honest sync
        return time.perf_counter() - t0

    per_step, spread_pct = _best_slope(walled, measure, repeats)
    sps_chip = global_batch / per_step / n_chips
    fps = train_step_flops_per_sample(cfg.arch, R)
    return {
        "batch_per_chip": batch_per_chip,
        "per_step_ms": round(per_step * 1e3, 2),
        "samples_per_sec_per_chip": round(sps_chip, 2),
        "repeats": max(1, repeats),
        "spread_pct": round(spread_pct, 1),
        "gflops_per_sample": round(fps / 1e9, 2),
        "tflops_per_sec_per_chip": round(sps_chip * fps / 1e12, 1),
        "mfu": round(mfu(sps_chip, fps), 3),
        "mfu_peak_tflops": PEAK_BF16_FLOPS / 1e12,
    }


def measure_host_feed(cfg, batches: int = 50, warmup: int = 5) -> dict:
    """Time the host-side input pipeline alone — cache gather + wire
    formatting + whatever augmentation policy ``cfg`` configures — with no
    device in the loop.

    This is the number to hold against ``measure_train_step``: the round-2
    verdict's top item was that the compiled step ran at 8.3k samples/sec
    while the host sustained only ~0.5–0.8k end to end, dominated by a
    per-sample Python+packbits gather that the packed cache format removed.
    ``cfg.data_cache`` must point at a cache; the dataset is built exactly
    the way the Trainer builds its train stream (device augmentation on →
    the host path is pure fancy indexing).
    """
    if not cfg.data_cache:
        raise ValueError("measure_host_feed needs cfg.data_cache")
    if cfg.task == "segment":
        from featurenet_tpu.data.offline import SegCacheDataset

        ds = SegCacheDataset(
            cfg.data_cache, global_batch=cfg.global_batch, split="train",
            test_fraction=cfg.test_fraction, seed=cfg.seed,
            augment=cfg.augment,
        )
        host_augment = cfg.augment
    else:
        from featurenet_tpu.data.offline import VoxelCacheDataset

        host_augment = cfg.augment and not cfg.device_augment
        ds = VoxelCacheDataset(
            cfg.data_cache, global_batch=cfg.global_batch, split="train",
            test_fraction=cfg.test_fraction, seed=cfg.seed,
            augment=host_augment,
        )
    it = ds.worker_iter(0, 1)
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    dt = time.perf_counter() - t0
    return {
        "host_samples_per_sec": round(batches * ds.local_batch / dt, 1),
        "local_batch": ds.local_batch,
        "batches": batches,
        "host_augment": bool(host_augment),
    }


def measure_inference(
    cfg, batch_per_chip: int = 256, warmup: int = WARMUP,
    measure: int = MEASURE, repeats: int = 1,
) -> dict:
    """Slope-time the serving path: eval-mode forward + on-device argmax of
    packed voxel batches (what ``infer.Predictor`` dispatches per batch,
    minus host-side STL parsing). Same best-of-``repeats`` + spread
    reporting as ``measure_train_step`` so the serving claim is
    reproducible from the artifact (round-2 verdict weak item 6)."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.data.synthetic import generate_batch, pack_voxels
    from featurenet_tpu.models import FeatureNet
    from featurenet_tpu.parallel.mesh import make_mesh, replicated
    from featurenet_tpu.train.steps import unpack_voxels

    if cfg.task != "classify":
        raise ValueError(
            f"measure_inference serves classify configs only; "
            f"{cfg.name!r} has task={cfg.task!r}"
        )
    n_chips = len(jax.devices())
    mesh = make_mesh()
    global_batch = batch_per_chip * mesh.shape["data"]
    R = cfg.resolution

    model = FeatureNet(arch=cfg.arch)
    rng = jax.random.key(0)
    # Param/BN shapes are batch-independent: init on a batch-1 sample so
    # init never runs a full global-batch f32 forward on one device.
    sample = jnp.zeros((1, R, R, R, 1), jnp.float32)
    variables = model.init(rng, sample, train=False)
    params = jax.device_put(variables, replicated(mesh))

    @jax.jit
    def serve(variables, packed):
        x = unpack_voxels(packed)  # [B,R,R,R,1] f32; model casts to bf16
        logits = model.apply(variables, x, train=False)
        return jnp.argmax(logits, axis=-1)

    host = pack_voxels(
        generate_batch(np.random.default_rng(0), global_batch, R)["voxels"]
    )
    from featurenet_tpu.parallel.mesh import batch_shardings

    packed = jax.device_put(
        host, batch_shardings(mesh, keys=("voxels",))["voxels"]
    )
    for _ in range(max(1, warmup)):  # >=1: the readback below drains it
        labels = serve(params, packed)
    int(labels[0])

    def walled(k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            labels = serve(params, packed)
        int(labels[0])  # device→host readback = honest sync
        return time.perf_counter() - t0

    # Adaptive slope length: a fast forward (warp64 is ~2 ms/batch) over
    # only MEASURE iterations gives a ~40 ms window that drowns in
    # tunnel/readback jitter (observed 159% spread). Size the window to
    # ~1 s of device work so the slope dominates the noise; best-of-2
    # probes so one jitter spike can't shrink the window back into the
    # noisy regime this sizing exists to escape.
    probe = max(min(walled(measure), walled(measure)) / measure, 1e-6)
    measure = max(measure, int(1.0 / probe))
    per_batch, spread_pct = _best_slope(walled, measure, repeats)
    return {
        "batch_per_chip": batch_per_chip,
        "per_batch_ms": round(per_batch * 1e3, 2),
        "inferences_per_sec_per_chip": round(
            global_batch / per_batch / n_chips, 1
        ),
        "repeats": max(1, repeats),
        "spread_pct": round(spread_pct, 1),
    }
