"""Shared train-step throughput measurement (the bench core).

Lives inside the package so both the driver's root-level ``bench.py`` and
``featurenet_tpu.ops.bench_arch`` (the architecture sweep) import it without
depending on the repo root being on sys.path.

Method — slope timing: jit the full train step (fwd+bwd+optimizer+BN), warm
up, then wall (1 step + loss transfer) and (N+1 steps + loss transfer);
per-step time = (t_long - t_short)/N. The final scalar transfer is the sync
point — on this environment's tunneled TPU backend, ``block_until_ready``
returns before device execution completes, so only a device→host readback is
an honest wall; the slope subtracts the constant round-trip latency.
"""

from __future__ import annotations

import time

import numpy as np

V100_SAMPLES_PER_SEC_EST = 330.0  # documented estimate, see BASELINE.md
# Per-chip batch: XLA pads the batch dim to multiples of 128 (measured —
# batch 96 and 128 take the same 53 ms step), so bench at the multiple;
# this is also the pod64 preset's training batch.
BATCH = 128
WARMUP, MEASURE = 5, 20


def _converged_slope(
    walled, measure: int, repeats: int, min_window_sec: float = 3.0,
    agree_pct: float = 3.0,
) -> dict:
    """Adaptive slope protocol — the discipline that fixed the round-3
    serving artifact (BENCH_r03's 19.2% spread), now shared by the train
    and serving measurements:

    1. Floor the slope window at ~``min_window_sec`` of device work — a
       single readback's jitter is hundreds of ms on this tunneled
       backend, i.e. tens of percent of a too-short window.
    2. Keep drawing slopes until the two best agree within ``agree_pct``
       (draw cap at 3× ``repeats``); non-positive slopes (a stall landed
       inside the short probe) are contamination and are dropped.
    3. Quote the MEAN of the two agreeing best draws. Not the min: with a
       draw-until-agreement loop, more draws monotonically lower a min, so
       a contaminated session would yield a *more* optimistic headline
       (round-5 advisor finding).

    Returns per-call seconds plus both spread views: ``spread_pct`` =
    best-two agreement (reproducibility of the quoted number) and
    ``spread_minmax_pct`` = full draw range including absorbed outliers.
    """
    probe = max(min(walled(measure), walled(measure)) / measure, 1e-9)
    measure = max(measure, int(min_window_sec / probe))
    slopes: list[float] = []
    draws = 0
    cap = max(2, repeats) * 3
    while True:
        draws += 1
        t_short = walled(1)
        t_long = walled(1 + measure)
        slope = (t_long - t_short) / measure
        if slope > 0:
            slopes.append(slope)
        if len(slopes) >= max(2, repeats):
            s = sorted(slopes)
            if 100.0 * (s[1] - s[0]) / s[0] <= agree_pct or draws >= cap:
                break
        elif draws >= cap and len(slopes) >= 2:
            break
        elif draws >= 2 * cap:
            raise RuntimeError(
                f"could not collect 2 positive slopes in {draws} draws — "
                "host/link too contaminated to measure"
            )
    s = sorted(slopes)
    return {
        "per_call": (s[0] + s[1]) / 2.0,
        "spread_pct": round(100.0 * (s[1] - s[0]) / s[0], 1),
        "spread_minmax_pct": round(100.0 * (s[-1] - s[0]) / s[0], 1),
        "draws": len(slopes),
        "window_calls": measure,
    }


def measure_train_step(
    cfg, batch_per_chip: int = BATCH, warmup: int = WARMUP,
    measure: int = MEASURE, repeats: int = 1,
    devices=None, min_window_sec: float = 3.0,
) -> dict:
    """Slope-time the compiled train step for ``cfg`` on all devices.

    Returns per-chip throughput plus the analytic-MFU fields. Weak scaling:
    the per-chip batch stays fixed regardless of chip count.

    ``devices``: restrict the mesh to these devices (default: all) — the
    scaling sweep (``measure_scaling``) measures the same program over
    device subsets so the per-chip retention vs chip count is one
    session's apples-to-apples. ``min_window_sec``: the converged-slope
    window floor (tests shrink it; the 3 s default is the honest one on
    the tunneled backend).

    ``repeats``: minimum independent slope draws. The measurement runs the
    shared ``_converged_slope`` protocol (≥3 s windows, draw until the two
    best agree, quote their mean) — one short-window slope through this
    environment's tunneled backend has shown ±13% spread under host load
    (round-2: 9520 clean vs 8252 loaded), and round-4's flagship train
    spread regressed to 6.9% under driver conditions with fixed 40-step
    windows while the same protocol held serving to 0.2%.
    """
    import dataclasses

    import jax

    if cfg.task != "classify":
        # This path builds a FeatureNet classifier on the classify wire
        # format unconditionally; benchmarking a segment config here would
        # silently measure the wrong model under that config's name.
        raise ValueError(
            f"measure_train_step benchmarks classify configs only; "
            f"{cfg.name!r} has task={cfg.task!r}"
        )

    from featurenet_tpu.data.synthetic import generate_batch, to_wire
    from featurenet_tpu.ops.flops import (
        PEAK_BF16_FLOPS,
        mfu,
        train_step_flops_per_sample,
    )
    from featurenet_tpu.parallel.mesh import make_mesh
    from featurenet_tpu.runtime import Runtime

    devices = list(devices) if devices is not None else jax.devices()
    n_chips = len(devices)
    # The measured program is the registry's own train_step at the swept
    # batch — what the Trainer dispatches is by construction what the
    # bench (and ops/bench_arch's variant sweep) times.
    rt = Runtime(dataclasses.replace(
        cfg, global_batch=batch_per_chip * n_chips,
        steps_per_dispatch=1, mesh_model=1, spatial=False,
    ), mesh=make_mesh(data=n_chips, model=1, devices=devices))
    mesh = rt.mesh
    global_batch = rt.cfg.global_batch
    R = cfg.resolution

    state = rt.build("init")(jax.random.key(0))
    step = rt.build("train_step")

    host = to_wire(
        generate_batch(np.random.default_rng(0), global_batch, R), "classify"
    )
    batch = jax.device_put(host, rt.batch_sh)
    rng = jax.device_put(jax.random.key(1), rt.rep)

    for _ in range(max(1, warmup)):  # >=1: the readback below drains it
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # drain the pipe

    def walled(k: int) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(k):
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])  # device→host readback = honest sync
        return time.perf_counter() - t0

    conv = _converged_slope(walled, measure, repeats,
                            min_window_sec=min_window_sec)
    per_step = conv["per_call"]
    sps_chip = global_batch / per_step / n_chips
    fps = train_step_flops_per_sample(cfg.arch, R)
    out = {
        "batch_per_chip": batch_per_chip,
        "per_step_ms": round(per_step * 1e3, 2),
        "samples_per_sec_per_chip": round(sps_chip, 2),
        "repeats": conv["draws"],
        "spread_pct": conv["spread_pct"],
        "spread_minmax_pct": conv["spread_minmax_pct"],
        "gflops_per_sample": round(fps / 1e9, 2),
        "tflops_per_sec_per_chip": round(sps_chip * fps / 1e12, 1),
        "mfu": round(mfu(sps_chip, fps), 3),
        "mfu_peak_tflops": PEAK_BF16_FLOPS / 1e12,
    }
    # Measured-cost attribution (obs.perf): MFU from the COMPILED
    # program's own XLA flop count (per-device, post-partitioning) over
    # the slope-timed step wall and the device-kind peak table — the
    # evidence-based counterpart of the analytic `mfu` above — plus the
    # executable's peak-memory footprint. Honest-absence on backends
    # with no cost analysis / no peak entry (CPU): the keys stay out,
    # and so do their gate pins.
    from featurenet_tpu.obs import perf as obs_perf

    peaks = obs_perf.local_device_peaks()
    cost = getattr(step, "cost", None) or {}
    m = obs_perf.mfu_value(cost, per_step, peaks)
    if m is not None:
        out["mfu_train"] = round(m, 4)
    if cost.get("peak_bytes"):
        out["hbm_peak_train_bytes"] = int(cost["peak_bytes"])
    roof = obs_perf.roofline(cost.get("flops"), cost.get("bytes"), peaks)
    if roof is not None:
        out["train_roofline"] = roof
    return out


def measure_ttfs(cfg, batch_per_chip: int = 256,
                 program: str = "serve_packed",
                 precision: str = "fp32") -> dict:
    """Time-to-first-step, cold vs warm, through the runtime registry's
    persistent executable cache: build → lower → compile (or cache load)
    → one executed dispatch, against a throwaway cache directory.

    ``cold`` populates the cache (a fresh XLA compile); ``warm`` rebuilds
    the same program in a NEW Runtime against the now-populated cache —
    the supervisor-respawn / serving-cold-start path. The guarded load can
    legitimately refuse (probe failure, FEATURENET_EXEC_CACHE_PROBE=
    reject): ``warm_source`` records whether the warm number actually came
    from the cache ("cache") or degraded to a fresh compile ("fresh") —
    a degraded warm ≈ cold is an honest artifact, not a broken round.

    ``precision`` selects the serving rung (``fp32 | bf16 | int8``) by
    resolving ``program`` to its precision variant (``serve_packed`` →
    ``serve_packed_bf16`` / ``serve_packed_int8``) — a fleet replica
    warming up serves ONE precision's bucket ladder, so cold/warm TTFS
    is a per-precision number, not an fp32-only one."""
    import dataclasses
    import shutil
    import tempfile

    import jax

    from featurenet_tpu.runtime import ExecutableCache, Runtime
    from featurenet_tpu.runtime.registry import serve_program_name

    if program in ("serve", "serve_packed"):
        program = serve_program_name(precision,
                                     packed=program == "serve_packed")
    elif precision != "fp32":
        raise ValueError(
            f"precision={precision!r} only applies to the serve/"
            f"serve_packed program families, not {program!r}"
        )
    mcfg = dataclasses.replace(
        cfg, global_batch=batch_per_chip * len(jax.devices()),
        steps_per_dispatch=1, mesh_model=1, spatial=False,
    )
    cache_dir = tempfile.mkdtemp(prefix="fn_ttfs_cache_")

    def first_step() -> tuple[float, str]:
        t0 = time.perf_counter()
        rt = Runtime(mcfg, cache=ExecutableCache(cache_dir))
        prog = rt.build(program)
        args = jax.tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype), prog.spec.abstract_args
        )
        # TTFS includes the first result's readback — dispatch alone
        # proves nothing on a hung backend.
        jax.block_until_ready(prog(*args))
        return time.perf_counter() - t0, prog.source

    try:
        cold_s, _ = first_step()
        warm_s, warm_source = first_step()
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return {
        "program": program,
        "precision": precision,
        "ttfs_cold_s": round(cold_s, 3),
        "ttfs_warm_s": round(warm_s, 3),
        "ttfs_speedup": round(cold_s / max(warm_s, 1e-9), 2),
        "warm_source": warm_source,
    }


def measure_host_feed(cfg, batches: int = 50, warmup: int = 5) -> dict:
    """Time the host-side input pipeline alone — cache gather + wire
    formatting + whatever augmentation policy ``cfg`` configures — with no
    device in the loop.

    This is the number to hold against ``measure_train_step``: the round-2
    verdict's top item was that the compiled step ran at 8.3k samples/sec
    while the host sustained only ~0.5–0.8k end to end, dominated by a
    per-sample Python+packbits gather that the packed cache format removed.
    ``cfg.data_cache`` must point at a cache; the dataset is built exactly
    the way the Trainer builds its train stream (device augmentation on →
    the host path is pure fancy indexing).
    """
    if not cfg.data_cache:
        raise ValueError("measure_host_feed needs cfg.data_cache")
    host_augment = cfg.augment and not cfg.device_augment
    if cfg.task == "segment":
        from featurenet_tpu.data.offline import SegCacheDataset

        ds = SegCacheDataset(
            cfg.data_cache, global_batch=cfg.global_batch, split="train",
            test_fraction=cfg.test_fraction, seed=cfg.seed,
            augment=host_augment,
        )
    else:
        from featurenet_tpu.data.offline import VoxelCacheDataset

        ds = VoxelCacheDataset(
            cfg.data_cache, global_batch=cfg.global_batch, split="train",
            test_fraction=cfg.test_fraction, seed=cfg.seed,
            augment=host_augment,
        )
    it = ds.worker_iter(0, 1)
    for _ in range(warmup):
        next(it)
    t0 = time.perf_counter()
    for _ in range(batches):
        next(it)
    dt = time.perf_counter() - t0
    return {
        "host_samples_per_sec": round(batches * ds.local_batch / dt, 1),
        "local_batch": ds.local_batch,
        "batches": batches,
        "host_augment": bool(host_augment),
    }


def measure_e2e(
    cfg, steps: int = 48, warmup: int = 16, repeats: int = 2
) -> dict:
    """Wall-clock end-to-end training rate through the Trainer's own path:
    host feed (cache gather + wire) → threaded prefetch → device_put →
    (possibly k-fused) dispatch → bounded-in-flight readback.

    ``measure_train_step`` times the compiled step with inputs resident —
    the device's honest rate. This times what a user's training run
    actually sustains on this host/link; the two diverge when dispatch or
    the host binds, which is exactly what ``cfg.steps_per_dispatch``
    amortizes (round-3 verdict: 14k device rate vs ~1.05k e2e through the
    tunnel, with 11.2 ms/step of dispatch as the largest non-compute line).
    """
    import jax  # noqa: F401  (device backend must initialize first)

    from featurenet_tpu.data.dataset import prefetch_to_device
    from featurenet_tpu.train.loop import Trainer

    trainer = Trainer(cfg)
    k = trainer._k
    stream = None if trainer._hbm else prefetch_to_device(
        trainer.train_data, sharding=trainer.batch_sh,
        num_workers=cfg.data_workers,
    )

    # Dispatch goes through Trainer.dispatch_group — the run loop's own
    # path — so this measures what training executes, not a re-impl of it.
    try:
        m = None
        for _ in range(max(1, warmup // k)):
            m = trainer.dispatch_group(stream, k)
        float(m["loss"])  # drain compile + pipeline fill
        groups = max(1, steps // k)

        def walled() -> float:
            pending: list = []
            t0 = time.perf_counter()
            for _ in range(groups):
                pending.append(trainer.dispatch_group(stream, k)["loss"])
                if len(pending) > max(1, cfg.max_inflight_steps // k):
                    float(pending.pop(0))
            for loss in pending:
                float(loss)
            return time.perf_counter() - t0

        # Best-of-repeats: a measurement window of only steps/k dispatch
        # groups (6 at the defaults with k=8) puts one ~second-scale tunnel
        # stall at 1/6 of the wall — a single window once measured a
        # *pipelined* loop as slower than unpipelined. The best window is
        # the honest sustained rate; spread is reported alongside.
        walls = [walled() for _ in range(max(1, repeats))]
    finally:
        if stream is not None:
            # Generator close → producer stop event: without it, each
            # measure_e2e leaves worker threads alive with up to a
            # lookahead of device_put batches pinned in HBM — host/HBM
            # contamination for any measurement that follows in-process
            # (round-5 advisor finding).
            stream.close()
    dt = min(walls)
    return {
        "e2e_samples_per_sec": round(groups * k * cfg.global_batch / dt, 1),
        "e2e_spread_pct": round(100.0 * (max(walls) - dt) / dt, 1),
        "steps_per_dispatch": k,
        "steps": groups * k,
        "global_batch": cfg.global_batch,
        "hbm_resident": bool(trainer._hbm),
    }


def measure_inference(
    cfg, batch_per_chip: int = 256, warmup: int = WARMUP,
    measure: int = MEASURE, repeats: int = 1, precision: str = "fp32",
) -> dict:
    """Slope-time the serving path: eval-mode forward + on-device argmax of
    packed voxel batches (what ``infer.Predictor`` dispatches per batch,
    minus host-side STL parsing), as the registry's ``serve_packed``
    program. ``precision="int8"`` measures ``serve_packed_int8`` — the
    per-channel weight-quantized serving executable — and
    ``precision="bf16"`` measures ``serve_packed_bf16``, the
    working-copy-cast rung of the serving precision ladder
    (``Config.serve_precision``). Same best-of-``repeats`` + spread
    reporting as ``measure_train_step`` so the serving claim is
    reproducible from the artifact (round-2 verdict weak item 6)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from featurenet_tpu.data.synthetic import generate_batch, pack_voxels
    from featurenet_tpu.runtime import Runtime
    from featurenet_tpu.runtime.registry import PRECISIONS

    if cfg.task != "classify":
        raise ValueError(
            f"measure_inference serves classify configs only; "
            f"{cfg.name!r} has task={cfg.task!r}"
        )
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown serving precision {precision!r}; one of "
            f"{', '.join(PRECISIONS)}"
        )
    n_chips = len(jax.devices())
    rt = Runtime(dataclasses.replace(
        cfg, steps_per_dispatch=1, mesh_model=1, spatial=False,
    ))
    mesh = rt.mesh
    global_batch = batch_per_chip * mesh.shape["data"]
    R = cfg.resolution

    rng = jax.random.key(0)
    # Param/BN shapes are batch-independent: init on a batch-1 sample so
    # init never runs a full global-batch f32 forward on one device.
    sample = jnp.zeros((1, R, R, R, 1), jnp.float32)
    variables = rt.model.init(rng, sample, train=False)
    if precision == "bf16":
        # Pre-cast the working copy ONCE, like the Predictor: the bf16
        # tree is the program argument, so the measured dispatches read
        # 2-byte weights from HBM — the rung's actual traffic.
        from featurenet_tpu.train.precision import serve_params_cast

        variables = dict(variables)
        variables["params"] = serve_params_cast(variables["params"], "bf16")
    variables = jax.device_put(variables, rt.rep)

    from featurenet_tpu.runtime.registry import serve_program_name

    if precision == "int8":
        from featurenet_tpu.runtime.quantize import quantize_tree

        qp, sc = quantize_tree(variables["params"])
        program = rt.build("serve_packed_int8", global_batch=global_batch)

        def serve(packed):
            return program(qp, sc, variables["batch_stats"], packed)
    else:
        # fp32 and bf16 share the (variables, packed) signature — bf16's
        # param avals are the pre-cast working copy above.
        program = rt.build(serve_program_name(precision, packed=True),
                           global_batch=global_batch)

        def serve(packed):
            return program(variables, packed)

    host = pack_voxels(
        generate_batch(np.random.default_rng(0), global_batch, R)["voxels"]
    )
    from featurenet_tpu.parallel.mesh import batch_shardings

    packed = jax.device_put(
        host, batch_shardings(mesh, keys=("voxels",))["voxels"]
    )
    for _ in range(max(1, warmup)):  # >=1: the readback below drains it
        labels = serve(packed)
    int(labels[0])

    def walled(k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            labels = serve(packed)
        int(labels[0])  # device→host readback = honest sync
        return time.perf_counter() - t0

    # Shared converged-slope protocol (see _converged_slope): ≥3 s windows
    # (warp64's ~2 ms forward over 20 iterations would drown in readback
    # jitter — the mechanism behind BENCH_r03's 19.2% artifact spread),
    # draw until the two best agree, quote their mean.
    conv = _converged_slope(walled, measure, repeats)
    per_batch = conv["per_call"]
    # Serving-side measured-cost MFU (obs.perf), same shape as
    # measure_train_step's mfu_train: compiled flops over the converged
    # per-batch wall over the peak table; absent when either is unknown.
    from featurenet_tpu.obs import perf as obs_perf

    peaks = obs_perf.local_device_peaks()
    m = obs_perf.mfu_value(getattr(program, "cost", None), per_batch, peaks)
    perf_fields = {} if m is None else {"serve_mfu": round(m, 4)}
    return {
        "batch_per_chip": batch_per_chip,
        "precision": precision,
        "per_batch_ms": round(per_batch * 1e3, 2),
        "inferences_per_sec_per_chip": round(
            global_batch / per_batch / n_chips, 1
        ),
        **perf_fields,
        "repeats": conv["draws"],
        # spread_pct: agreement between the two best slopes — the
        # reproducibility of the quoted number. spread_minmax_pct: full
        # range across draws, including contaminated ones; large minmax
        # with small best-two agreement = transient noise absorbed, not a
        # shaky headline.
        "spread_pct": conv["spread_pct"],
        "spread_minmax_pct": conv["spread_minmax_pct"],
    }


def measure_scaling(
    cfg, batch_per_chip: int = BATCH, repeats: int = 2,
    shapes=None, min_window_sec: float = 3.0,
) -> dict:
    """Per-chip train-step throughput at each power-of-two data-mesh
    shape this session's devices allow — the scaling-efficiency half of
    the MULTICHIP gate (the series used to be raw stdout tails a human
    eyeballed round over round; these rows pin samples/sec *vs chip
    count* so a widening lockstep tax fails a gate instead of hiding in
    a log).

    Weak scaling (per-chip batch fixed), the exact ``measure_train_step``
    protocol per shape, all shapes in one session so the rows are
    comparable. Returns ``{"shapes": {n: row}, "scaling_efficiency": r}``
    — ``r`` is the largest shape's per-chip rate over the single-chip
    rate (1.0 = perfect retention; absent with only one device).
    """
    import jax

    n_dev = len(jax.devices())
    if shapes is None:
        shapes = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= n_dev]
    rows: dict = {}
    for n in shapes:
        if n > n_dev:
            raise ValueError(f"shape {n} exceeds {n_dev} device(s)")
        rows[n] = measure_train_step(
            cfg, batch_per_chip=batch_per_chip, repeats=repeats,
            devices=jax.devices()[:n], min_window_sec=min_window_sec,
        )
    out: dict = {"shapes": rows}
    if len(rows) > 1:
        lo, hi = min(rows), max(rows)
        out["scaling_efficiency"] = round(
            rows[hi]["samples_per_sec_per_chip"]
            / max(rows[lo]["samples_per_sec_per_chip"], 1e-9), 4
        )
    return out


# The spread probe's worker: a tiny 2-process CPU mesh running a few real
# train steps with a run_dir, so the merged report's cross-host data-wait
# spread — the number the MULTICHIP series never pinned — exists for the
# gate even on a single-chip driver. CPU on purpose: the probe measures
# the HOST feed skew machinery end to end, and must not touch (or depend
# on) the accelerator the main measurements own.
_SPREAD_WORKER = r"""
import json, os, sys
rank, nproc, port, run_dir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except AttributeError:
    pass
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=nproc, process_id=rank,
)
from featurenet_tpu.config import get_config
from featurenet_tpu.train.loop import Trainer
cfg = get_config(
    "smoke16", total_steps=2, global_batch=8, data_workers=1,
    eval_batches=1, log_every=10**9, eval_every=10**9,
    checkpoint_every=10**9, run_dir=run_dir,
)
Trainer(cfg).run()
print("SPREAD_OK")
"""


def measure_host_spread(n_hosts: int = 2, timeout_s: float = 600.0) -> dict:
    """Cross-host data-wait spread of a real ``n_hosts``-process run —
    ``data_wait_spread`` for the scaling gate. Spawns the probe workers,
    merges their per-host event streams, and extracts the report's gate
    scalars. Raises on any probe failure; the caller (bench) degrades to
    an absent gate key with the error in-artifact."""
    import os
    import socket
    import subprocess
    import sys
    import tempfile
    import threading

    import featurenet_tpu
    from featurenet_tpu.obs.gates import report_gate_values
    from featurenet_tpu.obs.report import build_report_dir

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    run_dir = tempfile.mkdtemp(prefix="fn_spread_")
    env = {
        **os.environ,
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(featurenet_tpu.__file__))
        ),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _SPREAD_WORKER, str(i), str(n_hosts),
             str(port), run_dir],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(n_hosts)
    ]
    outs = [""] * n_hosts

    def drain(i: int, p) -> None:
        outs[i] = p.communicate()[0]

    threads = [
        threading.Thread(target=drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    import shutil

    try:
        try:
            for t in threads:
                t.start()
            deadline = time.monotonic() + timeout_s
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for t in threads:
                t.join(timeout=30)
        if any(p.returncode != 0 for p in procs) \
                or not all("SPREAD_OK" in o for o in outs):
            raise RuntimeError(
                "spread probe worker failed: "
                + " | ".join(o[-400:] for o in outs)
            )
        vals = report_gate_values(build_report_dir(run_dir))
    finally:
        # Failure paths leak the per-probe tempdir otherwise — bench
        # runs this every round, and a flaky gloo init would pile run
        # dirs in /tmp (the slo-tempdir lesson from the PR 5 review).
        shutil.rmtree(run_dir, ignore_errors=True)
    if "data_wait_spread" not in vals:
        raise RuntimeError(
            "spread probe produced no data_wait_spread (hosts missing "
            "loop telemetry)"
        )
    return {"data_wait_spread": vals["data_wait_spread"],
            "n_hosts": n_hosts}
