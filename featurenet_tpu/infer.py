"""Inference: trained checkpoint → class predictions for voxel grids or STL.

The reference had no serving path — eval doubled as inference (SURVEY.md §2
C7). This module is the missing capability done TPU-style: one AOT-jitted,
fixed-shape forward (padded to a static batch so every call hits the compile
cache), fed either by in-memory grids or by the full STL → normalize →
voxelize front end.

Usage:
    p = Predictor.from_checkpoint("ckpts/", config=get_config("pod64"))
    labels, probs = p.predict_voxels(grids)          # [N,R,R,R] occupancy
    results = p.predict_stl(["part.stl", ...])       # end-to-end

Segmentation checkpoints (``task='segment'``) use the same entry points;
``predict_stl`` then returns ``SegPrediction`` (per-voxel label grid +
per-class feature-voxel counts), and the grid path is
``predict_voxels_seg``. The per-voxel argmax runs on device so only int8
labels — not the 25-channel probability volume — cross back to the host.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from featurenet_tpu import obs
from featurenet_tpu.config import Config, get_config
from featurenet_tpu.data.stl import load_stl
from featurenet_tpu.data.synthetic import CLASS_NAMES
from featurenet_tpu.data.voxelize import voxelize


@dataclasses.dataclass
class Prediction:
    path: str
    label: int
    class_name: str
    prob: float
    top3: list[tuple[str, float]]


@dataclasses.dataclass
class SegPrediction:
    path: str
    # Predicted feature-voxel counts, class_name -> count (background 0
    # excluded); empty dict = no feature voxels predicted.
    voxel_counts: dict[str, int]
    # Per-voxel labels [R, R, R] int8: 0 = stock/air, 1+c = feature class c.
    labels: np.ndarray = dataclasses.field(repr=False)


@dataclasses.dataclass
class _Weights:
    """One generation of serving weights, bundled so a hot swap is a
    SINGLE reference flip: ``forward_padded`` reads ``self._weights``
    once per dispatch and every tree it hands the program comes from
    that one read — a swap landing between two dispatches can never
    produce a torn forward (old params, new scales)."""
    params: object            # fp32 masters (agreement gate, re-cast source)
    stats: object             # batch-norm stats
    serve_params: object      # what the serve program reads (bf16 copy or alias)
    qparams: object           # int8 precision only, else None
    scales: object            # int8 precision only, else None
    version: str              # model_version tag this generation serves


def checkpoint_version(checkpoint_dir: str, step) -> str:
    """The human-readable model_version tag for a checkpoint directory:
    ``<dirname>@<step>-<sidecar sha256 prefix>``. The digest comes from
    the save-time checksum sidecar, so two directories holding the same
    step number but different bytes get distinct tags; legacy dirs
    without a sidecar fall back to ``<dirname>@<step>``."""
    import hashlib
    import os

    from featurenet_tpu.train.checkpoint import _checksum_path

    base = os.path.basename(os.path.normpath(os.path.abspath(checkpoint_dir)))
    if step is None:
        return base
    tag = f"{base}@{int(step)}"
    try:
        with open(_checksum_path(checkpoint_dir, int(step)), "rb") as fh:
            return f"{tag}-{hashlib.sha256(fh.read()).hexdigest()[:8]}"
    except OSError:
        return tag


def _restore_for_serving(checkpoint_dir: str, config=None):
    """Restore a checkpoint's weights for serving: the shared walk under
    ``Predictor.from_checkpoint`` (cold start) and
    ``Predictor.swap_params`` (hot swap — ``config`` is then the LIVE
    config, so an identity-mismatched candidate raises before any state
    changes). Returns ``(state, cfg, model_version)``."""
    import jax

    from featurenet_tpu.config import check_identity
    from featurenet_tpu.runtime import build_model
    from featurenet_tpu.train.checkpoint import (
        CheckpointManager,
        load_run_config,
    )
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer

    saved = load_run_config(checkpoint_dir)
    if config is None:
        cfg = saved if saved is not None else get_config("pod64")
    else:
        cfg = get_config(config) if isinstance(config, str) else config
        if saved is not None:
            check_identity(saved, cfg)
    model = build_model(cfg)
    sample = np.zeros(
        (1, cfg.resolution, cfg.resolution, cfg.resolution, 1), np.float32
    )
    state = create_state(
        model, make_optimizer(cfg), sample, jax.random.key(0)
    )
    mgr = CheckpointManager(checkpoint_dir)
    state = mgr.restore(state)
    version = checkpoint_version(checkpoint_dir, mgr.latest_step())
    mgr.close()
    return state, cfg, version


class Predictor:
    """Fixed-shape compiled serving forward over a trained checkpoint.

    ``batch`` is the static compile shape; inputs are padded up / chunked to
    it. Single-device by design (serving a ~5M-param model never needs a
    mesh). The forward is a runtime-registry program (``serve`` /
    ``serve_int8``), built AOT at construction — that build IS the serving
    warmup: with ``Config.exec_cache_dir`` set, a cold start deserializes
    the executable from the persistent cache instead of recompiling, and
    the first request never pays XLA.

    ``precision`` is the serving weight precision (default: the config's
    ``serve_precision``): ``"bf16"`` serves the ``serve_bf16`` program —
    a bf16 working copy of the fp32 masters, cast ONCE at construction,
    is what the program's avals name and what HBM serves per dispatch
    (half the weight reads; masters/BN stats stay fp32) — and ``"int8"``
    serves per-channel weight-quantized int8 weights
    (``runtime.quantize``): 4x less weight HBM traffic, dequantized on
    device inside the program. Gate any reduced rung with
    ``agreement()`` — the precision-agnostic, CPU-testable stand-in for
    the held-out accuracy target (paper bar 96.7%).
    """

    def __init__(self, params, batch_stats, cfg: Config, batch: int = 32,
                 precision: str | None = None,
                 model_version: str = "unversioned",
                 checkpoint_dir: str | None = None):
        from featurenet_tpu.runtime import Runtime
        from featurenet_tpu.runtime.registry import PRECISIONS

        import jax

        if precision is None:
            precision = cfg.serve_precision
        if precision not in PRECISIONS:
            raise ValueError(
                f"unknown serving precision {precision!r}; one of "
                f"{', '.join(PRECISIONS)}"
            )
        self.cfg = cfg
        self.batch = batch
        self.precision = precision
        self.checkpoint_dir = checkpoint_dir
        # Single-device by design (a ~5M-param model never needs a serving
        # mesh), so the Runtime gets an explicit 1x1 mesh: a checkpoint
        # trained with a pod-scale mesh_data/mesh_model must restore and
        # serve on a one-device host instead of dying in make_mesh, and
        # the serve programs' cache fingerprints stay mesh-independent
        # across serving fleets.
        from featurenet_tpu.parallel.mesh import make_mesh

        dev = jax.devices()[0]
        self._device = dev
        self.rt = Runtime(cfg, mesh=make_mesh(1, 1, devices=[dev]))
        self.model = self.rt.model
        self._weights = self._build_weights(params, batch_stats,
                                            model_version)
        # One executable per compile batch, memoized: the batch-mode API
        # uses exactly one (``batch``), the serving front end
        # (featurenet_tpu.serve) warms one per bucket in its ladder.
        self._programs: dict[int, object] = {}
        self._program = self.program_for(batch)
        # Perf attribution (obs.perf): serving-side MFU folds each
        # batch's measured wall against the program's compiled counters
        # (explicit `unknown` tier — no samples — on CPU).
        from featurenet_tpu.obs import perf as _perf

        self._peaks = _perf.local_device_peaks()

    def _build_weights(self, params, batch_stats, version: str) -> _Weights:
        """Device-put + precision-transform one generation of weights —
        the construction-time path AND the hot-swap path (a swap pays
        exactly the cost of a cold construction's weight prep, while the
        old generation keeps serving)."""
        import jax

        # Weights handed over from a mesh-sharded Trainer state are
        # gathered onto the serving device here.
        dparams = jax.device_put(params, self._device)
        dstats = jax.device_put(batch_stats, self._device)
        qparams = scales = None
        if self.precision == "int8":
            from featurenet_tpu.runtime.quantize import quantize_tree

            # Quantize once at construction; the program dequantizes on
            # device, so int8 is what sits in serving HBM.
            qparams, scales = quantize_tree(dparams)
        # The tree the serve program reads per dispatch: the fp32
        # masters under fp32, a bf16 WORKING COPY cast once HERE under
        # bf16 — so 2-byte weights are what the program's avals name and
        # what HBM serves on every request (the int8 path's
        # transform-at-construction pattern; masters stay fp32 beside it
        # for the agreement gate and re-precision).
        serve_params = dparams
        if self.precision == "bf16":
            from featurenet_tpu.train.precision import serve_params_cast

            serve_params = serve_params_cast(dparams, "bf16")
        return _Weights(params=dparams, stats=dstats,
                        serve_params=serve_params, qparams=qparams,
                        scales=scales, version=version)

    # The per-generation trees read through the live bundle, so every
    # consumer (agreement gate, tests, the quality prober) follows a
    # swap automatically.
    @property
    def _params(self):
        return self._weights.params

    @property
    def _stats(self):
        return self._weights.stats

    @property
    def _serve_params(self):
        return self._weights.serve_params

    @property
    def _qparams(self):
        return self._weights.qparams

    @property
    def _scales(self):
        return self._weights.scales

    @property
    def model_version(self) -> str:
        return self._weights.version

    def swap_params(self, checkpoint_dir: str) -> str:
        """Hot-swap the serving weights to another checkpoint of the SAME
        model identity, with zero downtime: restore + device-put + cast /
        quantize happen on the CALLER's thread against the existing AOT
        programs (params are call arguments, so no executable is touched),
        then the new generation lands as one atomic reference flip —
        dispatches in flight finish on the old weights, the next dispatch
        reads the new ones, and no intermediate state is ever visible.
        Raises (identity mismatch, corrupt checkpoint) BEFORE the flip:
        a failed swap leaves the replica serving the old generation.
        Returns the new ``model_version``."""
        state, cfg, version = _restore_for_serving(checkpoint_dir,
                                                   config=self.cfg)
        new = self._build_weights(state.params, state.batch_stats, version)
        self._weights = new
        self.checkpoint_dir = checkpoint_dir
        return version

    def program_for(self, batch: int):
        """The ``serve``/``serve_bf16``/``serve_int8`` executable at this
        compile batch (``registry.serve_program_name`` — the one
        precision→program mapping), built AOT through the runtime
        registry and memoized. Building one per bucket at startup is the
        serving warmup — afterwards no request shape ever triggers a
        compile."""
        from featurenet_tpu.runtime.registry import serve_program_name

        batch = int(batch)
        prog = self._programs.get(batch)
        if prog is None:
            prog = self.rt.build(serve_program_name(self.precision),
                                 batch=batch)
            self._programs[batch] = prog
        return prog

    def forward_padded(self, voxels, batch: int | None = None):
        """Run the compiled forward on an ALREADY padded
        ``[batch, R, R, R, 1]`` array (no chunking, no trimming); returns
        the device result. The continuous batcher calls this once per
        dispatch with its chosen bucket."""
        prog = self.program_for(
            batch if batch is not None else voxels.shape[0]
        )
        # ONE read of the live bundle per dispatch: a concurrent
        # swap_params flip cannot mix generations within a forward.
        w = self._weights
        if self.precision == "int8":
            return prog(w.qparams, w.scales, w.stats, voxels)
        return prog(w.serve_params, w.stats, voxels)

    def _forward(self, voxels):
        return self.forward_padded(voxels, self.batch)

    def agreement(self, n: int = 48, seed: int = 0,
                  reference_precision: str = "fp32",
                  candidate_precision: str | None = None) -> float:
        """Top-1 agreement between two serving precisions of this
        checkpoint's weights on fresh synthetic parts — the
        precision-agnostic serving accuracy gate
        (``runtime.quantize.agreement``; a prediction the precision
        change did not flip cannot have moved held-out accuracy).
        ``candidate_precision`` defaults to THIS Predictor's precision,
        so ``Predictor(..., precision="bf16").agreement()`` is the bf16
        rung's gate and the int8 one reads the same way."""
        from featurenet_tpu.data.synthetic import generate_batch
        from featurenet_tpu.runtime.quantize import agreement

        grids = generate_batch(
            np.random.default_rng(seed), n, self.cfg.resolution
        )["voxels"]
        return agreement(
            self.model, self._params, self._stats, grids,
            reference_precision=reference_precision,
            candidate_precision=candidate_precision or self.precision,
        )

    def int8_agreement(self, n: int = 48, seed: int = 0) -> float:
        """Back-compat alias: the int8 rung of ``agreement()``."""
        return self.agreement(n=n, seed=seed, candidate_precision="int8")

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: str,
        config: Config | str | None = None,
        batch: int = 32,
        precision: str | None = None,
    ) -> "Predictor":
        """Restore params/batch_stats from an Orbax run directory.

        ``config=None`` (the default) reads the config persisted with the
        checkpoint (``config.json``, written at save time) — the checkpoint
        knows its own arch/resolution/task, so no flags are needed. An
        explicit ``config`` must agree with the persisted identity fields
        (hard error otherwise); for legacy dirs without the sidecar it is
        the only source and falls back to the pod64 preset.

        The optimizer state in the checkpoint is restored (Orbax needs the
        full tree) and immediately dropped — inference keeps weights only.
        """
        state, cfg, version = _restore_for_serving(checkpoint_dir,
                                                   config=config)
        return cls(state.params, state.batch_stats, cfg, batch=batch,
                   precision=precision, model_version=version,
                   checkpoint_dir=checkpoint_dir)

    # -- prediction ---------------------------------------------------------
    def predict_voxels(
        self,
        grids: np.ndarray,
        canonicalize: bool = False,
        tta_rotations: bool = False,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Classify ``[N, R, R, R]`` (or ``[N,R,R,R,1]``) occupancy grids.

        Returns ``(labels int32 [N], probs float32 [N, num_classes])``.
        Inputs are chunked/padded to the static compile batch.

        Robust-serving modes (round 5 — BASELINE.md "pose canonicalization"):

        - ``canonicalize=True``: undo arbitrary SO(3) pose per part by
          min-AABB search + re-voxelization through the benchmark mesh
          pipeline (``data/canonicalize.py``) — the part re-enters the
          training distribution (pose AND scale normalized), up to
          cube-group ambiguity. Host-side, ~0.5 s/part at 64³.
          IMPLIES ``tta_rotations``: the min-AABB result lands on an
          arbitrary one of the 24 cube orientations, so the vote is what
          makes the canonicalized answer well-defined.
        - ``tta_rotations=True``: classify all 24 cube-group orientations
          and average probabilities — resolves the canonicalization
          ambiguity (and is a cheap invariance lift on its own: rotations
          are pure layout ops). 24× the device work per part.
        """
        if self.cfg.task == "segment":
            raise ValueError(
                "this Predictor wraps a segmentation checkpoint — use "
                "predict_voxels_seg (per-voxel labels), not class probs"
            )
        g = self._validated(grids)
        n = g.shape[0]
        if n == 0:
            return (
                np.zeros((0,), np.int32),
                np.zeros((0, len(CLASS_NAMES)), np.float32),
            )
        if canonicalize:
            from featurenet_tpu.data.canonicalize import (
                canonicalize as _canon,
            )

            g = np.stack([
                # lint: allow-precision(wire contract: serve input edge is fp32)
                _canon(g[i, ..., 0] > 0.5).astype(np.float32)
                for i in range(n)
            ])[..., None]
            tta_rotations = True  # the vote resolves the 24-fold ambiguity
        if tta_rotations:
            from featurenet_tpu.ops.augment import CUBE_GROUP

            # Mean probability over the 24 axis-aligned orientations. The
            # rotations are numpy transposes/flips on the host (batch dim 0
            # untouched), stacked into ONE forward stream so the static-
            # batch padding is paid once per chunk, not 24 times.
            rots = []
            for perm, flips in CUBE_GROUP:
                rot = np.transpose(
                    g, (0,) + tuple(1 + p for p in perm) + (4,)
                )
                ax = [1 + i for i, f in enumerate(flips) if f]
                if ax:
                    rot = np.flip(rot, ax)
                rots.append(rot)
            # lint: allow-host-sync(host-built rotation stack, never on device)
            stacked = np.ascontiguousarray(np.concatenate(rots, axis=0))
            p = self._batched_forward(stacked)
            probs = p.reshape(len(CUBE_GROUP), n, -1).mean(axis=0)
        else:
            probs = self._batched_forward(g)
        return probs.argmax(axis=-1).astype(np.int32), probs

    def _batched_forward(self, g: np.ndarray) -> np.ndarray:
        """Chunk/pad ``g`` to the static compile batch, run, trim, concat."""
        import time as _time

        from featurenet_tpu.obs import perf as _perf

        out = []
        for s in range(0, g.shape[0], self.batch):
            chunk = g[s : s + self.batch]
            pad = self.batch - chunk.shape[0]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad,) + chunk.shape[1:], np.float32)]
                )
            # Serving latency span: np.asarray forces the readback, so the
            # measured interval is true request latency (dispatch + device
            # + transfer), feeding the report's latency histogram.
            t0 = _time.perf_counter()
            with obs.span("infer_batch", n=self.batch - pad,
                          batch=self.batch):
                # lint: allow-host-sync(readback IS the measured latency)
                y = np.asarray(self._forward(chunk))
            # Same wall the span measured, folded into the rolling MFU.
            _perf.observe_dispatch(
                getattr(self._program, "cost", None),
                _time.perf_counter() - t0, peaks=self._peaks,
            )
            out.append(y[: self.batch - pad])
        return np.concatenate(out, axis=0)

    def _validated(self, grids: np.ndarray) -> np.ndarray:
        # lint: allow-host-sync(host-side input array, never on device)
        g = np.asarray(grids, dtype=np.float32)
        if g.ndim == 4:
            g = g[..., None]
        R = self.cfg.resolution
        if g.shape[1:] != (R, R, R, 1):
            raise ValueError(
                f"expected [N,{R},{R},{R}(,1)] grids, got {g.shape}"
            )
        return g

    def predict_voxels_seg(self, grids: np.ndarray) -> np.ndarray:
        """Per-voxel labels for ``[N, R, R, R]`` grids (segment checkpoints).

        Returns int8 ``[N, R, R, R]``: 0 = stock/air, 1+c = feature class c.
        """
        if self.cfg.task != "segment":
            raise ValueError(
                "this Predictor wraps a classification checkpoint — use "
                "predict_voxels"
            )
        g = self._validated(grids)
        R = self.cfg.resolution
        if g.shape[0] == 0:
            return np.zeros((0, R, R, R), np.int8)
        return self._batched_forward(g)

    def predict_stl(
        self, paths: Sequence[str], fill: bool = True
    ) -> list[Prediction] | list[SegPrediction]:
        """End-to-end: STL file → normalized voxel grid → prediction.

        Classification checkpoints return ``Prediction`` (class + top-3);
        segmentation checkpoints return ``SegPrediction`` (per-voxel label
        grid + feature-voxel counts by class).
        """
        if not paths:
            return []
        R = self.cfg.resolution
        grids = np.stack(
            [voxelize(load_stl(p), R, fill=fill) for p in paths]
        )
        if self.cfg.task == "segment":
            label_grids = self.predict_voxels_seg(grids)
            seg_out: list[SegPrediction] = []
            for path, lab in zip(paths, label_grids):
                counts = np.bincount(
                    lab.ravel(), minlength=len(CLASS_NAMES) + 1
                )
                seg_out.append(
                    SegPrediction(
                        path=path,
                        voxel_counts={
                            # A head wider than the canonical block (custom
                            # num_classes) yields ids with no name — report
                            # them numerically instead of IndexError-ing.
                            (CLASS_NAMES[c - 1] if c - 1 < len(CLASS_NAMES)
                             else f"class_{c - 1}"): int(counts[c])
                            for c in range(1, len(counts))
                            if counts[c]
                        },
                        labels=lab,
                    )
                )
            return seg_out
        labels, probs = self.predict_voxels(grids)
        out = []
        for path, lab, pr in zip(paths, labels, probs):
            order = np.argsort(pr)[::-1][:3]
            out.append(
                Prediction(
                    path=path,
                    label=int(lab),
                    class_name=CLASS_NAMES[int(lab)],
                    prob=float(pr[lab]),
                    top3=[(CLASS_NAMES[i], float(pr[i])) for i in order],
                )
            )
        return out
