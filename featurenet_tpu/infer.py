"""Inference: trained checkpoint → class predictions for voxel grids or STL.

The reference had no serving path — eval doubled as inference (SURVEY.md §2
C7). This module is the missing capability done TPU-style: one AOT-jitted,
fixed-shape forward (padded to a static batch so every call hits the compile
cache), fed either by in-memory grids or by the full STL → normalize →
voxelize front end.

Usage:
    p = Predictor.from_checkpoint("ckpts/", config=get_config("pod64"))
    labels, probs = p.predict_voxels(grids)          # [N,R,R,R] occupancy
    results = p.predict_stl(["part.stl", ...])       # end-to-end
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from featurenet_tpu.config import Config, get_config
from featurenet_tpu.data.stl import load_stl
from featurenet_tpu.data.synthetic import CLASS_NAMES
from featurenet_tpu.data.voxelize import voxelize


@dataclasses.dataclass
class Prediction:
    path: str
    label: int
    class_name: str
    prob: float
    top3: list[tuple[str, float]]


class Predictor:
    """Fixed-shape compiled classifier forward over a trained checkpoint.

    ``batch`` is the static compile shape; inputs are padded up / chunked to
    it. Single-device by design (serving a ~5M-param model never needs a
    mesh); the params live wherever ``jax.jit`` places them.
    """

    def __init__(self, params, batch_stats, cfg: Config, batch: int = 32):
        import jax

        from featurenet_tpu.train.loop import build_model

        self.cfg = cfg
        self.batch = batch
        self.model = build_model(cfg)
        self._params = params
        self._stats = batch_stats

        def forward(params, batch_stats, voxels):
            logits = self.model.apply(
                {"params": params, "batch_stats": batch_stats},
                voxels,
                train=False,
            )
            return jax.nn.softmax(logits, axis=-1)

        self._forward = jax.jit(forward)

    # -- constructors -------------------------------------------------------
    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: str,
        config: Config | str = "pod64",
        batch: int = 32,
    ) -> "Predictor":
        """Restore params/batch_stats from an Orbax run directory.

        The optimizer state in the checkpoint is restored (Orbax needs the
        full tree) and immediately dropped — inference keeps weights only.
        """
        import jax

        from featurenet_tpu.train.checkpoint import CheckpointManager
        from featurenet_tpu.train.state import create_state
        from featurenet_tpu.train.loop import build_model
        from featurenet_tpu.train.steps import make_optimizer

        cfg = get_config(config) if isinstance(config, str) else config
        model = build_model(cfg)
        sample = np.zeros(
            (1, cfg.resolution, cfg.resolution, cfg.resolution, 1), np.float32
        )
        state = create_state(
            model, make_optimizer(cfg), sample, jax.random.key(0)
        )
        mgr = CheckpointManager(checkpoint_dir)
        state = mgr.restore(state)
        mgr.close()
        return cls(state.params, state.batch_stats, cfg, batch=batch)

    # -- prediction ---------------------------------------------------------
    def predict_voxels(
        self, grids: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Classify ``[N, R, R, R]`` (or ``[N,R,R,R,1]``) occupancy grids.

        Returns ``(labels int32 [N], probs float32 [N, num_classes])``.
        Inputs are chunked/padded to the static compile batch.
        """
        g = np.asarray(grids, dtype=np.float32)
        if g.ndim == 4:
            g = g[..., None]
        R = self.cfg.resolution
        if g.shape[1:] != (R, R, R, 1):
            raise ValueError(
                f"expected [N,{R},{R},{R}(,1)] grids, got {g.shape}"
            )
        n = g.shape[0]
        if n == 0:
            return (
                np.zeros((0,), np.int32),
                np.zeros((0, len(CLASS_NAMES)), np.float32),
            )
        probs = []
        for s in range(0, n, self.batch):
            chunk = g[s : s + self.batch]
            pad = self.batch - chunk.shape[0]
            if pad:
                chunk = np.concatenate(
                    [chunk, np.zeros((pad,) + chunk.shape[1:], np.float32)]
                )
            p = np.asarray(self._forward(self._params, self._stats, chunk))
            probs.append(p[: self.batch - pad])
        probs = np.concatenate(probs, axis=0)
        return probs.argmax(axis=-1).astype(np.int32), probs

    def predict_stl(
        self, paths: Sequence[str], fill: bool = True
    ) -> list[Prediction]:
        """End-to-end: STL file → normalized voxel grid → class prediction."""
        if not paths:
            return []
        R = self.cfg.resolution
        grids = np.stack(
            [voxelize(load_stl(p), R, fill=fill) for p in paths]
        )
        labels, probs = self.predict_voxels(grids)
        out = []
        for path, lab, pr in zip(paths, labels, probs):
            order = np.argsort(pr)[::-1][:3]
            out.append(
                Prediction(
                    path=path,
                    label=int(lab),
                    class_name=CLASS_NAMES[int(lab)],
                    prob=float(pr[lab]),
                    top3=[(CLASS_NAMES[i], float(pr[i])) for i in order],
                )
            )
        return out
