"""Elastic serving fleet (ROADMAP item 2): N replicas behind one router.

``cli serve`` is one ``InferenceService`` on one host — a single process
crash takes the whole workload down, which is exactly the failure class
the training side already survives (the elastic coordinator). This
package composes the pieces that already exist — batcher admission,
``/healthz`` readiness, the membership file, coordinator-style
heartbeat/exit-code supervision, trace-id propagation — into one
fault-tolerant serving layer:

- ``replica``: the replica manager — N ``cli serve --port 0`` subprocess
  children, each supervised by the shared heartbeat state machine
  (``train.heartbeat``) plus exit-code polling; a dead or wedged replica
  is SIGKILLed and respawned, and rejoins the roster only after its
  ``/healthz`` turns ready (warming from the fleet-shared
  ``--exec-cache-dir``, so rejoin is seconds, not minutes). The roster
  is durably mirrored into ``membership.json`` — the same document
  schema the elastic trainer writes.
- ``router``: the HTTP front end — health-gated least-queue-depth
  routing fed by each replica's ``/healthz``, spillover admission (a
  replica's overload 503 becomes "try the next healthy replica", trace
  id preserved), re-submit-once on replica loss (classification is
  pure, so a re-submitted request is idempotent), priority-lane
  shedding (``batch`` sheds first), fleet-wide 503 + ``Retry-After``
  only when every lane is full, and advisory SLO-driven scaling
  verdicts (``fleet_scale{verdict: add|shed|hold}``) off the rolling
  serving windows.
- ``pool``: the persistent-connection layer — a bounded, health-aware
  keep-alive channel pool (check-out/check-in, max-idle/max-age
  retirement, broken-socket detection with a stale-reuse fresh retry
  that preserves the router's re-submit-once semantics) shared by the
  router's forwards and the manager's ``/healthz`` probes; the one
  module allowed to construct raw HTTP connections (``raw-conn`` lint).
- ``loadgen``: the open-loop HTTP load generator (honors
  ``Retry-After``, keep-alive channel set with ``reconnects`` counted)
  and the bench entry point that pins ``fleet_qps_sustained`` /
  ``fleet_p99_ms`` / ``fleet_requests_dropped`` /
  ``fleet_conn_reuse_ratio`` through a mid-run replica kill.

Launch with ``cli fleet --replicas N --checkpoint-dir D --run-dir R``.
"""

from featurenet_tpu.fleet.replica import (  # noqa: F401
    Candidate,
    ReplicaManager,
)
from featurenet_tpu.fleet.router import (  # noqa: F401
    FleetRouter,
    scale_verdict,
)
