"""Fleet metrics scraper: the collection loop of the telemetry control
plane.

Every serving process already *exports* — each replica's ``GET
/metrics`` and the router's own exporter speak Prometheus text — but
until now nothing scraped them, so no signal survived a process exit.
``MetricsScraper`` is a manager-owned daemon thread that closes that
gap: on a jittered interval it fetches every replica's ``/metrics``
plus the router's own over the SAME persistent connection pool the
data plane uses (one socket per endpoint — a scrape reuses the warm
channel, it never opens a side connection), parses the exposition text,
and appends each sample to the run_dir time-series store
(``obs.tsdb``) labeled with the replica that emitted it.

Contracts, in order of importance:

- **Never load-bearing.** A scrape failure increments a counter and
  becomes a sample in the ``scrape_failures_total`` series — failures
  are themselves telemetry, they never raise into the serving path or
  stop the loop. The store itself degrades dark on disk errors.
- **Closed registry.** Only series whose base name is in the exporters'
  ``serve.metrics.METRIC_NAMES`` registry are written (plus the
  scraper's own ``SCRAPER_SERIES``, registered there too). The analysis
  lint pins this: no unregistered series can appear in the store.
- **Jittered cadence.** Each round sleeps ``interval_s`` ±
  ``jitter_frac`` so N fleets on one box don't thundering-herd their
  replicas at the same instant.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from featurenet_tpu.serve.metrics import METRIC_NAMES, _PREFIX

# The scraper's own series (registered in serve.metrics.METRIC_NAMES):
# per-target failure counter and per-round collection wall — the
# overhead evidence the bench pin reads.
SCRAPER_SERIES = ("scrape_failures_total", "scrape_duration_ms")

DEFAULT_INTERVAL_S = 1.0
DEFAULT_JITTER_FRAC = 0.2
DEFAULT_TIMEOUT_S = 2.0

ROUTER_TARGET = "router"


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Parse Prometheus text exposition 0.0.4 into (name, labels,
    value) triples. Comment/HELP/TYPE lines are skipped; malformed
    lines are skipped too (a scraper must survive a half-written
    response). Shared with the exposition-compliance test, which is the
    strict consumer — here we only need the samples."""
    out: list[tuple[str, dict, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # <name>{k="v",...} <value>  |  <name> <value>
        if "{" in line:
            name, _, rest = line.partition("{")
            body, _, tail = rest.partition("}")
            labels = {}
            ok = True
            for pair in _split_label_pairs(body):
                k, eq, v = pair.partition("=")
                if not eq or len(v) < 2 or v[0] != '"' or v[-1] != '"':
                    ok = False
                    break
                labels[k.strip()] = _unescape(v[1:-1])
            if not ok:
                continue
            value_str = tail.strip()
        else:
            name, _, value_str = line.partition(" ")
            labels = {}
        name = name.strip()
        parts = value_str.split()
        if not name or not parts:
            continue
        try:
            value = float(parts[0])  # parts[1], if any, is a timestamp
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def _split_label_pairs(body: str) -> list[str]:
    """Split ``k="v",k2="v2"`` on commas OUTSIDE quotes."""
    pairs, cur, in_q, esc = [], [], False, False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\" and in_q:
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            pairs.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        pairs.append("".join(cur))
    return [p for p in (p.strip() for p in pairs) if p]


def _unescape(v: str) -> str:
    """One left-to-right pass over the exposition escapes (``\\\\``,
    ``\\"``, ``\\n``). Sequential ``str.replace`` calls would corrupt
    values where an escaped backslash precedes an ``n`` or a quote —
    ``a\\\\nb`` (backslash then letter n) must round-trip as-is, not
    collapse into a newline."""
    out = []
    i = 0
    while i < len(v):
        ch = v[i]
        if ch == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt in ('\\', '"'):
                out.append(nxt)
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


class MetricsScraper:
    """The manager-owned collection thread.

    ``targets()`` must return ``{target_label: port}`` — the manager's
    live replica ports keyed by slot, plus the router's own exporter
    under ``ROUTER_TARGET``. Recomputed every round, so replicas that
    die or rejoin fall out of / into collection automatically.
    """

    def __init__(self, store, pool, targets: Callable[[], dict], *,
                 host: str = "127.0.0.1",
                 interval_s: float = DEFAULT_INTERVAL_S,
                 jitter_frac: float = DEFAULT_JITTER_FRAC,
                 timeout_s: float = DEFAULT_TIMEOUT_S,
                 registry: frozenset = METRIC_NAMES):
        self.store = store
        self.pool = pool
        self.targets = targets
        self.host = host
        self.interval_s = float(interval_s)
        self.jitter_frac = float(jitter_frac)
        self.timeout_s = float(timeout_s)
        self.registry = registry
        self.rounds = 0
        self.samples = 0
        self.skipped = 0          # unregistered series (lint's backstop)
        self.failures: dict[str, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._paused = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="fleet-scraper", daemon=True
        )
        self._thread.start()

    def stop(self, final_round: bool = True) -> None:
        """Stop the loop; by default take one last synchronous round so
        the store's tail reflects the fleet's final state."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.timeout_s + self.interval_s * 2)
            self._thread = None
        if final_round:
            self.scrape_once()

    def pause(self, on: bool = True) -> None:
        """Suspend collection without tearing down the thread — the
        bench harness uses this to measure serving qps with and without
        the scraper on the same warm fleet."""
        self._paused = on

    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._paused:
                self.scrape_once()
            lo = self.interval_s * (1.0 - self.jitter_frac)
            hi = self.interval_s * (1.0 + self.jitter_frac)
            self._stop.wait(random.uniform(lo, hi))

    # -- one collection round ------------------------------------------------
    def scrape_once(self) -> int:
        """Scrape every current target once; returns samples appended.
        Failures never escape: each becomes a bump of that target's
        failure counter and a sample in ``scrape_failures_total``."""
        try:
            targets = dict(self.targets())
        except Exception:
            targets = {}
        appended = 0
        for target, port in sorted(targets.items()):
            appended += self._scrape_target(str(target), port)
        self.rounds += 1
        return appended

    def _scrape_target(self, target: str, port: int) -> int:
        t0 = time.perf_counter()
        now = time.time()
        try:
            status, body = self.pool.get(
                self.host, int(port), "/metrics", timeout_s=self.timeout_s
            )
            if status != 200:
                raise OSError(f"/metrics -> {status}")
            text = body.decode("utf-8", "replace")
        except Exception:
            n = self.failures.get(target, 0) + 1
            self.failures[target] = n
            # The failure IS a series: a dashboard sees collection gaps
            # as data, not as absence.
            self.store.append("scrape_failures_total", n,
                              {"replica": target}, t=now)
            return 0
        appended = 0
        samples = parse_exposition(text)
        # The target's model_version (its build_info labels) becomes a
        # ``version`` label on every series scraped THIS round from this
        # target — during a rolling weight swap the store shows the
        # mixed-version window per replica, and a dashboard can split
        # any latency series by deploy. The router exports "n/a" (it
        # owns no checkpoint); that is not a version, so no label.
        version = None
        for name, labels, _value in samples:
            base = name[len(_PREFIX):] if name.startswith(_PREFIX) else name
            if base == "build_info":
                v = labels.get("model_version")
                if v and v != "n/a":
                    version = v
                break
        for name, labels, value in samples:
            base = name[len(_PREFIX):] if name.startswith(_PREFIX) else name
            if base not in self.registry:
                self.skipped += 1
                continue
            labels = dict(labels)
            labels["replica"] = target
            if version is not None:
                labels["version"] = version
            if self.store.append(base, value, labels, t=now):
                appended += 1
        dur_ms = (time.perf_counter() - t0) * 1e3
        self.store.append("scrape_duration_ms", dur_ms,
                          {"replica": target}, t=now)
        self.samples += appended
        return appended

    def stats(self) -> dict:
        return {
            "rounds": self.rounds,
            "samples": self.samples,
            "skipped": self.skipped,
            "failures": dict(self.failures),
            "paused": self._paused,
        }
