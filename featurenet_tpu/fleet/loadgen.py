"""Open-loop HTTP load generation for the fleet, and the bench row.

``serve.loadgen`` drives one in-process service; the fleet's contract is
an HTTP boundary, so this generator speaks the wire: Poisson arrivals
POSTed to the router's ``/predict_voxels`` (raw float32 grid bytes — no
per-request geometry work, so the generator measures the serving path,
not the client's voxelizer), each with a minted trace id and a priority
lane header. A 503 carrying ``Retry-After`` is honored ONCE (sleep the
hinted backoff, retry) before counting as a rejection — the polite-
client half of the admission contract.

The generator keeps a small keep-alive connection set (``fleet.pool``)
instead of reconnecting per request — the client half of the persistent
data plane. ``reconnects`` in the stats counts fresh connects beyond the
working set the thread-pool concurrency needed anyway, so client-side
channel churn (retirements, broken sockets) is visible in the bench row
rather than hiding inside the latency numbers.

``bench_fleet`` is the bench.py entry point: a 2-replica CPU fleet
(replicas forced onto ``JAX_PLATFORMS=cpu`` — the row pins the ROUTER
layer's robustness, deliberately independent of accelerator health),
open-loop load with one replica SIGKILLed mid-run, returning the pinned
``fleet_qps_sustained`` / ``fleet_p99_ms`` / ``fleet_requests_dropped``
fields — the last with a baseline of 0: the fleet's whole promise is
that admitted work survives replica loss — plus ``fleet_conn_reuse_ratio``
(router-side channel reuse over the whole run, pinned min: the pooling
payoff must not silently rot back to connect-per-request).
"""

from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Optional

import numpy as np

from featurenet_tpu.obs import tracing as _tracing
from featurenet_tpu.obs.report import _pct
from featurenet_tpu.obs.tracing import TRACE_HEADER
from featurenet_tpu.fleet.pool import ConnectionPool
from featurenet_tpu.serve.http import PRIORITY_HEADER


def _post(pool: ConnectionPool, host: str, port: int, path: str,
          body: bytes, lane: str,
          timeout_s: float) -> tuple[int, dict, Optional[float]]:
    """One pooled POST; returns (status, parsed body, Retry-After
    seconds). Connection-level failures raise OSError/HTTPException
    upward. Rides ``fleet.pool`` — the one hop implementation for the
    whole fleet package, client side included."""
    status, raw, ra = pool.post(host, port, path, body, {
        TRACE_HEADER: _tracing.mint_trace_id(),
        PRIORITY_HEADER: lane,
    }, timeout_s)
    try:
        doc = json.loads(raw.decode("utf-8"))
    except ValueError:
        doc = {}
    return status, doc, ra


def http_load(host: str, port: int, qps: float, n_requests: int,
              grids: np.ndarray, lane: str = "interactive",
              rng: Optional[np.random.Generator] = None,
              timeout_s: float = 60.0,
              honor_retry_after: bool = True,
              max_workers: int = 32) -> tuple[dict, list]:
    """Drive the router at ``host:port`` with ``n_requests`` Poisson
    arrivals at rate ``qps``; returns ``(stats, outcomes)`` where
    ``outcomes[i]`` records request i's final status, client latency,
    and label. Open-loop: arrivals are pre-scheduled; a slow fleet is
    submitted to late but never slower. Every request runs on a worker
    thread (the HTTP POST blocks for the full serving latency — the
    thread pool is the client's concurrency, not the load's clock) and
    rides a keep-alive channel set sized to the worker pool, so the
    client pays ~max_workers handshakes for the whole run instead of
    one per request; ``reconnects`` in the stats is the churn beyond
    that working set."""
    from concurrent.futures import ThreadPoolExecutor

    if qps <= 0:
        raise ValueError(f"qps must be > 0, got {qps}")
    if rng is None:
        rng = np.random.default_rng(0)
    # The client's keep-alive connection set: one idle slot per worker
    # thread (the natural concurrency bound), generous max-age — the
    # run IS the channel's useful lifetime.
    pool = ConnectionPool(max_idle_per_endpoint=max_workers,
                          max_age_s=600.0, timeout_s=timeout_s)
    payloads = [
        # lint: allow-host-sync(client-side wire encoding of host arrays)
        np.ascontiguousarray(
            g.reshape(g.shape[:3]), dtype="<f4"
        ).tobytes()
        for g in grids
    ]
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_requests))
    outcomes: list[Optional[dict]] = [None] * n_requests

    def one(i: int) -> None:
        t_submit = time.perf_counter()
        body = payloads[i % len(payloads)]
        try:
            status, doc, ra = _post(pool, host, port, "/predict_voxels",
                                    body, lane, timeout_s)
            retried = False
            if status == 503 and honor_retry_after and ra:
                # The polite client: the server said when to come back.
                time.sleep(ra)
                retried = True
                # Restamp the latency clock: the backoff sleep is
                # server-DIRECTED waiting, not serving latency — folding
                # it into latency_ms would swing the gate-pinned
                # fleet_p99_ms by the whole Retry-After on every round
                # whose kill lands slightly differently.
                t_submit = time.perf_counter()
                status, doc, ra = _post(pool, host, port,
                                        "/predict_voxels",
                                        body, lane, timeout_s)
        except (OSError, http.client.HTTPException) as e:
            outcomes[i] = {"status": None, "error": str(e)}
            return
        outcomes[i] = {
            "status": status,
            "latency_ms": (time.perf_counter() - t_submit) * 1e3,
            "label": doc.get("label"),
            "retried": retried,
            "body": doc,
        }

    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=max_workers) as workers:
        futs = []
        for i in range(n_requests):
            ahead = arrivals[i] - (time.perf_counter() - t0)
            if ahead > 0:
                time.sleep(ahead)
            futs.append(workers.submit(one, i))
        for f in futs:
            f.result()
    wall = time.perf_counter() - t0
    conn = pool.stats()
    pool.close()
    done = [o for o in outcomes if o is not None]
    ok = [o for o in done if o.get("status") == 200]
    rejected = sum(1 for o in done if o.get("status") == 503)
    # A drop is a request the fleet LOST: any 5xx that is not a clean
    # 503 rejection (502 = re-submit exhausted, 500 = forward error,
    # 504 = admitted but unanswered) or a connection death against the
    # router itself (status None).
    dropped = sum(
        1 for o in done
        if o.get("status") is None
        or (o["status"] >= 500 and o["status"] != 503)
    )
    lats = sorted(o["latency_ms"] for o in ok)
    stats = {
        "offered_qps": round(n_requests / float(arrivals[-1]), 1),
        "sustained_qps": round(len(ok) / wall, 1) if wall > 0 else None,
        "answered": len(ok),
        "rejected": rejected,
        "dropped": dropped,
        "retried": sum(1 for o in done if o.get("retried")),
        "p50_ms": round(_pct(lats, 50), 3) if lats else None,
        "p99_ms": round(_pct(lats, 99), 3) if lats else None,
        # Client-side channel churn: handshakes paid for the whole run
        # (≈ the worker-pool concurrency when pooling works) and the
        # reconnects beyond that working set (retired/broken channels).
        "connects": conn["opened"],
        "conn_reuses": conn["reused"],
        "reconnects": conn["reconnects"],
    }
    return stats, outcomes


def replica_argv(ckpt_dir: str, slot: int, heartbeat_file: str, *,
                 run_dir: Optional[str] = None,
                 exec_cache_dir: Optional[str] = None,
                 buckets: str = "1,4", max_wait_ms: float = 5.0,
                 queue_limit: int = 64,
                 slo_p99_ms: float = 250.0,
                 precision: Optional[str] = None,
                 inject_faults: Optional[str] = None,
                 trace_sample: Optional[float] = None,
                 quality: bool = False,
                 quality_baseline: Optional[str] = None,
                 capture: bool = False,
                 capture_sample: Optional[float] = None) -> list:
    """One replica's spawn argv (shared by ``cli fleet`` and
    ``bench_fleet`` so the two can never drift on the child contract):
    ``cli serve --port 0`` with the fleet identity flags — replica id,
    per-slot heartbeat file, per-slot event stream (``--process-index
    slot+1``; the router owns stream 0). Capture rings are per-slot
    (``<run_dir>/capture/replica<slot>``): the recorder's segment
    arithmetic assumes one writer process per directory."""
    argv = [
        sys.executable, "-m", "featurenet_tpu.cli", "serve",
        "--checkpoint-dir", ckpt_dir, "--port", "0",
        "--buckets", buckets, "--max-wait-ms", str(max_wait_ms),
        "--queue-limit", str(queue_limit),
        "--slo-p99-ms", str(slo_p99_ms),
        "--replica-id", str(slot),
        "--heartbeat-file", heartbeat_file,
        "--process-index", str(slot + 1),
    ]
    if run_dir:
        argv += ["--run-dir", run_dir]
    if exec_cache_dir:
        argv += ["--exec-cache-dir", exec_cache_dir]
    if precision:
        argv += ["--precision", precision]
    if inject_faults:
        argv += ["--inject-faults", inject_faults]
    if trace_sample is not None:
        argv += ["--trace-sample", str(trace_sample)]
    if quality or quality_baseline:
        argv += ["--quality"]
    if quality_baseline:
        argv += ["--quality-baseline", quality_baseline]
    if capture and run_dir:
        argv += ["--capture-dir",
                 os.path.join(run_dir, "capture", f"replica{slot}")]
        if capture_sample is not None:
            argv += ["--capture-sample", str(capture_sample)]
    return argv


def _train_tiny_checkpoint(ckpt_dir: str, env: dict) -> None:
    """A 2-step smoke16 checkpoint in a CPU subprocess (the bench parent
    may own an accelerator; this row must not touch it)."""
    code = (
        "from featurenet_tpu.config import get_config\n"
        "from featurenet_tpu.train.loop import Trainer\n"
        "cfg = get_config('smoke16', total_steps=2, checkpoint_every=2,"
        " eval_every=10**9, log_every=2, data_workers=1,"
        f" checkpoint_dir={ckpt_dir!r})\n"
        "Trainer(cfg).run()\n"
    )
    subprocess.run([sys.executable, "-c", code], env=env, check=True,
                   capture_output=True, timeout=600)


def bench_fleet(replicas: int = 2, qps: float = 60.0,
                n_requests: int = 240,
                ckpt_dir: Optional[str] = None,
                kill_after_fraction: float = 0.33,
                buckets: str = "1,4",
                queue_limit: int = 64) -> dict:
    """The bench.py fleet row: an N-replica CPU fleet under open-loop
    load with one replica SIGKILLed a third of the way in. Returns the
    flat ``fleet_*`` fields the gate pins — sustained QPS and p99 must
    hold THROUGH the loss, and dropped must be zero."""
    from featurenet_tpu.data.synthetic import generate_batch
    from featurenet_tpu.fleet.replica import Autoscaler, ReplicaManager
    from featurenet_tpu.fleet.router import FleetRouter
    from featurenet_tpu.fleet.scraper import ROUTER_TARGET, MetricsScraper
    from featurenet_tpu.obs import tsdb as _tsdb

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    tmp = tempfile.mkdtemp(prefix="fleet_bench_")
    run_dir = os.path.join(tmp, "run")
    cache_dir = os.path.join(tmp, "exec_cache")
    own_ckpt = ckpt_dir is None
    if own_ckpt:
        ckpt_dir = os.path.join(tmp, "ckpt")
        _train_tiny_checkpoint(ckpt_dir, env)

    def spawn(slot, hb):
        return replica_argv(
            ckpt_dir, slot, hb, run_dir=run_dir,
            exec_cache_dir=cache_dir, buckets=buckets,
            queue_limit=queue_limit,
            # Full-rate capture rings: the self-rollout below replays a
            # replica's ring against the SAME checkpoint, so the
            # rollout_agreement pin has real captured traffic to score.
            capture=True, capture_sample=1.0,
        )

    manager = ReplicaManager(replicas, spawn, run_dir, env=env)
    store = _tsdb.TimeSeriesStore.open(run_dir)
    router = FleetRouter(manager, rules=(), store=store)
    scraper = None
    srv = None
    autoscaler = None
    try:
        manager.start()
        deadline = time.monotonic() + 300
        while manager.ready_count() < replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet warmup timed out: {manager.stats()}"
                )
            time.sleep(0.25)
        srv = router.make_server("127.0.0.1", 0)
        port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        # The telemetry plane, exactly as cli fleet wires it: the
        # scraper collects every replica + the router into the run_dir
        # store over the manager's own pool, aggressively (the bench
        # must measure collection UNDER load, not a quiet fleet).
        scraper = MetricsScraper(
            store, manager.pool,
            lambda: {
                **{str(s): p
                   for s, p in manager.stats()["ports"].items()},
                ROUTER_TARGET: port,
            },
            interval_s=0.25,
        )
        scraper.start()
        # The ACTING control loop rides the bench fleet exactly as
        # `cli fleet --autoscale` wires it. Under handled load the burn
        # verdicts hold, so fleet_scale_actions is pinned ~0 (abs slack
        # 1): a regression here means the damping gates rotted and the
        # roster thrashes under flat load.
        autoscaler = Autoscaler(
            manager, router.scale_state,
            min_replicas=1, max_replicas=replicas + 1,
        )
        autoscaler.start()
        grids = generate_batch(np.random.default_rng(0), 16, 16)["voxels"]
        kill_at = max(1, int(n_requests * kill_after_fraction))
        done = threading.Event()

        def killer():
            # The mid-run loss: SIGKILL one live replica once the router
            # has seen a third of the load (the fault-injection site
            # drives the same arm from a spec; bench owns its own timing
            # so a round is never hostage to spec plumbing).
            while not done.is_set():
                if router.stats()["routed"] >= kill_at:
                    manager.kill_one()
                    return
                time.sleep(0.05)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        stats, _ = http_load("127.0.0.1", port, qps, n_requests, grids)
        done.set()
        kt.join(timeout=1.0)
        # Collection-tax A/B on the SAME warm fleet: a short open-loop
        # burst with the scraper paused, then one with it collecting at
        # its aggressive bench cadence. The pinned pct is the qps the
        # serving path loses to collection — "never load-bearing" as a
        # measured property (clamped at 0: a faster-with-scraper draw
        # is noise, not negative overhead).
        burst_n = max(40, n_requests // 4)
        scraper.pause(True)
        off, _ = http_load("127.0.0.1", port, qps, burst_n, grids)
        scraper.pause(False)
        on, _ = http_load("127.0.0.1", port, qps, burst_n, grids)
        qps_off = off["sustained_qps"] or 0.0
        qps_on = on["sustained_qps"] or 0.0
        scrape_overhead_pct = (
            max(0.0, (qps_off - qps_on) / qps_off * 100.0)
            if qps_off > 0 else 0.0
        )
        # Burn-verdict decision latency: one store-backed burn query +
        # verdict per call, best of a few (the autoscaler's read path).
        t_best = None
        for _ in range(5):
            t0 = time.perf_counter()
            router.scale_state()
            dt = (time.perf_counter() - t0) * 1e3
            t_best = dt if t_best is None else min(t_best, dt)
        autoscaler.stop()
        # The self-rollout pins, on the still-live fleet: hot-swap one
        # replica to the SAME checkpoint (the swap wall with zero model
        # delta — pure restore/cast/flip cost) and replay its capture
        # ring against that checkpoint in a CPU subprocess (agreement
        # pinned min ≈ 1.0: a model re-scoring its own recorded traffic
        # must agree with itself).
        rollout_swap_ms = None
        rollout_agreement = None
        ready_ports = {
            s: p for s, p in manager.stats()["ports"].items()
        }
        if ready_ports:
            slot = sorted(ready_ports)[0]
            try:
                st_code, raw, _ra = manager.pool.post(
                    "127.0.0.1", ready_ports[slot], "/admin/reload",
                    json.dumps({"checkpoint_dir": ckpt_dir}).encode(),
                    {"Content-Type": "application/json"}, 120.0,
                )
                if st_code == 200:
                    rollout_swap_ms = json.loads(
                        raw.decode("utf-8")
                    ).get("swap_ms")
            except (OSError, http.client.HTTPException):
                pass  # degrade to an absent key, like the other probes
            ring = os.path.join(run_dir, "capture", f"replica{slot}")
            if os.path.isdir(ring):
                rp = subprocess.run(
                    [sys.executable, "-m", "featurenet_tpu.cli",
                     "replay", ring, "--checkpoint-dir", ckpt_dir,
                     "--batch", "16"],
                    env=env, capture_output=True, timeout=600,
                )
                for line in rp.stdout.decode(
                        "utf-8", "replace").splitlines():
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue
                    if "replay" in doc:
                        rollout_agreement = doc["replay"]["agreement"]
        scraper.stop()
        st = router.drain()
        return {
            "fleet_replicas": replicas,
            "fleet_qps_offered": stats["offered_qps"],
            "fleet_qps_sustained": stats["sustained_qps"],
            "fleet_p50_ms": stats["p50_ms"],
            "fleet_p99_ms": stats["p99_ms"],
            "fleet_requests_dropped": stats["dropped"],
            "fleet_requests_rejected": stats["rejected"],
            "fleet_spillovers": st["spillovers"],
            "fleet_resubmits": st["resubmits"],
            "fleet_losses": st["replicas"]["losses"],
            "fleet_rejoins": st["replicas"]["rejoins"],
            "fleet_requests": n_requests,
            # The pooled-path evidence, measured THROUGH the kill:
            # router-side channel reuse over the whole run (pinned min —
            # connect-per-request would read ~0), the churn breakdown,
            # and the client generator's own reconnect count.
            "fleet_conn_reuse_ratio": st["pool"]["reuse_ratio"],
            "fleet_conns_opened": st["pool"]["opened"],
            "fleet_conns_retired": sum(st["pool"]["retired"].values()),
            "fleet_client_reconnects": stats["reconnects"],
            # The telemetry control plane's own pins: collection tax on
            # the serving path and the burn-verdict decision latency,
            # plus (unpinned) how much the store actually collected.
            "scrape_overhead_pct": round(scrape_overhead_pct, 2),
            "fleet_burn_verdict_ms": round(t_best, 3),
            "fleet_scrape_samples": scraper.samples,
            "fleet_scrape_rounds": scraper.rounds,
            # The acting control loop + rollout pins: scale actions
            # under handled load (expected 0 — the damping gates), the
            # live hot-swap wall, and the self-replay agreement.
            "fleet_scale_actions": autoscaler.actions,
            **({"rollout_swap_ms": rollout_swap_ms}
               if rollout_swap_ms is not None else {}),
            **({"rollout_agreement": rollout_agreement}
               if rollout_agreement is not None else {}),
        }
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        if scraper is not None:
            scraper.pause(True)
            scraper.stop(final_round=False)
        if srv is not None:
            srv.shutdown()
        manager.stop()
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
