"""The replica manager: N supervised ``cli serve`` children + the roster.

One replica is one ``cli serve --port 0`` subprocess. The manager owns
their whole lifecycle with the coordinator's supervision shape
(``elastic.coordinator``), applied to serving:

- **Spawn**: each child binds an ephemeral port and prints its serving
  banner (``{"serving": {..., "port": P}}``) to a per-replica stdout
  file in the run dir; the manager tails that file to learn the port.
  A child that never banners within ``spawn_timeout_s`` is killed and
  charged as a startup loss.
- **Liveness**: exit-code polling (a dead process) plus the shared
  heartbeat state machine (``train.heartbeat.HeartbeatMonitor`` — the
  same grace/stall/re-read protocol the train supervisor and the
  elastic coordinator drive; the serve child touches its heartbeat file
  once a second while ready). A wedged replica — process alive, HTTP
  hung — stops beating and is SIGKILLed like a stalled trainer.
- **Readiness**: the manager probes each replica's ``/healthz`` every
  poll; a replica routes traffic only while its probe answers 200
  (ready), and the probed ``queue_depth`` feeds the router's
  least-queue-depth pick. Probes ride the manager's connection pool
  (``fleet.pool`` — the same pool the router forwards on), so a poll
  cycle reuses a warm channel instead of opening a socket; a probe
  FAILURE retires that endpoint's pooled channels immediately, so the
  next forward starts on a fresh connection instead of discovering the
  corpse itself. Loss → respawn (with crash-loop backoff) →
  the respawned child warms its bucket ladder from the fleet-shared
  exec cache → rejoins the roster ONLY when ``/healthz`` turns ready.
- **Roster**: every ready/loss transition rewrites ``membership.json``
  (``elastic.membership`` — the exact schema the elastic trainer
  writes: generation counter, member slots, reason) and emits
  ``fleet_replica_ready`` / ``fleet_replica_loss`` events, so the
  report's fleet section can render the roster timeline next to the
  request stream.

Stdlib-only by contract, like the elastic coordinator: the manager
process supervises N backend-owning children and must never initialize
a device itself.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import threading
import time
from typing import Callable, Optional

from featurenet_tpu import faults, obs
from featurenet_tpu.elastic.coordinator import heartbeat_path
from featurenet_tpu.elastic.membership import Membership, write_membership
from featurenet_tpu.fleet.pool import ConnectionPool
from featurenet_tpu.train.heartbeat import HeartbeatMonitor
from featurenet_tpu.train.supervisor import _kill_tree

DEFAULT_POLL_S = 0.25
DEFAULT_GRACE_S = 300.0        # warmup allowance: a cold cache compiles
DEFAULT_STALL_TIMEOUT_S = 30.0
DEFAULT_SPAWN_TIMEOUT_S = 300.0


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One routable replica as the router sees it: where to connect and
    how loaded it looked at the last probe (``score`` = probed queue
    depth + the router's own in-flight count — the freshest cheap
    estimate of who answers soonest)."""

    slot: int
    host: str
    port: int
    score: int


class _Replica:
    """One slot's live state (manager-internal; guarded by the manager
    lock for the fields router threads touch)."""

    def __init__(self, slot: int):
        self.slot = slot
        self.proc: Optional[subprocess.Popen] = None
        self.out_path: Optional[str] = None
        self.out_offset = 0
        self.port: Optional[int] = None
        self.ready = False
        self.queue_depth = 0
        self.inflight = 0
        self.spawned_t = 0.0
        self.respawn_due = 0.0
        self.failures = 0  # consecutive, for backoff
        self.was_lost = False  # a later ready is a REJOIN
        self.mon: Optional[HeartbeatMonitor] = None
        self.probe_inflight = False  # one outstanding probe at a time
        # A deliberately shed slot (autoscale scale-down): the tick loop
        # neither respawns it nor charges its exit as a loss — shed_one
        # owns its teardown, add_one may later unpark it.
        self.parked = False
        # The model_version the last successful probe reported (the
        # /healthz tag) — the roster's per-replica deploy identity.
        self.model_version: Optional[str] = None


class ReplicaManager:
    """Spawn and supervise ``n`` serving replicas; provide the router's
    health-gated candidate view.

    ``spawn(slot, heartbeat_file) -> argv`` builds one replica's command
    (the ``cli fleet`` launcher passes through the serve flags plus
    ``--port 0 --replica-id <slot> --process-index <slot+1>``). The
    child must print the serve banner on stdout and touch
    ``heartbeat_file`` while ready.
    """

    def __init__(self, n: int, spawn: Callable[[int, str], list],
                 run_dir: str, *,
                 host: str = "127.0.0.1",
                 poll_s: float = DEFAULT_POLL_S,
                 grace_s: float = DEFAULT_GRACE_S,
                 stall_timeout_s: float = DEFAULT_STALL_TIMEOUT_S,
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 max_respawns: int = 16,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 10.0,
                 probe_timeout_s: float = 2.0,
                 env: Optional[dict] = None,
                 pool: Optional[ConnectionPool] = None):
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        self.n = n
        self.spawn = spawn
        self.run_dir = os.path.abspath(run_dir)
        self.host = host
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.stall_timeout_s = stall_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.max_respawns = max_respawns
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.probe_timeout_s = probe_timeout_s
        self.env = env
        # The fleet's one channel pool: probes ride it here, forwards
        # ride it in the router (FleetRouter adopts the provider's pool
        # via this attribute), so health verdicts and traffic share the
        # same view of which channels are alive.
        self.pool = pool or ConnectionPool()
        self._lock = threading.Lock()
        self._replicas = {slot: _Replica(slot) for slot in range(n)}
        self._spawns = 0
        self._losses = 0
        self._rejoins = 0
        self._generation = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        for r in self._replicas.values():
            self._spawn(r)
        self._thread = threading.Thread(
            target=self._run, name="fleet-replicas", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 20.0) -> None:
        """SIGTERM every child (a serving child drains on SIGTERM), wait
        briefly, SIGKILL stragglers, stop the supervision thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, 4 * self.poll_s))
        procs = [r.proc for r in self._replicas.values()
                 if r.proc is not None and r.proc.poll() is None]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        for p in procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                _kill_tree(p)
        self.pool.close()

    # -- spawn / supervision --------------------------------------------------
    def _spawn(self, r: _Replica) -> None:
        # The tick thread (respawn path) and the autoscaler thread
        # (add_one) both reach here: the counter increment is a
        # read-modify-write, so take the lock and capture the sequence
        # number it produced for the fault-site key below.
        with self._lock:
            self._spawns += 1
            spawn_seq = self._spawns
        hb = heartbeat_path(self.run_dir, r.slot)
        r.mon = HeartbeatMonitor(hb, self.stall_timeout_s, self.grace_s)
        r.mon.reset()
        argv = list(self.spawn(r.slot, hb))
        if faults.maybe_fail("spawn_fail", spawn=spawn_seq):
            import sys

            argv = [sys.executable, "-c", "raise SystemExit(13)"]
        r.out_path = os.path.join(self.run_dir, f"replica.{r.slot}.out")
        r.out_offset = 0
        # Truncate-and-redirect: the banner tail below must find THIS
        # spawn's banner, not a previous incarnation's.
        fh = open(r.out_path, "wb")
        try:
            r.proc = subprocess.Popen(
                argv, stdout=fh, stderr=subprocess.STDOUT,
                start_new_session=True, env=self.env,
            )
        finally:
            fh.close()
        r.port = None
        r.ready = False
        r.queue_depth = 0
        r.spawned_t = time.monotonic()
        obs.emit("supervisor", phase="spawn", host=r.slot,
                 pid=r.proc.pid, generation=self._generation)

    def _scan_banner(self, r: _Replica) -> Optional[int]:
        """The child's bound port from its stdout file (``--port 0``
        binds an ephemeral port only the child knows)."""
        try:
            with open(r.out_path, "rb") as fh:
                fh.seek(r.out_offset)
                chunk = fh.read()
        except OSError:
            return None
        # Only complete lines advance the offset; a torn tail is re-read
        # whole on the next poll.
        end = chunk.rfind(b"\n")
        if end < 0:
            return None
        r.out_offset += end + 1
        for line in chunk[:end].splitlines():
            try:
                doc = json.loads(line.decode("utf-8", "replace"))
                if isinstance(doc, dict) and "serving" in doc:
                    return int(doc["serving"]["port"])
            except (ValueError, KeyError, TypeError):
                continue  # not this child's banner; keep scanning
        return None

    def _probe(self, port: int) -> Optional[dict]:
        """One pooled ``/healthz`` probe: the parsed body on HTTP 200,
        None on anything else (503 warming/draining, connection refused,
        hung socket) — "not routable right now", with the kill decision
        left to the heartbeat/exit machinery. Rides the shared channel
        pool, so steady-state polling costs zero handshakes. Takes the
        port the caller CAPTURED (not ``r.port``, which the tick thread
        nulls on loss while a probe is in flight).

        Retirement discipline: only a CONNECTION-level failure retires
        the endpoint's pooled channels (the corpse-socket signal). A
        clean non-200 — a warming or draining replica answering 503 —
        arrived over a perfectly healthy channel; retiring it would be
        one handshake per poll cycle for the whole warmup, exactly the
        churn the pool exists to remove."""
        import http.client

        try:
            status, body = self.pool.get(
                self.host, port, "/healthz",
                timeout_s=self.probe_timeout_s,
            )
            if status != 200:
                return None
            return json.loads(body.decode("utf-8"))
        except (OSError, http.client.HTTPException, ValueError):
            self.pool.retire_endpoint(self.host, port, "probe_failure")
            return None

    def _lose(self, r: _Replica, reason: str) -> None:
        if r.proc is not None and r.proc.poll() is None:
            _kill_tree(r.proc)
        was_ready = r.ready
        port = r.port
        # Loss bookkeeping under the lock: stats()/candidates() read
        # failures/_losses from router and autoscaler threads, and the
        # backoff computation must see the failure count IT incremented.
        # The pool retire stays outside — the pool has its own lock and
        # this keeps the lock-order graph acyclic.
        with self._lock:
            r.proc = None
            r.port = None
            r.ready = False
            r.was_lost = True
            r.failures += 1
            self._losses += 1
            delay = min(self.backoff_cap_s,
                        self.backoff_base_s * (2 ** (r.failures - 1)))
            r.respawn_due = time.monotonic() + delay
        if port is not None:
            # A lost replica's channels are corpse sockets: retire them
            # NOW so no forward (or probe) inherits one.
            self.pool.retire_endpoint(self.host, port, "replica_loss")
        obs.emit("fleet_replica_loss", replica=r.slot, reason=reason)
        if was_ready:
            self._write_roster("replica_loss")

    def _tick(self) -> None:
        now = time.monotonic()
        for r in list(self._replicas.values()):
            if r.parked:
                # A shed slot: no respawn, no loss-charging — shed_one
                # owns its teardown and add_one its revival.
                continue
            if r.proc is None:
                if now >= r.respawn_due and self._spawns - self.n \
                        < self.max_respawns:
                    self._spawn(r)
                continue
            rc = r.proc.poll()
            if rc is not None:
                self._lose(r, f"exit_{rc}")
                continue
            if r.port is None:
                port = self._scan_banner(r)
                if port is not None:
                    r.port = port
                elif now - r.spawned_t > self.spawn_timeout_s:
                    self._lose(r, "startup_timeout")
                continue
            if r.mon is not None and r.mon.poll() == "stall":
                # Process alive, heartbeat stale: a wedged replica (hung
                # forward, stuck HTTP) — nothing softer than SIGKILL is
                # guaranteed to land, same as a wedged mesh member.
                self._lose(r, "stall")
                continue
            # Probe OFF the tick thread (one outstanding per replica):
            # a wedged replica's probe blocks for the full probe
            # timeout, and paying that serially here would delay loss
            # detection and respawns for the whole fleet.
            with self._lock:
                launch = not r.probe_inflight
                r.probe_inflight = launch
            if launch:
                # lint: allow-thread-leak(bounded to one in-flight per replica by the probe_inflight gate above; self-terminating after one probe round-trip, daemon so a wedged probe cannot block interpreter exit)
                threading.Thread(
                    target=self._probe_update, args=(r,),
                    name=f"fleet-probe-{r.slot}", daemon=True,
                ).start()

    def _probe_update(self, r: _Replica) -> None:
        """One /healthz probe + state fold, on its own thread."""
        try:
            port = r.port
            if port is None or r.proc is None:
                return
            health = self._probe(port)
            if health is None:
                # Not routable (warming, draining, or a probe failure):
                # gate it out of the candidate set but leave the kill
                # verdict to the heartbeat — probing through one dropped
                # packet must not cost a respawn. (_probe itself retires
                # the endpoint's channels when the failure was
                # connection-level — the earliest stale-channel signal —
                # and leaves them pooled on a clean warming/draining
                # 503.)
                with self._lock:
                    r.ready = False
                return
            with self._lock:
                if r.port != port:  # lost/respawned while we probed
                    return
                r.queue_depth = int(health.get("queue_depth") or 0)
                version = health.get("model_version")
                if isinstance(version, str):
                    r.model_version = version
                first_ready = not r.ready
                r.ready = True
                if first_ready:
                    r.failures = 0
                    if r.was_lost:
                        self._rejoins += 1
            if first_ready:
                obs.emit("fleet_replica_ready", replica=r.slot,
                         port=r.port, model_version=r.model_version)
                self._write_roster(
                    "replica_rejoin" if r.was_lost else "start"
                )
        finally:
            with self._lock:
                r.probe_inflight = False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # supervision must outlive everything
                # One bad spawn (fd exhaustion, ENOMEM — exactly
                # incident conditions) must not silently kill the one
                # thread whose job is respawning: log and keep polling.
                obs.warn("fleet_tick_error", repr(e)[:300])
            self._stop.wait(self.poll_s)

    def _write_roster(self, reason: str) -> None:
        """Mirror the ready set into ``membership.json`` — the elastic
        trainer's document schema, reused as the fleet roster (an
        operator mid-incident reads one file either way)."""
        with self._lock:
            members = tuple(sorted(
                r.slot for r in self._replicas.values() if r.ready
            ))
            self._generation += 1
            generation = self._generation
        write_membership(self.run_dir, Membership(
            generation=generation,
            members=members,
            min_world_size=1,
            reason=reason,
        ))

    # -- the router's view ----------------------------------------------------
    def candidates(self) -> list[Candidate]:
        """Routable replicas, least-loaded first: ready (health-gated)
        replicas scored by probed queue depth + the router's in-flight
        count on that replica."""
        with self._lock:
            out = [
                Candidate(r.slot, self.host, r.port,
                          r.queue_depth + r.inflight)
                for r in self._replicas.values()
                if r.ready and r.port is not None
            ]
        return sorted(out, key=lambda c: (c.score, c.slot))

    def note_inflight(self, slot: int, delta: int) -> None:
        with self._lock:
            r = self._replicas.get(slot)
            if r is not None:
                r.inflight = max(0, r.inflight + delta)

    def note_failure(self, slot: int) -> None:
        """A router-observed connection failure: gate the replica out of
        the candidate set NOW (the supervision tick will confirm the
        death and charge the loss within a poll)."""
        with self._lock:
            r = self._replicas.get(slot)
            if r is not None:
                r.ready = False

    def kill_one(self) -> Optional[int]:
        """SIGKILL one live replica (the ``replica_loss`` fault site's
        arm): the HIGHEST live slot, mirroring the ``host_loss``
        convention — slot 0's event stream stays the primary one."""
        for r in sorted(self._replicas.values(),
                        key=lambda x: -x.slot):
            if r.parked:
                continue
            if r.proc is not None and r.proc.poll() is None:
                _kill_tree(r.proc)
                return r.slot
        return None

    # -- elastic roster (the autoscaler's levers) -----------------------------
    def add_one(self) -> int:
        """Grow the roster by one replica: revive the lowest parked slot
        if a scale-down left one (its stdout file, heartbeat path, and
        slot identity are reused), else mint the next slot id. Returns
        the slot; it joins the candidate set only when its /healthz
        turns ready, like any spawn. The caller (the autoscaler) owns
        the max-replicas bound."""
        with self._lock:
            parked = sorted(
                r.slot for r in self._replicas.values() if r.parked
            )
            if parked:
                slot = parked[0]
                r = self._replicas[slot]
                r.parked = False
                r.failures = 0
                r.respawn_due = 0.0
            else:
                slot = max(self._replicas) + 1
                r = _Replica(slot)
                self._replicas[slot] = r
            self.n += 1
        self._spawn(r)
        return slot

    def shed_one(self, drain_wait_s: float = 10.0) -> Optional[int]:
        """Shrink the roster by one: take the HIGHEST ready slot out of
        the candidate set (new traffic immediately routes to its peers —
        the router's spillover path covers any request already racing
        toward it), wait briefly for the router's in-flight count on it
        to drain, then SIGTERM (a serve child drains its queue on
        SIGTERM and exits clean). The slot is PARKED, not forgotten: the
        tick loop neither respawns it nor charges the exit as a loss,
        and a later ``add_one`` revives it warm from the shared exec
        cache. Returns the slot, or None when nothing is sheddable. The
        caller owns the min-replicas bound; the manager only refuses to
        shed its last replica."""
        with self._lock:
            victims = sorted(
                (r for r in self._replicas.values()
                 if r.ready and not r.parked),
                key=lambda x: -x.slot,
            )
            live = sum(1 for r in self._replicas.values()
                       if not r.parked and r.proc is not None)
            if not victims or live <= 1 or self.n <= 1:
                return None
            r = victims[0]
            r.parked = True
            r.ready = False
            self.n -= 1
        self._write_roster("scale_down")
        # Drain-through-spillover: the ready flip above already steers
        # new requests away; in-flight forwards finish on the live
        # process (or spill over on its 503s). Bounded wait, then the
        # child's own SIGTERM drain covers the stragglers.
        deadline = time.monotonic() + drain_wait_s
        while time.monotonic() < deadline:
            with self._lock:
                if r.inflight <= 0:
                    break
            time.sleep(self.poll_s)
        proc, port = r.proc, r.port
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass
            try:
                proc.wait(timeout=max(drain_wait_s, 5.0))
            except subprocess.TimeoutExpired:
                _kill_tree(proc)
        if port is not None:
            self.pool.retire_endpoint(self.host, port, "replica_loss")
        with self._lock:
            r.proc = None
            r.port = None
            r.queue_depth = 0
        return r.slot

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.ready)

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": self.n,
                "ready": sum(
                    1 for r in self._replicas.values() if r.ready
                ),
                "parked": sum(
                    1 for r in self._replicas.values() if r.parked
                ),
                "spawns": self._spawns,
                "losses": self._losses,
                "rejoins": self._rejoins,
                "ports": {
                    r.slot: r.port for r in self._replicas.values()
                    if r.port is not None
                },
                "versions": {
                    r.slot: r.model_version
                    for r in self._replicas.values()
                    if r.model_version is not None
                },
            }


class Autoscaler:
    """The acting half of the scale loop: turn the router's advisory
    ``scale_state()`` verdicts into ``add_one``/``shed_one`` calls, with
    the damping that keeps a flapping verdict from thrashing the roster.

    Three gates between a verdict and an action, in order:

    - **Honest hold on data absence**: a ``shed`` verdict computed with
      BOTH burn rates None (no store samples yet, windows still empty)
      is evidence of missing telemetry, not of idle capacity — it is
      held, never acted on. Symmetrically, an ``add`` with both burns
      None AND no queued work is the cold fleet mid-warmup (the
      empty-roster verdict), not demand — held too. An ``add`` backed
      by a deep queue stands even without burn data: queued work is
      direct observation.
    - **Hysteresis**: the same actionable verdict must hold for
      ``hysteresis`` consecutive evaluations (alert-style sustain) —
      one noisy tick never moves the roster.
    - **Action cooldown**: at least ``cooldown_s`` must have elapsed
      since the LAST ACTION — not since the last verdict change — so an
      oscillating verdict (add, hold, add, hold …) cannot fire on every
      rising edge.

    Bounds: never below ``min_replicas``, never above ``max_replicas``
    (a verdict at a bound is silently refused — no action, no cooldown).
    Every action taken is a ``fleet_autoscale`` event with
    ``{action, from_n, to_n, reason}``. ``step()`` is pure
    state-machine (caller supplies the clock) so the flap tests drive
    oscillating series without threads; ``start()`` runs it on the
    manager-owned control thread."""

    def __init__(self, manager, scale_state: Callable[[], dict], *,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 hysteresis: int = 3,
                 cooldown_s: float = 30.0,
                 interval_s: float = 1.0):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}"
            )
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= "
                f"min_replicas ({min_replicas})"
            )
        self.manager = manager
        self.scale_state = scale_state
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        self.actions = 0
        self._streak_verdict = "hold"
        self._streak = 0
        self._last_action_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the decision step (threadless; the unit tests drive this) -----------
    def step(self, state: dict, now: float) -> Optional[dict]:
        """Evaluate one scale_state snapshot at time ``now``; returns
        the action record (also emitted as ``fleet_autoscale``) when the
        roster moved, else None."""
        verdict = state.get("verdict", "hold")
        burn_fast = state.get("burn_fast")
        burn_slow = state.get("burn_slow")
        if verdict == "shed" and burn_fast is None and burn_slow is None:
            # Shedding wants positive evidence of idle capacity; two
            # None burns mean the telemetry isn't there yet.
            verdict = "hold"
        if verdict == "add" and burn_fast is None and burn_slow is None \
                and (state.get("queue_depth") or 0) <= 0:
            # Adding wants positive evidence of DEMAND (burn or queued
            # work). A bare empty-roster add with neither is the cold
            # fleet mid-warmup — spawning more replicas into a warmup
            # doesn't serve anyone sooner; the manager's respawn path
            # already owns actually-dead rosters.
            verdict = "hold"
        if verdict == self._streak_verdict:
            self._streak += 1
        else:
            self._streak_verdict = verdict
            self._streak = 1
        if verdict not in ("add", "shed"):
            return None
        if self._streak < self.hysteresis:
            return None
        if self._last_action_t is not None and \
                now - self._last_action_t < self.cooldown_s:
            return None
        from_n = self.manager.n
        reason = (
            f"sustained_{verdict}(streak={self._streak},"
            f"burn_fast={burn_fast},burn_slow={burn_slow},"
            f"queue_depth={state.get('queue_depth')})"
        )
        if verdict == "add":
            if from_n >= self.max_replicas:
                return None
            self.manager.add_one()
        else:
            if from_n <= self.min_replicas:
                return None
            if self.manager.shed_one() is None:
                return None
        to_n = self.manager.n
        self._last_action_t = now
        self._streak = 0
        self.actions += 1
        action = {"action": verdict, "from_n": from_n, "to_n": to_n,
                  "reason": reason}
        obs.emit("fleet_autoscale", action=verdict, from_n=from_n,
                 to_n=to_n, reason=reason)
        return action

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.step(self.scale_state(), time.monotonic())
            except Exception as e:  # the control loop must outlive a tick
                obs.warn("fleet_autoscale_error", repr(e)[:300])
            self._stop.wait(self.interval_s)

    def stats(self) -> dict:
        return {
            "actions": self.actions,
            "streak": self._streak,
            "streak_verdict": self._streak_verdict,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
        }
