"""The replica manager: N supervised ``cli serve`` children + the roster.

One replica is one ``cli serve --port 0`` subprocess. The manager owns
their whole lifecycle with the coordinator's supervision shape
(``elastic.coordinator``), applied to serving:

- **Spawn**: each child binds an ephemeral port and prints its serving
  banner (``{"serving": {..., "port": P}}``) to a per-replica stdout
  file in the run dir; the manager tails that file to learn the port.
  A child that never banners within ``spawn_timeout_s`` is killed and
  charged as a startup loss.
- **Liveness**: exit-code polling (a dead process) plus the shared
  heartbeat state machine (``train.heartbeat.HeartbeatMonitor`` — the
  same grace/stall/re-read protocol the train supervisor and the
  elastic coordinator drive; the serve child touches its heartbeat file
  once a second while ready). A wedged replica — process alive, HTTP
  hung — stops beating and is SIGKILLed like a stalled trainer.
- **Readiness**: the manager probes each replica's ``/healthz`` every
  poll; a replica routes traffic only while its probe answers 200
  (ready), and the probed ``queue_depth`` feeds the router's
  least-queue-depth pick. Probes ride the manager's connection pool
  (``fleet.pool`` — the same pool the router forwards on), so a poll
  cycle reuses a warm channel instead of opening a socket; a probe
  FAILURE retires that endpoint's pooled channels immediately, so the
  next forward starts on a fresh connection instead of discovering the
  corpse itself. Loss → respawn (with crash-loop backoff) →
  the respawned child warms its bucket ladder from the fleet-shared
  exec cache → rejoins the roster ONLY when ``/healthz`` turns ready.
- **Roster**: every ready/loss transition rewrites ``membership.json``
  (``elastic.membership`` — the exact schema the elastic trainer
  writes: generation counter, member slots, reason) and emits
  ``fleet_replica_ready`` / ``fleet_replica_loss`` events, so the
  report's fleet section can render the roster timeline next to the
  request stream.

Stdlib-only by contract, like the elastic coordinator: the manager
process supervises N backend-owning children and must never initialize
a device itself.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import threading
import time
from typing import Callable, Optional

from featurenet_tpu import faults, obs
from featurenet_tpu.elastic.coordinator import heartbeat_path
from featurenet_tpu.elastic.membership import Membership, write_membership
from featurenet_tpu.fleet.pool import ConnectionPool
from featurenet_tpu.train.heartbeat import HeartbeatMonitor
from featurenet_tpu.train.supervisor import _kill_tree

DEFAULT_POLL_S = 0.25
DEFAULT_GRACE_S = 300.0        # warmup allowance: a cold cache compiles
DEFAULT_STALL_TIMEOUT_S = 30.0
DEFAULT_SPAWN_TIMEOUT_S = 300.0


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One routable replica as the router sees it: where to connect and
    how loaded it looked at the last probe (``score`` = probed queue
    depth + the router's own in-flight count — the freshest cheap
    estimate of who answers soonest)."""

    slot: int
    host: str
    port: int
    score: int


class _Replica:
    """One slot's live state (manager-internal; guarded by the manager
    lock for the fields router threads touch)."""

    def __init__(self, slot: int):
        self.slot = slot
        self.proc: Optional[subprocess.Popen] = None
        self.out_path: Optional[str] = None
        self.out_offset = 0
        self.port: Optional[int] = None
        self.ready = False
        self.queue_depth = 0
        self.inflight = 0
        self.spawned_t = 0.0
        self.respawn_due = 0.0
        self.failures = 0  # consecutive, for backoff
        self.was_lost = False  # a later ready is a REJOIN
        self.mon: Optional[HeartbeatMonitor] = None
        self.probe_inflight = False  # one outstanding probe at a time


class ReplicaManager:
    """Spawn and supervise ``n`` serving replicas; provide the router's
    health-gated candidate view.

    ``spawn(slot, heartbeat_file) -> argv`` builds one replica's command
    (the ``cli fleet`` launcher passes through the serve flags plus
    ``--port 0 --replica-id <slot> --process-index <slot+1>``). The
    child must print the serve banner on stdout and touch
    ``heartbeat_file`` while ready.
    """

    def __init__(self, n: int, spawn: Callable[[int, str], list],
                 run_dir: str, *,
                 host: str = "127.0.0.1",
                 poll_s: float = DEFAULT_POLL_S,
                 grace_s: float = DEFAULT_GRACE_S,
                 stall_timeout_s: float = DEFAULT_STALL_TIMEOUT_S,
                 spawn_timeout_s: float = DEFAULT_SPAWN_TIMEOUT_S,
                 max_respawns: int = 16,
                 backoff_base_s: float = 0.5,
                 backoff_cap_s: float = 10.0,
                 probe_timeout_s: float = 2.0,
                 env: Optional[dict] = None,
                 pool: Optional[ConnectionPool] = None):
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        self.n = n
        self.spawn = spawn
        self.run_dir = os.path.abspath(run_dir)
        self.host = host
        self.poll_s = poll_s
        self.grace_s = grace_s
        self.stall_timeout_s = stall_timeout_s
        self.spawn_timeout_s = spawn_timeout_s
        self.max_respawns = max_respawns
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.probe_timeout_s = probe_timeout_s
        self.env = env
        # The fleet's one channel pool: probes ride it here, forwards
        # ride it in the router (FleetRouter adopts the provider's pool
        # via this attribute), so health verdicts and traffic share the
        # same view of which channels are alive.
        self.pool = pool or ConnectionPool()
        self._lock = threading.Lock()
        self._replicas = {slot: _Replica(slot) for slot in range(n)}
        self._spawns = 0
        self._losses = 0
        self._rejoins = 0
        self._generation = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> None:
        os.makedirs(self.run_dir, exist_ok=True)
        for r in self._replicas.values():
            self._spawn(r)
        self._thread = threading.Thread(
            target=self._run, name="fleet-replicas", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 20.0) -> None:
        """SIGTERM every child (a serving child drains on SIGTERM), wait
        briefly, SIGKILL stragglers, stop the supervision thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, 4 * self.poll_s))
        procs = [r.proc for r in self._replicas.values()
                 if r.proc is not None and r.proc.poll() is None]
        for p in procs:
            try:
                p.terminate()
            except OSError:
                pass
        deadline = time.monotonic() + timeout_s
        for p in procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                _kill_tree(p)
        self.pool.close()

    # -- spawn / supervision --------------------------------------------------
    def _spawn(self, r: _Replica) -> None:
        self._spawns += 1
        hb = heartbeat_path(self.run_dir, r.slot)
        r.mon = HeartbeatMonitor(hb, self.stall_timeout_s, self.grace_s)
        r.mon.reset()
        argv = list(self.spawn(r.slot, hb))
        if faults.maybe_fail("spawn_fail", spawn=self._spawns):
            import sys

            argv = [sys.executable, "-c", "raise SystemExit(13)"]
        r.out_path = os.path.join(self.run_dir, f"replica.{r.slot}.out")
        r.out_offset = 0
        # Truncate-and-redirect: the banner tail below must find THIS
        # spawn's banner, not a previous incarnation's.
        fh = open(r.out_path, "wb")
        try:
            r.proc = subprocess.Popen(
                argv, stdout=fh, stderr=subprocess.STDOUT,
                start_new_session=True, env=self.env,
            )
        finally:
            fh.close()
        r.port = None
        r.ready = False
        r.queue_depth = 0
        r.spawned_t = time.monotonic()
        obs.emit("supervisor", phase="spawn", host=r.slot,
                 pid=r.proc.pid, generation=self._generation)

    def _scan_banner(self, r: _Replica) -> Optional[int]:
        """The child's bound port from its stdout file (``--port 0``
        binds an ephemeral port only the child knows)."""
        try:
            with open(r.out_path, "rb") as fh:
                fh.seek(r.out_offset)
                chunk = fh.read()
        except OSError:
            return None
        # Only complete lines advance the offset; a torn tail is re-read
        # whole on the next poll.
        end = chunk.rfind(b"\n")
        if end < 0:
            return None
        r.out_offset += end + 1
        for line in chunk[:end].splitlines():
            try:
                doc = json.loads(line.decode("utf-8", "replace"))
                if isinstance(doc, dict) and "serving" in doc:
                    return int(doc["serving"]["port"])
            except (ValueError, KeyError, TypeError):
                continue  # not this child's banner; keep scanning
        return None

    def _probe(self, port: int) -> Optional[dict]:
        """One pooled ``/healthz`` probe: the parsed body on HTTP 200,
        None on anything else (503 warming/draining, connection refused,
        hung socket) — "not routable right now", with the kill decision
        left to the heartbeat/exit machinery. Rides the shared channel
        pool, so steady-state polling costs zero handshakes. Takes the
        port the caller CAPTURED (not ``r.port``, which the tick thread
        nulls on loss while a probe is in flight).

        Retirement discipline: only a CONNECTION-level failure retires
        the endpoint's pooled channels (the corpse-socket signal). A
        clean non-200 — a warming or draining replica answering 503 —
        arrived over a perfectly healthy channel; retiring it would be
        one handshake per poll cycle for the whole warmup, exactly the
        churn the pool exists to remove."""
        import http.client

        try:
            status, body = self.pool.get(
                self.host, port, "/healthz",
                timeout_s=self.probe_timeout_s,
            )
            if status != 200:
                return None
            return json.loads(body.decode("utf-8"))
        except (OSError, http.client.HTTPException, ValueError):
            self.pool.retire_endpoint(self.host, port, "probe_failure")
            return None

    def _lose(self, r: _Replica, reason: str) -> None:
        if r.proc is not None and r.proc.poll() is None:
            _kill_tree(r.proc)
        was_ready = r.ready
        port = r.port
        with self._lock:
            r.proc = None
            r.port = None
            r.ready = False
        if port is not None:
            # A lost replica's channels are corpse sockets: retire them
            # NOW so no forward (or probe) inherits one.
            self.pool.retire_endpoint(self.host, port, "replica_loss")
        r.was_lost = True
        r.failures += 1
        self._losses += 1
        delay = min(self.backoff_cap_s,
                    self.backoff_base_s * (2 ** (r.failures - 1)))
        r.respawn_due = time.monotonic() + delay
        obs.emit("fleet_replica_loss", replica=r.slot, reason=reason)
        if was_ready:
            self._write_roster("replica_loss")

    def _tick(self) -> None:
        now = time.monotonic()
        for r in self._replicas.values():
            if r.proc is None:
                if now >= r.respawn_due and self._spawns - self.n \
                        < self.max_respawns:
                    self._spawn(r)
                continue
            rc = r.proc.poll()
            if rc is not None:
                self._lose(r, f"exit_{rc}")
                continue
            if r.port is None:
                port = self._scan_banner(r)
                if port is not None:
                    r.port = port
                elif now - r.spawned_t > self.spawn_timeout_s:
                    self._lose(r, "startup_timeout")
                continue
            if r.mon is not None and r.mon.poll() == "stall":
                # Process alive, heartbeat stale: a wedged replica (hung
                # forward, stuck HTTP) — nothing softer than SIGKILL is
                # guaranteed to land, same as a wedged mesh member.
                self._lose(r, "stall")
                continue
            # Probe OFF the tick thread (one outstanding per replica):
            # a wedged replica's probe blocks for the full probe
            # timeout, and paying that serially here would delay loss
            # detection and respawns for the whole fleet.
            with self._lock:
                launch = not r.probe_inflight
                r.probe_inflight = launch
            if launch:
                threading.Thread(
                    target=self._probe_update, args=(r,),
                    name=f"fleet-probe-{r.slot}", daemon=True,
                ).start()

    def _probe_update(self, r: _Replica) -> None:
        """One /healthz probe + state fold, on its own thread."""
        try:
            port = r.port
            if port is None or r.proc is None:
                return
            health = self._probe(port)
            if health is None:
                # Not routable (warming, draining, or a probe failure):
                # gate it out of the candidate set but leave the kill
                # verdict to the heartbeat — probing through one dropped
                # packet must not cost a respawn. (_probe itself retires
                # the endpoint's channels when the failure was
                # connection-level — the earliest stale-channel signal —
                # and leaves them pooled on a clean warming/draining
                # 503.)
                with self._lock:
                    r.ready = False
                return
            with self._lock:
                if r.port != port:  # lost/respawned while we probed
                    return
                r.queue_depth = int(health.get("queue_depth") or 0)
                first_ready = not r.ready
                r.ready = True
                if first_ready:
                    r.failures = 0
                    if r.was_lost:
                        self._rejoins += 1
            if first_ready:
                obs.emit("fleet_replica_ready", replica=r.slot,
                         port=r.port)
                self._write_roster(
                    "replica_rejoin" if r.was_lost else "start"
                )
        finally:
            with self._lock:
                r.probe_inflight = False

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # supervision must outlive everything
                # One bad spawn (fd exhaustion, ENOMEM — exactly
                # incident conditions) must not silently kill the one
                # thread whose job is respawning: log and keep polling.
                obs.warn("fleet_tick_error", repr(e)[:300])
            self._stop.wait(self.poll_s)

    def _write_roster(self, reason: str) -> None:
        """Mirror the ready set into ``membership.json`` — the elastic
        trainer's document schema, reused as the fleet roster (an
        operator mid-incident reads one file either way)."""
        with self._lock:
            members = tuple(sorted(
                r.slot for r in self._replicas.values() if r.ready
            ))
            self._generation += 1
            generation = self._generation
        write_membership(self.run_dir, Membership(
            generation=generation,
            members=members,
            min_world_size=1,
            reason=reason,
        ))

    # -- the router's view ----------------------------------------------------
    def candidates(self) -> list[Candidate]:
        """Routable replicas, least-loaded first: ready (health-gated)
        replicas scored by probed queue depth + the router's in-flight
        count on that replica."""
        with self._lock:
            out = [
                Candidate(r.slot, self.host, r.port,
                          r.queue_depth + r.inflight)
                for r in self._replicas.values()
                if r.ready and r.port is not None
            ]
        return sorted(out, key=lambda c: (c.score, c.slot))

    def note_inflight(self, slot: int, delta: int) -> None:
        with self._lock:
            r = self._replicas.get(slot)
            if r is not None:
                r.inflight = max(0, r.inflight + delta)

    def note_failure(self, slot: int) -> None:
        """A router-observed connection failure: gate the replica out of
        the candidate set NOW (the supervision tick will confirm the
        death and charge the loss within a poll)."""
        with self._lock:
            r = self._replicas.get(slot)
            if r is not None:
                r.ready = False

    def kill_one(self) -> Optional[int]:
        """SIGKILL one live replica (the ``replica_loss`` fault site's
        arm): the HIGHEST live slot, mirroring the ``host_loss``
        convention — slot 0's event stream stays the primary one."""
        for r in sorted(self._replicas.values(),
                        key=lambda x: -x.slot):
            if r.proc is not None and r.proc.poll() is None:
                _kill_tree(r.proc)
                return r.slot
        return None

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas.values() if r.ready)

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": self.n,
                "ready": sum(
                    1 for r in self._replicas.values() if r.ready
                ),
                "spawns": self._spawns,
                "losses": self._losses,
                "rejoins": self._rejoins,
                "ports": {
                    r.slot: r.port for r in self._replicas.values()
                    if r.port is not None
                },
            }
