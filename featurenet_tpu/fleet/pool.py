"""The fleet's persistent-connection layer: one pool, every hop.

PR 14's data plane opened a fresh TCP connection for every forwarded
request, every ``/healthz`` probe, and every load-generator request — at
the measured serving rates the handshake churn, not the model, bounds
fleet latency for small voxel payloads. This module is the one place in
the package allowed to construct ``http.client.HTTPConnection``
(``analysis.rules`` raw-conn lint); everything else checks a channel out
of a pool and puts it back.

Pool contract:

- **Check-out / check-in**: ``checkout(host, port)`` hands back an idle
  keep-alive channel for that endpoint (or opens a fresh one); the
  caller owns it exclusively until ``checkin``. One pool serves many
  threads — the router's request threads and the manager's probe
  threads share channels to the same replica.
- **Bounded idle**: at most ``max_idle_per_endpoint`` channels are kept
  per endpoint; extras are retired on check-in (``idle_overflow``), so
  a burst's connection fan never lingers as open sockets.
- **Max-age retirement**: a channel older than ``max_age_s`` is retired
  instead of reused (``max_age``) — long-lived sockets quietly
  accumulate middlebox state; bounded age keeps the pool honest about
  what a "fresh" connection costs (``connect_ms`` keeps measuring).
- **Broken-socket detection**: a channel that dies mid-request is
  retired (``broken``), never re-pooled. ``post`` additionally retries
  ONCE on a *fresh* channel when the failure happened on a REUSED one —
  a keep-alive peer is allowed to close an idle connection between
  requests (a stale channel on a healthy replica), and surfacing that
  as a replica failure would burn the router's one re-submit on a
  replica that never misbehaved. A fresh channel failing is the real
  replica-loss shape and raises to the caller, so the router's
  re-submit-once + zero-drop semantics are exactly what they were.
- **Health coupling**: ``retire_endpoint`` drops every idle channel for
  an endpoint NOW — called when a probe fails or a replica is charged
  lost, so the next forward starts clean instead of discovering the
  corpse socket itself.

Telemetry (never load-bearing): ``conn_open`` / ``conn_reuse`` /
``conn_retire{reason}`` events land in the run stream (the report's
serve/fleet sections and ``/metrics`` count them), and each fresh
connect feeds the ``connect_ms`` rolling window — the number that
proves pooling pays. The pool also keeps plain counters (``stats()``)
so ``bench_fleet`` can pin the reuse ratio with no sink installed.

Stdlib-only, like the rest of the fleet package: the pool lives in the
router/manager process, which owns no device and must survive every
replica.
"""

from __future__ import annotations

import http.client
import threading
import time
from collections import deque
from typing import Optional

from featurenet_tpu import obs
from featurenet_tpu.obs import windows as _windows

# Idle bound: sized to the load generator's worker-pool concurrency (32)
# so a healthy burst's whole fan can come back to the idle set instead of
# churning through idle_overflow retirement; the bound exists for the
# pathological fan (a stampede), not the steady state.
DEFAULT_MAX_IDLE_PER_ENDPOINT = 32
DEFAULT_MAX_AGE_S = 60.0
DEFAULT_TIMEOUT_S = 60.0

# Retirement reasons (the conn_retire event's vocabulary — closed set so
# the report/metrics fold never meets a free-form string).
RETIRE_REASONS = ("broken", "max_age", "idle_overflow", "server_close",
                  "probe_failure", "replica_loss", "shutdown")


class PooledChannel:
    """One keep-alive channel: the raw connection plus the bookkeeping
    the retirement policies need (endpoint identity, birth time, use
    count). Owned exclusively by one caller between checkout/checkin."""

    __slots__ = ("conn", "host", "port", "opened_t", "uses")

    def __init__(self, conn: http.client.HTTPConnection, host: str,
                 port: int):
        self.conn = conn
        self.host = host
        self.port = port
        self.opened_t = time.monotonic()
        self.uses = 0

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def age_s(self) -> float:
        return time.monotonic() - self.opened_t


class ConnectionPool:
    """Bounded keep-alive channel pool over ``(host, port)`` endpoints
    (see the module doc for the full contract)."""

    def __init__(self,
                 max_idle_per_endpoint: int = DEFAULT_MAX_IDLE_PER_ENDPOINT,
                 max_age_s: float = DEFAULT_MAX_AGE_S,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        if max_idle_per_endpoint < 1:
            raise ValueError(
                f"max_idle_per_endpoint must be >= 1, "
                f"got {max_idle_per_endpoint}"
            )
        if max_age_s <= 0:
            raise ValueError(f"max_age_s must be > 0, got {max_age_s}")
        self.max_idle_per_endpoint = int(max_idle_per_endpoint)
        self.max_age_s = float(max_age_s)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._idle: dict[tuple[str, int], deque[PooledChannel]] = {}
        self._closed = False
        self._opened = 0
        self._reused = 0
        self._in_use = 0
        self._in_use_peak = 0
        self._retired: dict[str, int] = {}

    # -- core check-out / check-in --------------------------------------------
    def checkout(self, host: str, port: int,
                 timeout_s: Optional[float] = None,
                 fresh: bool = False) -> PooledChannel:
        """An exclusive channel to ``host:port``: the freshest idle one
        (max-age violators retired on the way), else a new connection.
        ``timeout_s`` re-arms the socket timeout per use — probes and
        forwards share channels but not deadlines. ``fresh=True`` skips
        the idle set entirely (the stale-reuse retry must not inherit a
        sibling channel the same peer close already killed)."""
        with self._lock:
            if self._closed:
                # A closed pool must not silently degrade to
                # connect-per-request churn: refuse like a dead endpoint
                # (OSError — every caller's failure policy already
                # handles the connection-failure shape).
                raise OSError("connection pool is closed")
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        key = (host, int(port))
        ch: Optional[PooledChannel] = None
        while not fresh:
            with self._lock:
                q = self._idle.get(key)
                cand = q.pop() if q else None
            if cand is None:
                break
            if cand.age_s() > self.max_age_s or cand.conn.sock is None:
                self._retire(cand, "max_age" if cand.conn.sock is not None
                             else "server_close")
                continue
            ch = cand
            break
        if ch is None:
            conn = http.client.HTTPConnection(host, port, timeout=timeout)
            t0 = time.perf_counter()
            conn.connect()
            connect_ms = (time.perf_counter() - t0) * 1e3
            ch = PooledChannel(conn, host, int(port))
            with self._lock:
                self._opened += 1
            _windows.observe("connect_ms", connect_ms)
            obs.emit("conn_open", endpoint=ch.endpoint,
                     connect_ms=round(connect_ms, 3))
        else:
            with self._lock:
                self._reused += 1
            if ch.conn.sock is not None:
                ch.conn.sock.settimeout(timeout)
            obs.emit("conn_reuse", endpoint=ch.endpoint, uses=ch.uses)
        ch.uses += 1
        with self._lock:
            self._in_use += 1
            self._in_use_peak = max(self._in_use_peak, self._in_use)
        return ch

    def checkin(self, ch: PooledChannel) -> None:
        """Return a still-healthy channel to the idle set; channels past
        max-age, already closed, or over the idle bound are retired
        instead (the bound keeps a burst's fan from lingering)."""
        with self._lock:
            self._in_use = max(0, self._in_use - 1)
        if ch.conn.sock is None:
            self._retire(ch, "server_close")
            return
        if ch.age_s() > self.max_age_s:
            self._retire(ch, "max_age")
            return
        key = (ch.host, ch.port)
        with self._lock:
            if not self._closed:
                q = self._idle.setdefault(key, deque())
                if len(q) < self.max_idle_per_endpoint:
                    q.append(ch)
                    return
        self._retire(ch, "idle_overflow" if not self._closed
                     else "shutdown")

    def _retire(self, ch: PooledChannel, reason: str) -> None:
        try:
            ch.conn.close()
        except OSError:
            pass
        with self._lock:
            self._retired[reason] = self._retired.get(reason, 0) + 1
        obs.emit("conn_retire", endpoint=ch.endpoint, reason=reason,
                 uses=ch.uses)

    def retire(self, ch: PooledChannel, reason: str = "broken") -> None:
        """Retire a checked-out channel (a caller saw it break)."""
        with self._lock:
            self._in_use = max(0, self._in_use - 1)
        self._retire(ch, reason)

    def retire_endpoint(self, host: str, port: int,
                        reason: str = "probe_failure") -> int:
        """Drop every IDLE channel for an endpoint now (probe failure,
        replica charged lost) — the next checkout starts clean instead
        of inheriting a corpse socket. Returns the count retired."""
        key = (host, int(port))
        with self._lock:
            q = self._idle.pop(key, None)
        if not q:
            return 0
        for ch in q:
            self._retire(ch, reason)
        return len(q)

    def close(self) -> None:
        """Retire every idle channel (``shutdown``); later check-ins are
        retired instead of pooled. Checked-out channels stay valid until
        their owners return them."""
        with self._lock:
            self._closed = True
            qs = list(self._idle.values())
            self._idle.clear()
        for q in qs:
            for ch in q:
                self._retire(ch, "shutdown")

    # -- request helpers (the package's ONLY wire hops) ------------------------
    def post(self, host: str, port: int, path: str, body: bytes,
             headers: dict, timeout_s: Optional[float] = None
             ) -> tuple[int, bytes, Optional[float]]:
        """One pooled HTTP POST (the router's forward AND the fleet load
        generator's request — one implementation, so Retry-After parsing
        and header handling can never drift). Returns ``(status,
        body_bytes, retry_after_s)``. A REUSED channel that breaks is
        retired and retried once on a fresh connection (stale keep-alive
        ≠ dead replica); a fresh channel's failure raises ``OSError`` /
        ``http.client.HTTPException`` upward — the replica-loss shape
        the router's re-submit-once path absorbs."""
        return self._request(host, port, "POST", path, body, headers,
                             timeout_s)

    def get(self, host: str, port: int, path: str,
            timeout_s: Optional[float] = None) -> tuple[int, bytes]:
        """One pooled HTTP GET (the ``/healthz`` probe hop). Same stale-
        reuse retry as ``post``; raises on a fresh channel's failure."""
        status, data, _ = self._request(host, port, "GET", path, None,
                                        {}, timeout_s)
        return status, data

    def _request(self, host: str, port: int, method: str, path: str,
                 body: Optional[bytes], headers: dict,
                 timeout_s: Optional[float]
                 ) -> tuple[int, bytes, Optional[float]]:
        """The one checkout → roundtrip → stale-retry → checkin state
        machine behind ``post`` and ``get`` (a retry-rule change must
        apply to forwards and probes together, never drift)."""
        force_fresh = False
        while True:
            ch = self.checkout(host, port, timeout_s, fresh=force_fresh)
            reused = ch.uses > 1
            try:
                status, data, ra = self._roundtrip(
                    ch, method, path, body, headers
                )
            except (OSError, http.client.HTTPException) as e:
                self.retire(ch, "broken")
                # A TIMEOUT is not a stale channel: the peer is alive
                # but slow (an admitted request still running) — a
                # silent re-send would duplicate work on an overloaded
                # endpoint and block the caller for a second full
                # timeout. Raise it to the caller's own failure policy.
                if isinstance(e, TimeoutError):
                    raise
                if reused and not force_fresh:
                    # The peer closed a keep-alive channel between
                    # requests; a FRESH connection decides whether the
                    # endpoint is actually gone.
                    force_fresh = True
                    continue
                raise
            self.checkin(ch)
            return status, data, ra

    @staticmethod
    def _roundtrip(ch: PooledChannel, method: str, path: str,
                   body: Optional[bytes], headers: dict
                   ) -> tuple[int, bytes, Optional[float]]:
        hdrs = dict(headers)
        if body is not None:
            hdrs.setdefault("Content-Type", "application/octet-stream")
        ch.conn.request(method, path, body=body, headers=hdrs)
        resp = ch.conn.getresponse()
        data = resp.read()  # fully drained: the channel is reusable
        ra = resp.getheader("Retry-After")
        try:
            ra = float(ra) if ra is not None else None
        except ValueError:
            ra = None
        if resp.will_close:
            # The server said this was the channel's last response
            # (Connection: close — e.g. a draining 503): honor it.
            ch.conn.close()
        return resp.status, data, ra

    # -- introspection ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            opened, reused = self._opened, self._reused
            total = opened + reused
            return {
                "opened": opened,
                "reused": reused,
                "reuse_ratio": round(reused / total, 4) if total else None,
                "retired": dict(sorted(self._retired.items())),
                "idle": sum(len(q) for q in self._idle.values()),
                "in_use": self._in_use,
                "in_use_peak": self._in_use_peak,
                # Client-side churn: fresh connects beyond the working
                # set a caller's concurrency needed anyway — each one is
                # a channel that had to be REopened (retirement, broken
                # socket), which is exactly what pooling exists to avoid.
                "reconnects": max(0, opened - self._in_use_peak),
            }
