"""The fleet router: one HTTP front end over N serving replicas.

Routing policy, in the order a request experiences it:

1. **Lane shed** — a ``batch``-lane request is forwarded only to a
   replica whose load score sits under ``batch_shed_depth``; when every
   healthy replica is above it, batch sheds IMMEDIATELY (fleet 503 +
   ``Retry-After``, a ``fleet_shed`` event) while interactive traffic
   still gets the full spillover walk. Same shed order as the batcher's
   per-lane admission caps, one level up.
2. **Health-gated least-queue-depth pick** — candidates come from the
   replica manager's ``/healthz``-fed view (ready replicas scored by
   probed queue depth + router in-flight), least-loaded first.
3. **Spillover** — a replica's overload/draining 503 means "try the
   next healthy replica" (``fleet_spillover``, trace id preserved via
   ``X-Featurenet-Trace``); the fleet-wide 503 with ``Retry-After``
   answers only when every lane is full.
4. **Re-submit once** — a connection that dies mid-request (the replica
   was SIGKILLed under us) re-submits the request to ONE survivor
   (``fleet_resubmit``; idempotent — classification is pure). A second
   connection death is an honest drop (502, counted in
   ``fleet_requests_dropped`` — the number the gate pins at 0).

Every router→replica hop rides the connection pool (``fleet.pool``):
forwards check a keep-alive channel out per request instead of paying a
TCP handshake, a broken channel is retired on the spot (a stale
keep-alive reuse retries once on a FRESH connection inside the pool, so
only a genuinely dead replica reaches the re-submit path), and the
manager's ``/healthz`` probes share the same pool — a probe failure
retires that endpoint's channels immediately instead of letting the
next forward discover the corpse socket. The front end itself speaks
HTTP/1.1 keep-alive, so the client side of the hop persists too.

Scaling verdicts are advisory, never load-bearing: the router feeds its
end-to-end walls into the rolling ``serving_ms`` window (the SAME alert
machinery every service runs) and a background cycle turns sustained SLO
burn rates + roster queue depths into ``fleet_scale{verdict:
add|shed|hold}`` events — what an autoscaler would subscribe to; nothing
in the routing path reads them back. With a time-series store attached
(``store=`` — the fleet CLI wires the scraper-fed ``obs.tsdb`` store),
burn is evaluated multi-window over the fleet's durable history
(``alerts.BurnEvaluator``: fast window proves "now", slow window proves
"sustained", both must burn); without one it falls back to the router's
own live ``serving_ms`` ring buffer — same math, process-local axis. A
point-in-time p99 cannot tell a blip from a capacity problem; a burning
slow window can, which is what makes these verdicts safe for an
autoscaler to act on.

Stdlib + numpy-free by contract (``analysis.rules.HOT_PATH_MODULES``):
the router process owns no device and must survive every replica.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from featurenet_tpu import faults, obs
from featurenet_tpu.fleet.pool import ConnectionPool
from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.obs.tracing import TRACE_HEADER, normalize_trace_id
from featurenet_tpu.serve.batcher import normalize_lane
from featurenet_tpu.serve.http import PRIORITY_HEADER
from featurenet_tpu.serve.service import DEFAULT_SLO_P99_MS, serve_rules

DEFAULT_BATCH_SHED_DEPTH = 8
DEFAULT_RETRY_AFTER_S = 0.25
DEFAULT_SCALE_EVERY_S = 5.0

_ENDPOINTS = ["POST /predict", "POST /predict_voxels", "GET /stats",
              "GET /healthz", "GET /metrics"]

# Queue depth (mean over ready replicas) above which the scale verdict
# says "add" even while the burn still holds — pressure building is the
# earlier signal.
_SCALE_ADD_DEPTH = 8.0

# Slow-window burn below which an idle multi-replica fleet is provably
# oversized: essentially no budget spent over the whole look-back.
_SCALE_SHED_BURN = 0.1


def scale_verdict(burn_fast: Optional[float], burn_slow: Optional[float],
                  queue_depth: float, ready: int,
                  max_burn: float = 1.0) -> str:
    """The advisory verdict from one observation cycle, judged on SLO
    burn rates rather than a point-in-time p99: ``add`` when no replica
    is routable, when BOTH burn windows exceed ``max_burn`` (the
    error budget is being spent faster than allowed, and has been for
    the whole fast window — a sustained capacity problem, not a blip),
    or when queues are building; ``shed`` when the fleet is provably
    oversized (more than one replica, idle queues, and a slow window
    that has burned almost nothing — sustained headroom); else
    ``hold``. A burn window with no samples is ``None`` — honest
    absence: it can neither justify an ``add`` nor (for the slow
    window's sustained-headroom proof) block a ``shed``. Pure —
    unit-testable without a fleet or a store."""
    if ready == 0:
        return "add"
    if (burn_fast is not None and burn_slow is not None
            and burn_fast > max_burn and burn_slow > max_burn):
        return "add"
    if queue_depth > _SCALE_ADD_DEPTH:
        return "add"
    if ready > 1 and queue_depth <= 0.5 and (
        burn_slow is None or burn_slow < _SCALE_SHED_BURN
    ) and (burn_fast is None or burn_fast < _SCALE_SHED_BURN):
        return "shed"
    return "hold"


class FleetRouter:
    """Route requests over a replica provider (``ReplicaManager`` in
    production; anything with ``candidates()`` / ``note_inflight`` /
    ``note_failure`` / ``kill_one`` in tests)."""

    def __init__(self, fleet, *,
                 slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                 rules: Optional[Sequence] = None,
                 batch_shed_depth: int = DEFAULT_BATCH_SHED_DEPTH,
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S,
                 request_timeout_s: float = 60.0,
                 scale_every_s: float = DEFAULT_SCALE_EVERY_S,
                 store=None,
                 slos: Optional[Sequence] = None,
                 burn_fast_s: float = _alerts.DEFAULT_FAST_WINDOW_S,
                 burn_slow_s: float = _alerts.DEFAULT_SLOW_WINDOW_S,
                 run_dir: Optional[str] = None):
        self.fleet = fleet
        self.slo_p99_ms = float(slo_p99_ms)
        self.batch_shed_depth = int(batch_shed_depth)
        self.retry_after_s = float(retry_after_s)
        self.request_timeout_s = float(request_timeout_s)
        self.scale_every_s = float(scale_every_s)
        # The burn-rate SLO the scale verdicts judge: an explicit rule
        # list (``slos=``, e.g. from ``--slos``), else the default
        # serving objective at THIS router's SLO threshold — p99 under
        # slo_p99_ms for 99% of samples, standard window pair unless
        # overridden.
        if slos is not None:
            self._slos = list(slos)
        else:
            self._slos = [_alerts.BurnRateRule(
                "serving_p99_ms", "<", self.slo_p99_ms, 0.99, "critical",
                fast_s=float(burn_fast_s), slow_s=float(burn_slow_s),
            )]
        # With a store the evaluator reads the scraper-fed durable
        # history (and owns the burn alerts' fire/resolve hysteresis);
        # without one the tick computes the same burn over the live
        # serving_ms ring buffer.
        self._burn = _alerts.BurnEvaluator(store, self._slos) \
            if store is not None else None
        self.store = store
        # Forwards ride the replica provider's pool when it has one
        # (ReplicaManager owns it so /healthz probes share channels with
        # forwards); a bare provider (tests) gets the router's own. Only
        # a pool the router CONSTRUCTED is the router's to close — the
        # manager's outlives the router's drain (its probes still run).
        shared = getattr(fleet, "pool", None)
        self._own_pool = shared is None
        self.pool: ConnectionPool = shared or ConnectionPool()
        self._lock = threading.Lock()
        self._routed = 0
        self._answered = 0
        self._rejected = 0
        self._shed = 0
        self._spillovers = 0
        self._resubmits = 0
        self._dropped = 0
        self._draining = False
        self._stopped = False
        # The same rolling-window/alert machinery every InferenceService
        # installs — here it watches the ROUTER's end-to-end walls, so
        # the drain gate and the scale verdicts read fleet-level latency.
        if rules is None:
            rules = serve_rules(slo_p99_ms)
        if rules:
            _windows.install(_windows.WindowAggregator(rules=list(rules)))
        # Incident plane (obs.incidents): with a run_dir the router owns
        # the process-wide incident manager — a burn-rate alert or a
        # replica-loss storm freezes a fleet-level diagnostic bundle
        # (tsdb slice, roster, events tail, host stacks).
        self._incidents = None
        if run_dir is not None:
            from featurenet_tpu.obs import incidents as _incidents

            self._incidents = _incidents.arm(run_dir)
        self._last_verdict: Optional[str] = None
        self._scale_stop = threading.Event()
        self._scale_thread = threading.Thread(
            target=self._scale_loop, name="fleet-scale", daemon=True
        )
        self._scale_thread.start()

    # -- scaling verdicts (advisory) ------------------------------------------
    def scale_state(self) -> dict:
        """One observation cycle's inputs + verdict: both burn windows
        (from the store when attached, else the live window), mean
        roster queue depth, ready count. This is what ``_scale_tick``
        emits on change and what the bench pins time — one call is one
        full burn-query + verdict evaluation."""
        cands = self.fleet.candidates()
        depth = (sum(c.score for c in cands) / len(cands)) if cands \
            else 0.0
        rule = self._slos[0]
        if self._burn is not None:
            res = self._burn.evaluate().get(rule.metric) or {}
            fast, slow = res.get("fast"), res.get("slow")
        else:
            # Store-less fallback: identical math over the router's own
            # serving_ms ring buffer (perf_counter axis end to end).
            samples = _windows.samples("serving_ms")
            now = time.perf_counter()
            fast = _alerts.burn_rate(samples, rule, rule.fast_s, now)
            slow = _alerts.burn_rate(samples, rule, rule.slow_s, now)
        verdict = scale_verdict(fast, slow, depth, len(cands),
                                rule.max_burn)
        return {
            "verdict": verdict,
            "burn_fast": round(fast, 4) if fast is not None else None,
            "burn_slow": round(slow, 4) if slow is not None else None,
            "queue_depth": round(depth, 2),
            "replicas": len(cands),
        }

    def _scale_tick(self) -> None:
        st = self.scale_state()
        if st["verdict"] != self._last_verdict:
            self._last_verdict = st["verdict"]
            obs.emit("fleet_scale", verdict=st["verdict"],
                     burn_fast=st["burn_fast"],
                     burn_slow=st["burn_slow"],
                     queue_depth=st["queue_depth"],
                     replicas=st["replicas"])

    def _scale_loop(self) -> None:
        while not self._scale_stop.wait(self.scale_every_s):
            self._scale_tick()

    # -- the routing core -----------------------------------------------------
    def _forward(self, cand, path: str, body: bytes, trace_id: str,
                 lane: str):
        """One pooled hop to one replica. Returns ``(status, body_bytes,
        retry_after_s)``; raises ``OSError`` / ``HTTPException`` only
        when a FRESH connection fails (the pool absorbs stale keep-alive
        channels itself) — the replica-loss shape the re-submit path
        absorbs."""
        return self.pool.post(
            cand.host, cand.port, path, body,
            {TRACE_HEADER: trace_id, PRIORITY_HEADER: lane},
            self.request_timeout_s,
        )

    def route(self, path: str, body: bytes,
              trace_id: Optional[str] = None,
              lane: str = "interactive") -> tuple[int, bytes, dict]:
        """Route one request; returns ``(status, body_bytes, headers)``
        with the trace echo and any ``Retry-After`` in ``headers``."""
        lane = normalize_lane(lane)
        trace_id = normalize_trace_id(trace_id)
        headers = {TRACE_HEADER: trace_id}
        with self._lock:
            if self._draining:
                headers["Retry-After"] = f"{self.retry_after_s:.3f}"
                # The keep-alive hangup marker: the front end sends this
                # header through, which also closes the channel — a
                # draining fleet must not keep clients parked on it.
                headers["Connection"] = "close"
                return 503, json.dumps(
                    {"error": "draining", "fleet": True}
                ).encode(), headers
            self._routed += 1
            routed = self._routed
        if faults.maybe_fail("replica_loss", request=routed):
            # The chaos arm: SIGKILL a live replica mid-stream — the
            # in-flight requests riding it are exactly what the
            # re-submit path below must absorb.
            self.fleet.kill_one()
        t0 = time.perf_counter()
        tried: set = set()
        failed_once = False
        retry_hint = None
        while True:
            cands = [c for c in self.fleet.candidates()
                     if c.slot not in tried]
            if lane == "batch" and cands:
                under = [c for c in cands
                         if c.score < self.batch_shed_depth]
                if not under and not failed_once:
                    # Shed batch first: every healthy replica is above
                    # the batch-pressure bar — don't even occupy one.
                    # A request that already DIED on a replica is NOT
                    # shed-able (it may have been admitted there): empty
                    # the candidate walk instead, so the exhaustion
                    # branch below counts it as the drop it is.
                    with self._lock:
                        self._shed += 1
                    obs.emit("fleet_shed", lane=lane,
                             queue_depth=min(c.score for c in cands))
                    headers["Retry-After"] = f"{self.retry_after_s:.3f}"
                    return 503, json.dumps({
                        "error": "overload", "fleet": True,
                        "lane": lane, "shed": True,
                        "retry_after_s": self.retry_after_s,
                    }).encode(), headers
                cands = under
            if not cands:
                # Every lane is full (or every replica tried): the
                # fleet-wide verdict. A request that already DIED on a
                # replica once may have been admitted there — that is a
                # drop, not a clean rejection.
                ra = retry_hint if retry_hint is not None \
                    else self.retry_after_s
                headers["Retry-After"] = f"{ra:.3f}"
                if failed_once:
                    with self._lock:
                        self._dropped += 1
                    return 502, json.dumps({
                        "error": "replica_lost", "fleet": True,
                        "detail": "no surviving replica to re-submit to",
                    }).encode(), headers
                with self._lock:
                    self._rejected += 1
                return 503, json.dumps({
                    "error": "overload", "fleet": True, "lane": lane,
                    "retry_after_s": ra,
                }).encode(), headers
            cand = cands[0]
            tried.add(cand.slot)
            self.fleet.note_inflight(cand.slot, 1)
            try:
                status, data, ra = self._forward(
                    cand, path, body, trace_id, lane
                )
            except (OSError, http.client.HTTPException):
                self.fleet.note_failure(cand.slot)
                # The channel that died is already retired (pool.post);
                # drop the endpoint's remaining IDLE channels too — a
                # dead replica's whole channel set is corpse sockets.
                self.pool.retire_endpoint(cand.host, cand.port,
                                          "replica_loss")
                if failed_once:
                    # Re-submit ONCE: a second replica dying under the
                    # same request is an honest drop, not a retry loop.
                    with self._lock:
                        self._dropped += 1
                    return 502, json.dumps({
                        "error": "replica_lost", "fleet": True,
                        "replica": cand.slot,
                    }).encode(), headers
                failed_once = True
                with self._lock:
                    self._resubmits += 1
                obs.emit("fleet_resubmit", trace=trace_id,
                         from_replica=cand.slot)
                continue
            finally:
                self.fleet.note_inflight(cand.slot, -1)
            if status == 503:
                # Replica-level overload/draining: spill to the next
                # healthy replica, trace id preserved. The replica's
                # Retry-After rides along in case the WALK ends 503.
                retry_hint = ra if ra is not None else retry_hint
                with self._lock:
                    self._spillovers += 1
                obs.emit("fleet_spillover", trace=trace_id,
                         from_replica=cand.slot)
                continue
            if status == 200:
                with self._lock:
                    self._answered += 1
                # The fleet-level end-to-end wall (client admission →
                # replica response through every spill/re-submit hop):
                # what the serving SLO means at the fleet boundary.
                _windows.observe(
                    "serving_ms", (time.perf_counter() - t0) * 1e3
                )
            return status, data, headers

    # -- HTTP front end -------------------------------------------------------
    def make_server(self, host: str = "127.0.0.1",
                    port: int = 0) -> ThreadingHTTPServer:
        router = self

        class Handler(BaseHTTPRequestHandler):
            # Keep-alive front end: HTTP/1.1 + exact Content-Length on
            # every response, mirroring the replica servers — a client
            # (or upstream balancer) holds one warm channel to the
            # fleet instead of re-handshaking per request.
            protocol_version = "HTTP/1.1"
            timeout = router.request_timeout_s + 15.0

            def log_message(self, fmt, *args):  # noqa: N802
                pass

            def _send(self, code: int, body: bytes,
                      headers: dict) -> None:
                # A "Connection: close" in headers (the draining 503's
                # hangup marker, set by route()) also flips the stdlib
                # close_connection flag via send_header.
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers.items():
                    if v is not None:
                        self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    healthy = router.fleet.ready_count()
                    ready = healthy > 0 and not router._draining
                    st = router.fleet.stats()
                    body = json.dumps({
                        "ready": ready, "fleet": True,
                        # Roster summary for external probes: how many
                        # replicas are serving out of how many exist,
                        # and whether the front door is closing — no
                        # /metrics parsing required to answer "is this
                        # fleet degraded".
                        "healthy": healthy,
                        "total": st.get("replicas", healthy),
                        "draining": router._draining,
                        **st,
                    }).encode()
                    self._send(200 if ready else 503, body, {})
                    return
                if self.path == "/stats":
                    body = json.dumps(
                        {"ok": True, **router.stats()}
                    ).encode()
                    self._send(200, body, {})
                    return
                if self.path == "/metrics":
                    from featurenet_tpu.serve.metrics import (
                        CONTENT_TYPE,
                        render_router_metrics,
                    )

                    body = render_router_metrics(router).encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self._send(404, json.dumps({
                    "error": "not_found", "endpoints": _ENDPOINTS,
                }).encode(), {})

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length)
                if self.path not in ("/predict", "/predict_voxels"):
                    # Body already drained above: an unread body on a
                    # keep-alive channel would desync the NEXT request.
                    self._send(404, json.dumps({
                        "error": "not_found", "endpoints": _ENDPOINTS,
                    }).encode(), {})
                    return
                status, data, headers = router.route(
                    self.path, body,
                    trace_id=self.headers.get(TRACE_HEADER),
                    lane=self.headers.get(PRIORITY_HEADER),
                )
                self._send(status, data, headers)

        srv = ThreadingHTTPServer((host, port), Handler)
        srv.daemon_threads = True
        return srv

    # -- introspection / lifecycle --------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "routed": self._routed,
                "answered": self._answered,
                "rejected": self._rejected,
                "shed": self._shed,
                "spillovers": self._spillovers,
                "resubmits": self._resubmits,
                "dropped": self._dropped,
                "replicas": self.fleet.stats(),
            }
        # Channel-churn evidence (opened/reused/retired{reason}): the
        # pooling payoff, read by bench_fleet's reuse-ratio pin.
        out["pool"] = self.pool.stats()
        return out

    def drain(self) -> dict:
        """Stop routing, flush the final window cycle, report the fleet
        verdict: ``exit_code`` 2 when a serving alert is unresolved OR
        any admitted request was dropped — the fleet's whole promise."""
        with self._lock:
            self._draining = True
            first = not self._stopped
            self._stopped = True
        self._scale_stop.set()
        self._scale_thread.join(timeout=2.0)
        _windows.flush()
        # Final flush first: it may resolve alerts (closing incidents
        # through the tap) so the bundle durations stay honest.
        if self._incidents is not None:
            from featurenet_tpu.obs import incidents as _incidents

            _incidents.disarm(self._incidents)
        st = self.stats()
        # Retire the idle channel set — but only a pool the router
        # constructed: closing the manager's shared pool here would
        # turn its still-running probes into connect-and-refuse churn
        # (ReplicaManager.stop closes that one when supervision ends).
        if self._own_pool:
            self.pool.close()
        active = [m for m in _windows.active_alerts()
                  if _alerts.is_serving_metric(m)]
        st["active_serving_alerts"] = active
        st["exit_code"] = 2 if (active or st["dropped"]) else 0
        if first:
            obs.emit("fleet_stop", routed=st["routed"],
                     answered=st["answered"], rejected=st["rejected"],
                     dropped=st["dropped"])
        return st
