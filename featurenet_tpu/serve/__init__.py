"""Always-on serving front end: continuous batching over bucketed AOT
executables, with admission control and SLO-gated latency.

- ``batcher``  — backend-free scheduling core: bounded queue, flush on
                 max-batch or max-wait, bucket padding, per-request
                 de-mux, overload fast-reject.
- ``service``  — the ladder of pre-built ``serve`` executables + the STL
                 upload path + SLO-gated drain (``InferenceService``).
- ``http``     — stdlib HTTP/1.1 keep-alive front end (``POST
                 /predict`` with STL bytes, ``POST
                 /predict_voxels_stream`` pipelining length-prefixed
                 voxel frames over one socket, ``GET /stats``).
- ``loadgen``  — Poisson open-loop load generator (``bench_serving`` is
                 bench.py's sustained-QPS / p50/p99 / occupancy row) and
                 ``stream_load``, the single-socket stream client.

Entry point: ``python -m featurenet_tpu.cli serve --checkpoint-dir D``.
"""

from featurenet_tpu.serve.batcher import (  # noqa: F401
    ContinuousBatcher,
    OverloadError,
    PendingRequest,
    pick_bucket,
)
from featurenet_tpu.serve.service import (  # noqa: F401
    InferenceService,
    serve_rules,
)
