"""Stdlib-only Prometheus text exporter for the serving path.

``GET /metrics`` folds the numbers the process already keeps — the
batcher's counters, the event sink's per-kind counts (compiles, cache
verdicts, overloads), the tracing sampler's totals, and the rolling
``RollingWindow`` summaries — into the Prometheus text exposition
format (version 0.0.4), so the fleet router and any external monitor
scrape the SAME windows the SLO alerts fire on. No client library, no
histogram buckets: quantile-style gauges (``featurenet_serving_ms
{q="0.99"}``) mirror the nearest-rank percentiles the ``window_summary``
events carry, which is what makes the exporter's numbers bit-equal to
the report's.

The name set is a closed registry (``METRIC_NAMES``): every line the
exporter can emit is declared here and the window gauge family is
derived from ``alerts.WINDOW_METRICS``, so a renamed window metric
changes the exporter with it — never a silently dropped scrape series.
A drift test pins exporter output ⊆ registry.
"""

from __future__ import annotations

from featurenet_tpu.obs import events as _events
from featurenet_tpu.obs import tracing as _tracing
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.obs.alerts import WINDOW_METRICS

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_PREFIX = "featurenet_"

# Event kinds worth exporting as counters, with their metric names: the
# sink counts every emit per kind, so these are free and always agree
# with what `cli report` will later count from the stream.
_EVENT_COUNTERS = {
    "program_compile": "program_compiles_total",
    "cache_hit": "exec_cache_hits_total",
    "cache_miss": "exec_cache_misses_total",
    "cache_reject": "exec_cache_rejects_total",
    "overload": "overloads_total",
    "serve_batch": "serve_batches_total",
    # Persistent-connection data plane (fleet.pool): channel lifecycle.
    "conn_open": "connections_opened_total",
    "conn_reuse": "connections_reused_total",
    "conn_retire": "connections_retired_total",
}

_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))

# Every metric family this exporter can emit (base names, no labels).
METRIC_NAMES = frozenset(
    {
        "ready",
        "uptime_seconds",
        "window_seq",
        "requests_total",          # labeled by outcome: served/rejected/error
        "serve_queue_depth",
        "serve_occupancy",
        "trace_admitted_total",
        "trace_sampled_total",
        "trace_forced_total",
        # Build identity: one constant gauge whose labels carry the
        # jax version / serving precision / conv lowering — the three
        # facts a dashboard needs to split a latency regression by
        # deploy rather than by time.
        "build_info",
        # Router flavor (render_router_metrics): routing outcomes and
        # the pool's own counters — labeled by outcome / reason. The
        # pool counters deliberately mirror the event-counter names so
        # a dashboard reads one series whichever process exported it.
        "fleet_requests_total",
        "connections_retired_total",
        # Scraper-side series (fleet.scraper appends these to the tsdb;
        # no exporter emits them): per-target scrape failures and
        # per-round collection wall. Registered here because
        # METRIC_NAMES is the CLOSED registry for every series the
        # telemetry plane can write — the exporter-output drift test
        # checks output ⊆ registry, and the analysis lint checks the
        # store's series the same way.
        "scrape_failures_total",
        "scrape_duration_ms",
        # Alert-timeline mirror (obs.alerts.set_store): fire=1/resolve=0
        # per rule, appended straight to the store — dash and the report
        # render alert timelines from the store alone.
        "alerts_active",
        # Incident plane (obs.incidents): currently-open incident count,
        # exported by BOTH exporters so any scrape says whether the
        # process is mid-incident.
        "incidents_open",
    }
    | set(_EVENT_COUNTERS.values())
    # One gauge family per rolling window (quantile-labeled) + its count.
    | set(WINDOW_METRICS)
    | {f"{m}_count" for m in WINDOW_METRICS}
)

# One HELP string per family the exporters emit — satellite contract:
# every emitted family carries exactly one # HELP / # TYPE pair.
_HELP = {
    "ready": "1 between warmup completing and drain beginning",
    "uptime_seconds": "process uptime",
    "window_seq": "rolling-window emission sequence number",
    "requests_total": "requests by outcome (served/rejected/error)",
    "serve_queue_depth": "continuous batcher queue depth",
    "serve_occupancy": "mean dispatched-batch occupancy",
    "trace_admitted_total": "requests admitted to tracing decisions",
    "trace_sampled_total": "requests sampled into traces",
    "trace_forced_total": "SLO-breach forced trace samples",
    "build_info": "constant 1; labels carry build identity",
    "fleet_requests_total": "router requests by outcome",
    "program_compiles_total": "XLA program compiles",
    "exec_cache_hits_total": "executable cache hits",
    "exec_cache_misses_total": "executable cache misses",
    "exec_cache_rejects_total": "executable cache fingerprint rejects",
    "overloads_total": "admission-bound rejections",
    "serve_batches_total": "dispatched serving batches",
    "connections_opened_total": "fresh pooled channels opened",
    "connections_reused_total": "pooled channel reuses",
    "connections_retired_total": "pooled channels retired by reason",
    "alerts_active": "1 while the labeled alert rule is firing",
    "incidents_open": "incidents currently open in this process",
}


def _help_for(name: str) -> str:
    h = _HELP.get(name)
    if h is not None:
        return h
    if name.endswith("_count"):
        return f"samples in the {name[:-len('_count')]} rolling window"
    return f"rolling-window quantile gauge over {name} samples"


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _build_info_labels(serve_precision: str, conv_backend: str,
                       model_version: str = "n/a") -> str:
    try:
        import jax
        jax_version = getattr(jax, "__version__", "unknown")
    except Exception:  # pragma: no cover - jax is baked into the image
        jax_version = "unknown"
    return (
        f'{{jax_version="{_escape_label(jax_version)}",'
        f'serve_precision="{_escape_label(serve_precision)}",'
        f'conv_backend="{_escape_label(conv_backend)}",'
        f'model_version="{_escape_label(model_version)}"}}'
    )


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    # Prometheus exposition spells non-finite samples "NaN"/"+Inf"/"-Inf"
    # — Python's "nan"/"inf" would be rejected by conformant scrapers.
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return format(f, "g")


def _row(lines: list[str], name: str, value, labels: str = "",
         kind: str | None = None) -> None:
    """One exposition row (with its ``# HELP``/``# TYPE`` pair when
    ``kind`` is given — i.e. on the family's FIRST row) — the single row
    builder behind BOTH exporters, so a format change can never diverge
    them."""
    full = _PREFIX + name
    if kind is not None:
        lines.append(f"# HELP {full} {_help_for(name)}")
        lines.append(f"# TYPE {full} {kind}")
    lines.append(f"{full}{labels} {_fmt(value)}")


def render_metrics(service) -> str:
    """The /metrics body for one ``InferenceService``: counters first,
    then the rolling-window quantile gauges. Honest absence throughout —
    a window with no samples emits nothing, a dark sink contributes no
    event counters (the batcher/tracing numbers still export)."""
    lines: list[str] = []

    def row(name: str, value, labels: str = "",
            kind: str | None = None) -> None:
        _row(lines, name, value, labels, kind)

    cfg = getattr(service, "cfg", None)
    row("build_info", 1, _build_info_labels(
        getattr(cfg, "serve_precision", "unknown"),
        getattr(getattr(cfg, "arch", None), "conv_backend", "unknown"),
        model_version=getattr(
            getattr(service, "predictor", None),
            "model_version", "unversioned",
        ),
    ), kind="gauge")

    health = service.health()
    row("ready", health["ready"], kind="gauge")
    row("uptime_seconds", health["uptime_s"], kind="gauge")
    if health.get("window_seq") is not None:
        row("window_seq", health["window_seq"], kind="gauge")

    st = service.stats()
    row("requests_total", st["served"], '{outcome="served"}',
        kind="counter")
    row("requests_total", st["rejected"], '{outcome="rejected"}')
    row("requests_total", st["errors"], '{outcome="error"}')
    row("serve_queue_depth", st["queue_depth"], kind="gauge")
    if st.get("occupancy") is not None:
        row("serve_occupancy", st["occupancy"], kind="gauge")

    kinds = _events.kind_counts()
    for ev, name in sorted(_EVENT_COUNTERS.items()):
        if ev in kinds:
            row(name, kinds[ev], kind="counter")

    tc = _tracing.counters()
    row("trace_admitted_total", tc["admitted"], kind="counter")
    row("trace_sampled_total", tc["sampled"], kind="counter")
    row("trace_forced_total", tc["forced"], kind="counter")

    row("incidents_open", _incidents_open(), kind="gauge")

    _window_lines(lines)
    return "\n".join(lines) + "\n"


def _incidents_open() -> int:
    """Currently-open incident count (0 when the plane is unarmed) —
    function-level import: incidents pulls tsdb/windows, and this module
    must stay importable by the lightest exporter path."""
    from featurenet_tpu.obs import incidents as _incidents

    return _incidents.open_count()


def _window_lines(lines: list[str]) -> None:
    """The rolling-window quantile gauges (shared by the service and
    router exporters — one formula, bit-equal to the report's)."""
    for metric, summary in sorted(_windows.snapshot().items()):
        full = _PREFIX + metric
        lines.append(f"# HELP {full} {_help_for(metric)}")
        lines.append(f"# TYPE {full} gauge")
        for q, stat in _QUANTILES:
            lines.append(f'{full}{{q="{q}"}} {_fmt(summary[stat])}')
        count = f"{metric}_count"
        lines.append(f"# HELP {_PREFIX}{count} {_help_for(count)}")
        lines.append(f"# TYPE {_PREFIX}{count} gauge")
        lines.append(f"{_PREFIX}{count} {summary['n']}")


def render_router_metrics(router) -> str:
    """The /metrics body for one ``FleetRouter``: routing outcomes, the
    connection pool's own lifecycle counters (plain pool counters, so
    the export works with no event sink installed), and the rolling
    windows the router feeds (``serving_ms`` end-to-end walls,
    ``connect_ms`` per fresh channel). Same honest-absence discipline
    as the service exporter."""
    lines: list[str] = []

    def row(name: str, value, labels: str = "",
            kind: str | None = None) -> None:
        _row(lines, name, value, labels, kind)

    st = router.stats()
    # The router owns no checkpoint: precision/lowering are per-replica
    # facts its build_info can't claim — "n/a" is the honest value, the
    # jax version is still the router process's own.
    row("build_info", 1, _build_info_labels("n/a", "n/a"), kind="gauge")
    row("ready", router.fleet.ready_count() > 0, kind="gauge")
    row("fleet_requests_total", st["routed"], '{outcome="routed"}',
        kind="counter")
    row("fleet_requests_total", st["answered"], '{outcome="answered"}')
    row("fleet_requests_total", st["rejected"], '{outcome="rejected"}')
    row("fleet_requests_total", st["shed"], '{outcome="shed"}')
    row("fleet_requests_total", st["dropped"], '{outcome="dropped"}')

    pool = st.get("pool") or {}
    row("connections_opened_total", pool.get("opened", 0), kind="counter")
    row("connections_reused_total", pool.get("reused", 0), kind="counter")
    retired = pool.get("retired") or {}
    lines.append(
        f"# HELP {_PREFIX}connections_retired_total "
        f"{_help_for('connections_retired_total')}"
    )
    lines.append(f"# TYPE {_PREFIX}connections_retired_total counter")
    if retired:
        for reason, n in sorted(retired.items()):
            lines.append(
                f'{_PREFIX}connections_retired_total'
                f'{{reason="{_escape_label(reason)}"}} {_fmt(n)}'
            )
    else:
        lines.append(f"{_PREFIX}connections_retired_total 0")

    row("incidents_open", _incidents_open(), kind="gauge")

    _window_lines(lines)
    return "\n".join(lines) + "\n"
