"""Poisson open-loop load generator for the serving front end.

Closed-loop measurement (``benchmark.measure_inference``) asks "how fast
can the device go when a full batch is always waiting" — bench r04's ~48k
inferences/sec/chip is that number, and no real traffic pattern can reach
it. This module asks the production question: at an *open-loop* arrival
rate — requests arrive on a Poisson clock whether or not the service has
finished the previous ones — what QPS does the service sustain, what do
the end-to-end p50/p99 look like, and how full do the dispatch buckets
run?

Open-loop discipline: arrivals are scheduled from the exponential
inter-arrival draws up front, and the generator sleeps only when it is
*ahead* of schedule — a slow service makes the generator submit late but
never slower, which is exactly how a load balancer treats a slow backend.
Rejections (``OverloadError``) are counted, not retried: retry storms are
a client policy, not a generator's.

``bench_serving`` is the bench.py entry point: a random-init weights
service (throughput is weight-agnostic) measured at a target fraction of
the closed-loop rate, returning the flat ``serve_*`` fields bench pins in
``gate_summary``.

``stream_load`` is this module's wire-speaking client: the streamed
multi-part protocol (``POST /predict_voxels_stream``) over ONE
keep-alive socket — the persistent-connection discipline both load
generators now follow (the fleet generator pools its channels;
the stream client needs exactly one).
"""

from __future__ import annotations

import json
import time
from typing import Optional, Sequence

import numpy as np

from featurenet_tpu import obs
from featurenet_tpu.obs import tracing as _tracing
from featurenet_tpu.obs.report import _pct
from featurenet_tpu.serve.batcher import OverloadError

# Bench loadgen sizing: offered load as a fraction of the measured
# closed-loop serving rate (deep enough to fill the big buckets, far
# enough from saturation that p99 measures the service, not the queue),
# and a cap so a Python-thread generator is never asked for arrival gaps
# it cannot schedule.
BENCH_LOAD_FRACTION = 0.3
BENCH_QPS_CAP = 8000.0


def poisson_load(service, qps: float, n_requests: int,
                 rng: Optional[np.random.Generator] = None,
                 grids: Optional[np.ndarray] = None,
                 timeout_s: float = 120.0,
                 lane: str = "interactive",
                 honor_retry_after: bool = True) -> tuple[dict, list]:
    """Drive ``service`` with ``n_requests`` Poisson arrivals at rate
    ``qps``; returns ``(stats, futures)`` where ``futures`` are the
    accepted requests' resolved futures (request i's grid is
    ``grids[i % len(grids)]`` — callers verify answers against a
    reference forward). Every accepted request is waited on before the
    stats are computed, so ``sustained_qps`` is answered-requests over
    the full wall, not an admission rate.

    A rejection carrying the server's ``retry_after_s`` hint is retried
    ONCE after that backoff (``honor_retry_after``) — a polite client
    honoring ``Retry-After`` instead of booking a blind rejection; a
    second refusal counts as rejected. The retry is scheduled work like
    any arrival: the generator stays open-loop."""
    if qps <= 0:
        raise ValueError(f"qps must be > 0, got {qps}")
    if rng is None:
        rng = np.random.default_rng(0)
    if grids is None:
        from featurenet_tpu.data.synthetic import generate_batch

        grids = generate_batch(
            rng, min(64, max(1, n_requests)), service.cfg.resolution
        )["voxels"]
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_requests))
    t0 = time.perf_counter()
    futures: list = []
    submit_t: list[float] = []  # per-future client submit stamp
    rejected = 0
    retried = 0
    retries: list[tuple[float, int]] = []  # (absolute due stamp, grid i)

    def _try(i: int, may_retry: bool) -> None:
        nonlocal rejected, retried
        # The generator mints its own trace id per request (the client
        # half of the propagation contract) and stamps the CLIENT clock
        # before the submit call — client-observed latency covers
        # validation + admission + queue + device on the same monotonic
        # clock the server stamps with, so the client-vs-server skew is
        # real queueing, never clock noise.
        t_submit = time.perf_counter()
        try:
            futures.append(service.submit_voxels(
                grids[i % len(grids)],
                trace_id=_tracing.mint_trace_id(),
                lane=lane,
            ))
            submit_t.append(t_submit)
        except OverloadError as e:
            if may_retry and e.retry_after_s:
                retried += 1
                retries.append(
                    (time.perf_counter() + e.retry_after_s, i)
                )
            else:
                rejected += 1

    for i in range(n_requests):
        while retries and retries[0][0] <= time.perf_counter():
            _try(retries.pop(0)[1], may_retry=False)
        ahead = arrivals[i] - (time.perf_counter() - t0)
        if ahead > 0:
            time.sleep(ahead)
        _try(i, may_retry=honor_retry_after)
    for due, i in retries:  # leftover honored backoffs after last arrival
        wait = due - time.perf_counter()
        if wait > 0:
            time.sleep(wait)
        _try(i, may_retry=False)
    for fut in futures:
        fut.result(timeout=timeout_s)
    wall = time.perf_counter() - t0
    lats = sorted(f.latency_ms for f in futures)
    # Client-observed latency per trace id: submit-call start → the
    # dispatcher's resolution stamp (t_done), both perf_counter.
    client_by_trace = {
        f.trace_id: round((f.t_done - ts) * 1e3, 3)
        for f, ts in zip(futures, submit_t)
    }
    client = sorted(client_by_trace.values())
    st = service.stats()
    stats = {
        "offered_qps": round(n_requests / float(arrivals[-1]), 1),
        "sustained_qps": round(len(futures) / wall, 1) if wall > 0 else None,
        "accepted": len(futures),
        "rejected": rejected,
        "retried": retried,
        "p50_ms": round(_pct(lats, 50), 3) if lats else None,
        "p99_ms": round(_pct(lats, 99), 3) if lats else None,
        "client_p50_ms": round(_pct(client, 50), 3) if client else None,
        "client_p99_ms": round(_pct(client, 99), 3) if client else None,
        "client_by_trace": client_by_trace,
        "occupancy": st["occupancy"],
        "by_bucket": st["by_bucket"],
    }
    if client:
        # The client-side summary lands in the run log so the report's
        # traces section can state the client-vs-server p99 skew next
        # to the sampled server timelines (no-op when dark).
        obs.emit("loadgen", n=len(client),
                 client_p50_ms=stats["client_p50_ms"],
                 client_p99_ms=stats["client_p99_ms"],
                 offered_qps=stats["offered_qps"])
    return stats, futures


def stream_load(host: str, port: int, grids, lane: str = "interactive",
                timeout_s: float = 120.0,
                trace_id: Optional[str] = None) -> dict:
    """The stream-protocol client: pipeline every grid in ``grids`` over
    ONE keep-alive socket as length-prefixed float32 frames
    (``POST /predict_voxels_stream``) and collect the per-frame JSON
    response lines as the server streams them back. This is the client
    half of the persistent data plane for batched work — hundreds of
    parts, one handshake — so ``reconnects`` is 0 by construction and
    reported anyway, keeping the bench-row schema aligned with the
    per-request generators. Returns status, the stream id the server
    echoed, per-frame lines (in frame order), and the answered/error
    split."""
    import http.client
    import struct

    from featurenet_tpu.obs.tracing import TRACE_HEADER
    from featurenet_tpu.serve.http import PRIORITY_HEADER

    frames = []
    for g in grids:
        payload = np.ascontiguousarray(
            np.asarray(g).reshape(np.asarray(g).shape[:3]), dtype="<f4"
        ).tobytes()
        frames.append(struct.pack("<I", len(payload)) + payload)
    body = b"".join(frames)
    headers = {"Content-Type": "application/octet-stream",
               PRIORITY_HEADER: lane}
    if trace_id:
        headers[TRACE_HEADER] = trace_id
    # lint: allow-raw-conn(the stream protocol IS one persistent socket — a pool adds nothing to a single-channel client)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    t0 = time.perf_counter()
    lines: list[dict] = []
    try:
        conn.request("POST", "/predict_voxels_stream", body=body,
                     headers=headers)
        resp = conn.getresponse()
        stream_id = resp.getheader(TRACE_HEADER)
        if resp.status != 200:
            try:
                err = json.loads(resp.read().decode("utf-8"))
            except ValueError:
                err = {}
            return {"status": resp.status, "stream_id": stream_id,
                    "frames": len(frames), "answered": 0,
                    "errors": len(frames), "lines": [], "detail": err,
                    "reconnects": 0}
        # readline through the chunked decoder: each line lands the
        # moment its frame resolves server-side.
        while True:
            raw = resp.readline()
            if not raw:
                break
            raw = raw.strip()
            if raw:
                lines.append(json.loads(raw.decode("utf-8")))
    finally:
        conn.close()
    wall = time.perf_counter() - t0
    ok = [ln for ln in lines
          if "label" in ln or "voxel_counts" in ln]
    return {
        "status": 200,
        "stream_id": stream_id,
        "frames": len(frames),
        "answered": len(ok),
        "errors": len(lines) - len(ok),
        "lines": lines,
        "wall_s": round(wall, 4),
        "frames_per_sec": round(len(lines) / wall, 1) if wall > 0
        else None,
        "reconnects": 0,
    }


def _build_service(cfg, buckets: Sequence[int], max_wait_ms: float,
                   queue_limit: int, **service_kw):
    """One random-init service builder for every loadgen probe
    (throughput is weight-agnostic, like ``measure_inference``) — the
    construction boilerplate must not fork between the open-loop row
    and the trace-overhead probe."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model
    from featurenet_tpu.serve.service import InferenceService

    R = cfg.resolution
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, R, R, R, 1), jnp.float32),
        train=False,
    )
    pred = Predictor(
        variables["params"], variables["batch_stats"], cfg,
        batch=max(buckets),
    )
    return InferenceService(
        pred, buckets=buckets, max_wait_ms=max_wait_ms,
        queue_limit=queue_limit, **service_kw,
    )


def bench_serving(cfg, qps: float, n_requests: int = 512,
                  buckets: Sequence[int] = (1, 4, 16, 64),
                  max_wait_ms: float = 5.0,
                  queue_limit: int = 256) -> dict:
    """The bench.py serving row: build a random-init service for ``cfg``,
    run the open-loop generator at ``qps``, drain, and return flat
    ``serve_*`` fields for the gate summary."""
    service = _build_service(cfg, buckets, max_wait_ms, queue_limit)
    try:
        stats, _ = poisson_load(
            service, qps=qps, n_requests=n_requests,
            rng=np.random.default_rng(0),
        )
    finally:
        service.drain()
    return {
        "serve_qps_offered": stats["offered_qps"],
        "serve_qps_sustained": stats["sustained_qps"],
        "serve_p50_ms": stats["p50_ms"],
        "serve_p99_ms": stats["p99_ms"],
        # The client-observed percentiles beside the server windows:
        # the gap between serve_client_p99_ms and serve_p99_ms is
        # queueing upstream of admission, on one clock.
        "serve_client_p50_ms": stats["client_p50_ms"],
        "serve_client_p99_ms": stats["client_p99_ms"],
        "serve_occupancy": stats["occupancy"],
        "serve_rejected": stats["rejected"],
        "serve_buckets": {str(k): v for k, v in stats["by_bucket"].items()},
        "serve_requests": n_requests,
    }


def measure_trace_overhead(cfg, n_requests: int = 192,
                           buckets: Sequence[int] = (1, 4, 16),
                           run_dir: Optional[str] = None) -> dict:
    """The tracing tax, measured: closed-loop request rate through one
    warmed service with the sampler OFF (``trace_sample=0`` — contexts
    still mint, nothing flushes) vs fully ON (``trace_sample=1`` —
    every request's admit/dispatch/done lands in the stream), same
    session so the service/executables are identical. The returned
    ``trace_overhead_pct`` is pinned (max) in the bench gate: tracing
    can never silently grow a hot-path cost. Both phases run with the
    sink active, so the delta isolates the TRACING emission cost rather
    than file-I/O-in-general. ``run_dir`` None uses a throwaway dir."""
    import shutil
    import tempfile

    if obs.active():
        # The probe owns the process-wide obs state (it installs and
        # then CLOSES its own run); silently tearing down the caller's
        # live run — leaving every later emit dark — is worse than a
        # refusal naming the precondition.
        raise RuntimeError(
            "measure_trace_overhead installs and closes its own obs "
            "run; close_run() the active run first"
        )
    tmp = run_dir or tempfile.mkdtemp(prefix="trace_overhead_")
    obs.init_run(tmp, extra={"cmd": "trace_overhead"}, process_index=0)
    # slo_p99_ms=inf: the closed-loop burst queues requests for far
    # longer than any real SLO, and a finite threshold would FORCE-
    # sample the tail even in the "dark" phase — both phases would then
    # do the same emission work and the probe would measure ~0 overhead
    # no matter what tracing costs. rules=() for the same reason: this
    # probe measures the tracing delta, not the alert engine.
    service = _build_service(
        cfg, buckets, max_wait_ms=2.0,
        queue_limit=max(256, n_requests), rules=(),
        slo_p99_ms=float("inf"),
    )
    grid = np.zeros((cfg.resolution,) * 3 + (1,), np.float32)

    def closed_loop_qps() -> float:
        t0 = time.perf_counter()
        futs = [service.submit_voxels(grid) for _ in range(n_requests)]
        for f in futs:
            f.result(timeout=120.0)
        return n_requests / (time.perf_counter() - t0)

    try:
        service.batcher.trace_sample = 0.0   # dark sampler, warm pass
        closed_loop_qps()                    # JIT/page-cache warmup
        dark = closed_loop_qps()
        service.batcher.trace_sample = 1.0   # every request sampled
        traced = closed_loop_qps()
    finally:
        service.drain()
        obs.close_run()
        if run_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return {
        "trace_overhead_pct": round(
            max(0.0, (dark - traced) / dark * 100.0), 2
        ) if dark > 0 else None,
        "trace_dark_qps": round(dark, 1),
        "trace_sampled_qps": round(traced, 1),
        "trace_overhead_requests": n_requests,
    }


def measure_incident_overhead(cfg, n_requests: int = 192,
                              buckets: Sequence[int] = (1, 4, 16),
                              run_dir: Optional[str] = None) -> dict:
    """The incident plane's steady-state tax, measured: closed-loop
    request rate through one warmed service with NO incident manager
    armed vs one armed on the run_dir (the event tap installed, the
    alert funnel watched), same session so the executables are
    identical. Both phases run fully traced (``trace_sample=1``) so the
    tap sits on the real per-request emit path — an incident manager's
    quiescent cost IS the tap consult per event plus the force-all flag
    read per request. ``rules=()`` so no alert ever fires and no
    incident opens: this pins the price of being ARMED, not of a
    capture (captures are rare, alert-gated, and run on their own
    thread). The returned ``incident_overhead_pct`` is pinned (max) in
    the bench gate."""
    import shutil
    import tempfile

    from featurenet_tpu.obs import incidents as _incidents

    if obs.active():
        raise RuntimeError(
            "measure_incident_overhead installs and closes its own obs "
            "run; close_run() the active run first"
        )
    tmp = run_dir or tempfile.mkdtemp(prefix="incident_overhead_")
    obs.init_run(tmp, extra={"cmd": "incident_overhead"}, process_index=0)
    service = _build_service(
        cfg, buckets, max_wait_ms=2.0,
        queue_limit=max(256, n_requests), rules=(),
        slo_p99_ms=float("inf"),
    )
    grid = np.zeros((cfg.resolution,) * 3 + (1,), np.float32)

    def closed_loop_qps() -> float:
        t0 = time.perf_counter()
        futs = [service.submit_voxels(grid) for _ in range(n_requests)]
        for f in futs:
            f.result(timeout=120.0)
        return n_requests / (time.perf_counter() - t0)

    manager = None
    try:
        service.batcher.trace_sample = 1.0   # tap on the hot emit path
        closed_loop_qps()                    # JIT/page-cache warmup
        dark = closed_loop_qps()             # no manager armed
        manager = _incidents.arm(tmp)
        armed = closed_loop_qps()
    finally:
        if manager is not None:
            _incidents.disarm(manager)
        service.drain()
        obs.close_run()
        if run_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return {
        "incident_overhead_pct": round(
            max(0.0, (dark - armed) / dark * 100.0), 2
        ) if dark > 0 else None,
        "incident_dark_qps": round(dark, 1),
        "incident_armed_qps": round(armed, 1),
        "incident_overhead_requests": n_requests,
    }


def measure_quality_overhead(cfg, n_requests: int = 192,
                             buckets: Sequence[int] = (1, 4, 16),
                             run_dir: Optional[str] = None) -> dict:
    """The model-quality telemetry tax, measured: closed-loop request
    rate through one warmed service with the quality plane OFF (the
    batcher's result hook detached — the zero-cost default every
    non-``--quality`` serve runs) vs ON (confidence/margin/entropy
    windows + drift score against a pinned uniform baseline + the
    flight recorder at its default sample rate), same session so the
    executables are identical. The returned ``quality_overhead_pct`` is
    pinned (max) in the bench gate: per-request quality math and
    capture must never silently grow a hot-path cost. The recorder's
    confidence floor is 0 for the probe — a random-init model predicts
    at ~uniform confidence, and force-capturing every request would
    measure disk bandwidth, not the telemetry tax on healthy traffic."""
    import shutil
    import tempfile

    from featurenet_tpu.data.synthetic import CLASS_NAMES
    from featurenet_tpu.obs.quality import QualityTracker
    from featurenet_tpu.serve.recorder import FlightRecorder, capture_dir

    if obs.active():
        raise RuntimeError(
            "measure_quality_overhead installs and closes its own obs "
            "run; close_run() the active run first"
        )
    tmp = run_dir or tempfile.mkdtemp(prefix="quality_overhead_")
    obs.init_run(tmp, extra={"cmd": "quality_overhead"}, process_index=0)
    num_classes = len(CLASS_NAMES)
    quality = QualityTracker(
        num_classes, baseline=[1.0 / num_classes] * num_classes
    )
    recorder = FlightRecorder(capture_dir(tmp), confidence_floor=0.0)
    # rules=() / slo inf, exactly like the trace probe: this measures
    # the per-request quality math + capture policy, not the alert
    # engine or forced SLO-breach sampling.
    service = _build_service(
        cfg, buckets, max_wait_ms=2.0,
        queue_limit=max(256, n_requests), rules=(),
        slo_p99_ms=float("inf"),
        quality=quality, recorder=recorder,
    )
    grid = np.zeros((cfg.resolution,) * 3 + (1,), np.float32)

    def closed_loop_qps() -> float:
        t0 = time.perf_counter()
        futs = [service.submit_voxels(grid) for _ in range(n_requests)]
        for f in futs:
            f.result(timeout=120.0)
        return n_requests / (time.perf_counter() - t0)

    hooks = (service.batcher.on_result, service.batcher.on_reject)
    try:
        service.batcher.trace_sample = 0.0   # isolate from the trace tax
        service.batcher.on_result = None     # quality plane detached
        service.batcher.on_reject = None
        closed_loop_qps()                    # JIT/page-cache warmup
        off = closed_loop_qps()
        service.batcher.on_result, service.batcher.on_reject = hooks
        on = closed_loop_qps()
        captured = recorder.stats()["captured"]
    finally:
        service.drain()
        obs.close_run()
        if run_dir is None:
            shutil.rmtree(tmp, ignore_errors=True)
    return {
        "quality_overhead_pct": round(
            max(0.0, (off - on) / off * 100.0), 2
        ) if off > 0 else None,
        "quality_off_qps": round(off, 1),
        "quality_on_qps": round(on, 1),
        "quality_overhead_requests": n_requests,
        "quality_captured": captured,
    }
