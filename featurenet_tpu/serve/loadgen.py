"""Poisson open-loop load generator for the serving front end.

Closed-loop measurement (``benchmark.measure_inference``) asks "how fast
can the device go when a full batch is always waiting" — bench r04's ~48k
inferences/sec/chip is that number, and no real traffic pattern can reach
it. This module asks the production question: at an *open-loop* arrival
rate — requests arrive on a Poisson clock whether or not the service has
finished the previous ones — what QPS does the service sustain, what do
the end-to-end p50/p99 look like, and how full do the dispatch buckets
run?

Open-loop discipline: arrivals are scheduled from the exponential
inter-arrival draws up front, and the generator sleeps only when it is
*ahead* of schedule — a slow service makes the generator submit late but
never slower, which is exactly how a load balancer treats a slow backend.
Rejections (``OverloadError``) are counted, not retried: retry storms are
a client policy, not a generator's.

``bench_serving`` is the bench.py entry point: a random-init weights
service (throughput is weight-agnostic) measured at a target fraction of
the closed-loop rate, returning the flat ``serve_*`` fields bench pins in
``gate_summary``.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from featurenet_tpu.obs.report import _pct
from featurenet_tpu.serve.batcher import OverloadError

# Bench loadgen sizing: offered load as a fraction of the measured
# closed-loop serving rate (deep enough to fill the big buckets, far
# enough from saturation that p99 measures the service, not the queue),
# and a cap so a Python-thread generator is never asked for arrival gaps
# it cannot schedule.
BENCH_LOAD_FRACTION = 0.3
BENCH_QPS_CAP = 8000.0


def poisson_load(service, qps: float, n_requests: int,
                 rng: Optional[np.random.Generator] = None,
                 grids: Optional[np.ndarray] = None,
                 timeout_s: float = 120.0) -> tuple[dict, list]:
    """Drive ``service`` with ``n_requests`` Poisson arrivals at rate
    ``qps``; returns ``(stats, futures)`` where ``futures`` are the
    accepted requests' resolved futures (request i's grid is
    ``grids[i % len(grids)]`` — callers verify answers against a
    reference forward). Every accepted request is waited on before the
    stats are computed, so ``sustained_qps`` is answered-requests over
    the full wall, not an admission rate."""
    if qps <= 0:
        raise ValueError(f"qps must be > 0, got {qps}")
    if rng is None:
        rng = np.random.default_rng(0)
    if grids is None:
        from featurenet_tpu.data.synthetic import generate_batch

        grids = generate_batch(
            rng, min(64, max(1, n_requests)), service.cfg.resolution
        )["voxels"]
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_requests))
    t0 = time.perf_counter()
    futures: list = []
    rejected = 0
    for i in range(n_requests):
        ahead = arrivals[i] - (time.perf_counter() - t0)
        if ahead > 0:
            time.sleep(ahead)
        try:
            futures.append(service.submit_voxels(grids[i % len(grids)]))
        except OverloadError:
            rejected += 1
    for fut in futures:
        fut.result(timeout=timeout_s)
    wall = time.perf_counter() - t0
    lats = sorted(f.latency_ms for f in futures)
    st = service.stats()
    stats = {
        "offered_qps": round(n_requests / float(arrivals[-1]), 1),
        "sustained_qps": round(len(futures) / wall, 1) if wall > 0 else None,
        "accepted": len(futures),
        "rejected": rejected,
        "p50_ms": round(_pct(lats, 50), 3) if lats else None,
        "p99_ms": round(_pct(lats, 99), 3) if lats else None,
        "occupancy": st["occupancy"],
        "by_bucket": st["by_bucket"],
    }
    return stats, futures


def bench_serving(cfg, qps: float, n_requests: int = 512,
                  buckets: Sequence[int] = (1, 4, 16, 64),
                  max_wait_ms: float = 5.0,
                  queue_limit: int = 256) -> dict:
    """The bench.py serving row: build a random-init service for ``cfg``
    (throughput is weight-agnostic, like ``measure_inference``), run the
    open-loop generator at ``qps``, drain, and return flat ``serve_*``
    fields for the gate summary."""
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.runtime.registry import build_model
    from featurenet_tpu.serve.service import InferenceService

    R = cfg.resolution
    variables = build_model(cfg).init(
        jax.random.key(0), jnp.zeros((1, R, R, R, 1), jnp.float32),
        train=False,
    )
    pred = Predictor(
        variables["params"], variables["batch_stats"], cfg,
        batch=max(buckets),
    )
    service = InferenceService(
        pred, buckets=buckets, max_wait_ms=max_wait_ms,
        queue_limit=queue_limit,
    )
    try:
        stats, _ = poisson_load(
            service, qps=qps, n_requests=n_requests,
            rng=np.random.default_rng(0),
        )
    finally:
        service.drain()
    return {
        "serve_qps_offered": stats["offered_qps"],
        "serve_qps_sustained": stats["sustained_qps"],
        "serve_p50_ms": stats["p50_ms"],
        "serve_p99_ms": stats["p99_ms"],
        "serve_occupancy": stats["occupancy"],
        "serve_rejected": stats["rejected"],
        "serve_buckets": {str(k): v for k, v in stats["by_bucket"].items()},
        "serve_requests": n_requests,
    }
