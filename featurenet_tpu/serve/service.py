"""The always-on inference service: bucketed AOT executables behind the
continuous batcher, with SLO-gated drain.

``InferenceService`` glues the three serving layers this package exists
to combine:

- **A warm bucket ladder**: one ``serve``/``serve_int8`` executable per
  configured bucket, built through the runtime registry at construction
  (``Predictor.program_for``) — with ``Config.exec_cache_dir`` set they
  deserialize from the persistent cache. After construction, no request
  ever pays an XLA compile; the load-gen e2e asserts this via
  ``program_compile`` events.
- **The continuous batcher** (``serve.batcher``): flush on max-batch or
  max-wait, pad to the smallest fitting bucket, de-mux per request,
  fast-reject under overload.
- **The upload path**: ``submit_stl_bytes`` takes raw STL bytes (a CAD
  part as it arrives over the wire), parses (``data.stl.parse_stl``) and
  voxelizes (``data.voxelize``) it host-side in the caller's thread, and
  enqueues the grid — so the service accepts real parts, not
  pre-voxelized tensors, and the (comparatively slow) geometry work never
  blocks the dispatch thread.

SLO gating: the service installs alert rules over the serving windows
(``serving_p99_ms`` end-to-end latency, ``queue_wait_ms_p99`` queue wait
— ``serve_rules``; a custom ``Config.alert_rules`` spec replaces them).
``drain()`` flushes the final window cycle and reports which serving
alerts are still unresolved; its ``exit_code`` (0 clean, 2 on an active
serving alert) is what ``cli serve --drain`` and ``cli infer`` exit with,
so CI can gate on latency regressions the same way it gates on accuracy.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional, Sequence

import numpy as np

from featurenet_tpu import faults, obs
from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import windows as _windows
from featurenet_tpu.serve.batcher import (
    DEFAULT_BUCKETS,
    DEFAULT_MAX_WAIT_MS,
    DEFAULT_QUEUE_LIMIT,
    ContinuousBatcher,
    PendingRequest,
    normalize_buckets,
    normalize_lane,
)

# Default p99 end-to-end SLO for the built-in serving rules. Generous by
# design: the operator's real SLO arrives via --slo-p99-ms or a full
# --alert-rules spec; the default exists so an unconfigured service still
# notices a pathological tail.
DEFAULT_SLO_P99_MS = 250.0


def serve_rules(slo_p99_ms: float = DEFAULT_SLO_P99_MS) -> list:
    """The serving alert-rule set: the built-in defaults plus the two
    rules no batch workload has — end-to-end p99 latency against the SLO
    and queue-wait p99 (admission pressure building before latency
    blows)."""
    return list(_alerts.DEFAULT_RULES) + [
        _alerts.AlertRule("serving_p99_ms", ">", float(slo_p99_ms),
                          "critical"),
        _alerts.AlertRule("queue_wait_ms_p99", ">", float(slo_p99_ms),
                          "warning"),
    ]


class InferenceService:
    """Continuous-batching serving over a ``Predictor``'s checkpoint.

    Construction is the warmup: every bucket's executable builds (or
    loads from the exec cache) before the batcher accepts a request.
    ``rules=None`` installs ``serve_rules(slo_p99_ms)`` over the rolling
    windows; pass an explicit rule list (e.g. from a ``--alert-rules``
    spec) to take full control, or ``rules=()`` to leave whatever
    aggregator is already installed untouched.
    """

    def __init__(self, predictor, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
                 queue_limit: int = DEFAULT_QUEUE_LIMIT,
                 rules: Optional[Sequence] = None,
                 slo_p99_ms: float = DEFAULT_SLO_P99_MS,
                 emit_every_s: float = _windows.DEFAULT_EMIT_EVERY_S,
                 batch_queue_limit: Optional[int] = None,
                 replica: Optional[str] = None,
                 quality=None,
                 recorder=None,
                 run_dir: Optional[str] = None):
        self.predictor = predictor
        self.cfg = predictor.cfg
        self.buckets = normalize_buckets(buckets)
        # Model-quality plane (obs.quality / serve.recorder), both
        # optional: a QualityTracker feeding the confidence/drift
        # windows per answered request, and a FlightRecorder keeping a
        # replayable capture ring. Classification only — a segmentation
        # row is a label grid, not a probability vector.
        if (quality is not None or recorder is not None) \
                and self.cfg.task != "classify":
            raise ValueError(
                "quality telemetry and the flight recorder need a "
                f"classify checkpoint, got task={self.cfg.task!r}"
            )
        self.quality = quality
        self.recorder = recorder
        # The replica's name in a fleet (None when standalone): echoed in
        # overload error bodies and /healthz so a router — or a client
        # reading a 503 — can say WHICH backend rejected it.
        self.replica = replica
        # Forward ordinal for the replica_slow fault site (one replica's
        # forward drags — the latency failure mode the fleet's p99 gate
        # must survive, distinct from replica death).
        self._forwards = 0
        # Reload ordinal (the swap_corrupt / replica_loss_rollout fault
        # counters) + a lock so two concurrent /admin/reload calls can't
        # interleave restore work; the dispatch path never takes it.
        self._swaps = 0
        self._swap_lock = threading.Lock()
        # Rollout cordon: readiness drops while a hot-swap's restore/
        # cast runs so the fleet router steers new traffic to peers
        # (drain via spillover); requests already here keep being
        # answered by the OLD weights until the atomic flip.
        self._reload_cordon = False
        # Readiness (the /healthz split): a server is ready only between
        # warmup completing and drain beginning — today a warming or
        # draining process would answer "healthy" to a router probing
        # it, which is exactly when it must not receive traffic.
        self._t_start = time.perf_counter()
        self._ready = False
        # AOT warmup: one serve build per bucket through the runtime
        # registry (memoized in Predictor._programs, which _forward
        # re-resolves per dispatch). This loop is the whole reason no
        # request ever sees a compile — every shape the batcher can
        # dispatch exists now. The per-bucket cost counters captured at
        # build feed the batcher's MFU fold (obs.perf) below.
        costs = {
            b: getattr(predictor.program_for(b), "cost", None)
            for b in self.buckets
        }
        if rules is None:
            rules = serve_rules(slo_p99_ms)
            if quality is not None:
                from featurenet_tpu.obs.quality import quality_rules

                # Confidence collapse always; drift only when a baseline
                # is pinned (a drift rule with nothing to drift FROM
                # would never see a sample and never fire or resolve).
                rules = list(rules) + list(quality_rules(
                    with_drift=quality.baseline is not None
                ))
        if rules:
            _windows.install(_windows.WindowAggregator(
                rules=list(rules), emit_every_s=emit_every_s
            ))
        from featurenet_tpu.obs import perf as _perf

        # Priority lanes: the batch lane defaults to HALF the admission
        # bound, so deferrable bulk can never starve interactive traffic
        # of queue room — the documented shed order (batch first).
        if batch_queue_limit is None:
            batch_queue_limit = max(1, queue_limit // 2)
        self.batcher = ContinuousBatcher(
            self._forward, buckets=self.buckets, max_wait_ms=max_wait_ms,
            queue_limit=queue_limit,
            lane_limits={"batch": int(batch_queue_limit)},
            cost_for=costs.get, peaks=_perf.local_device_peaks(),
            # Request tracing (obs.tracing): the config's healthy-traffic
            # sampling rate; a request breaching the serving SLO is
            # always sampled regardless (the p99 EXEMPLARS matter as
            # much as the p99).
            trace_sample=getattr(self.cfg, "trace_sample", 1.0),
            trace_slo_ms=float(slo_p99_ms),
            on_result=self._on_result
            if (quality is not None or recorder is not None) else None,
            on_reject=self._on_reject if recorder is not None else None,
        )
        # Incident plane (obs.incidents): with a run_dir this service
        # owns the process-wide incident manager — an SLO alert firing
        # over the windows above now freezes a diagnostic bundle under
        # <run_dir>/incidents/ instead of being one line in the log.
        self._incidents = None
        if run_dir is not None:
            from featurenet_tpu.obs import incidents as _incidents

            self._incidents = _incidents.arm(run_dir)
        obs.emit("serve_start", buckets=list(self.buckets),
                 max_wait_ms=float(max_wait_ms), queue_limit=int(queue_limit))
        self._ready = True

    # -- the dispatch hot path ----------------------------------------------
    def _forward(self, bucket: int, padded: np.ndarray):
        self._forwards += 1
        if faults.maybe_fail("replica_slow", request=self._forwards):
            # One replica's forward drags (thermal throttle, a noisy
            # neighbor, a stuck readback): latency, not death — the
            # failure mode the SLO alerts and the fleet's least-queue
            # routing exist for, and one no crash path ever exercises.
            time.sleep(faults.SLOW_SLEEP_S)
        # lint: allow-host-sync(the readback IS the served response)
        return np.asarray(self.predictor.forward_padded(padded, batch=bucket))

    # -- model-quality hooks (batcher callbacks; telemetry, never
    # load-bearing — the batcher swallows anything these raise) --------------
    def _on_result(self, p, row, total_ms: float, outcome: str) -> None:
        """Per answered request: reduce the probability row to floats,
        feed the quality tracker, and offer the request to the flight
        recorder. Runs on the single dispatcher thread."""
        confidence = label = None
        if row is not None:
            from featurenet_tpu.obs.quality import confidence_stats

            # lint: allow-host-sync(row is a host array post-readback)
            probs = np.asarray(row, np.float32)
            label = int(probs.argmax())
            confidence, margin, entropy = confidence_stats(probs.tolist())
            if self.quality is not None:
                self.quality.observe(label, confidence, margin, entropy)
        if self.recorder is not None:
            self.recorder.maybe_capture(
                p.voxels, p.trace_id, label=label, confidence=confidence,
                total_ms=total_ms, outcome=outcome,
            )

    def _on_reject(self, p) -> None:
        """Per admission rejection: rejected requests are always worth a
        capture — they are what the operator replays after a 503 storm."""
        self.recorder.maybe_capture(
            p.voxels, p.trace_id, outcome="rejected",
        )

    # -- request entry points ------------------------------------------------
    def submit_voxels(self, grid: np.ndarray,
                      trace_id: Optional[str] = None,
                      lane: str = "interactive") -> PendingRequest:
        """Enqueue one ``[R,R,R]`` (or ``[R,R,R,1]``) occupancy grid;
        returns its future. ``OverloadError`` at the admission bound (or
        the request's lane bound — ``batch`` sheds first). ``trace_id``
        adopts a caller-supplied trace id (propagation); None mints one
        at admission."""
        # lint: allow-host-sync(host-side request payload, never on device)
        g = np.asarray(grid, dtype=np.float32)
        if g.ndim == 3:
            g = g[..., None]
        R = self.cfg.resolution
        if g.shape != (R, R, R, 1):
            raise ValueError(
                f"expected one [{R},{R},{R}(,1)] grid, got {g.shape}"
            )
        return self.batcher.submit(g, trace_id=trace_id,
                                   lane=normalize_lane(lane))

    def submit_stl_bytes(self, data: bytes, fill: bool = True,
                         trace_id: Optional[str] = None,
                         lane: str = "interactive") -> PendingRequest:
        """The upload path: raw STL bytes → parse → normalize+voxelize →
        enqueue. Geometry runs in the caller's thread (an HTTP worker),
        never the dispatch thread; malformed bytes raise ``ValueError``
        before anything is admitted."""
        from featurenet_tpu.data.stl import parse_stl
        from featurenet_tpu.data.voxelize import voxelize

        tris = parse_stl(data)
        grid = voxelize(tris, self.cfg.resolution, fill=fill)
        # lint: allow-precision(wire contract: the serve input edge is fp32)
        return self.submit_voxels(grid.astype(np.float32),
                                  trace_id=trace_id, lane=lane)

    def format_row(self, row: np.ndarray) -> dict:
        """One request's output row as the wire response: class + top-3
        for classify checkpoints, per-class feature-voxel counts for
        segment ones."""
        from featurenet_tpu.data.synthetic import CLASS_NAMES

        if self.cfg.task == "segment":
            counts = np.bincount(
                # lint: allow-host-sync(row is a host array post-readback)
                np.asarray(row, np.int32).ravel(),
                minlength=len(CLASS_NAMES) + 1,
            )
            return {
                "voxel_counts": {
                    (CLASS_NAMES[c - 1] if c - 1 < len(CLASS_NAMES)
                     else f"class_{c - 1}"): int(counts[c])
                    for c in range(1, len(counts))
                    if counts[c]
                },
            }
        # lint: allow-host-sync(row is already a host array — see above)
        probs = np.asarray(row, np.float32)
        label = int(probs.argmax())
        order = np.argsort(probs)[::-1][:3]
        return {
            "label": label,
            "class_name": CLASS_NAMES[label],
            "prob": float(probs[label]),
            "top3": [(CLASS_NAMES[int(i)], float(probs[i])) for i in order],
        }

    def predict(self, fut: PendingRequest,
                timeout: Optional[float] = None) -> dict:
        return self.format_row(fut.result(timeout))

    # -- lifecycle -----------------------------------------------------------
    def stats(self) -> dict:
        st = self.batcher.stats()
        if self.quality is not None:
            st["quality"] = self.quality.stats()
        if self.recorder is not None:
            st["capture"] = self.recorder.stats()
        return st

    def ready(self) -> bool:
        """True only between warmup completing and drain beginning —
        the /healthz readiness verdict a fleet router keys traffic off.
        Also False for the duration of a weight hot-swap: the router
        drains the replica through its spillover path while the new
        generation is restored and cast."""
        return self._ready and not self._reload_cordon

    def reloading(self) -> bool:
        """True while ``reload`` is mid-swap — the replica is cordoned
        (not ready) but alive and working, so liveness heartbeats must
        keep beating."""
        return self._reload_cordon

    def health(self) -> dict:
        """The /healthz payload: the readiness split plus uptime and
        the last rolling-window emission seq (a monitor can tell a
        fresh server from one whose windows have moved)."""
        out = {
            "ready": self.ready(),
            "uptime_s": round(time.perf_counter() - self._t_start, 3),
            "window_seq": _windows.last_seq(),
            "queue_depth": self.batcher.stats()["queue_depth"],
        }
        if self.replica is not None:
            out["replica"] = self.replica
        # Version tags (the rollout plane): which weights THIS replica is
        # serving right now, and where they came from — the orchestrator
        # reads the mixed-version window straight off /healthz, and the
        # checkpoint_dir is what a rollback re-submits.
        out["model_version"] = getattr(
            self.predictor, "model_version", "unversioned"
        )
        ckpt = getattr(self.predictor, "checkpoint_dir", None)
        if ckpt is not None:
            out["checkpoint_dir"] = ckpt
        return out

    def reload(self, checkpoint_dir: str) -> dict:
        """Zero-downtime weight hot-swap (`POST /admin/reload`): verify
        the candidate checkpoint's checksum sidecar, then flip the
        predictor's serving weights via ``Predictor.swap_params`` — the
        restore/cast work runs HERE (an HTTP worker thread), never the
        dispatch thread, and the flip is one atomic reference move, so
        requests keep being answered throughout. Any failure (checksum
        mismatch, identity mismatch, unreadable checkpoint) raises
        BEFORE the flip: the replica is never half-swapped, it keeps
        serving the old generation and the caller gets a structured
        refusal. Every attempt — either way — is a ``swap`` event."""
        from featurenet_tpu.train.checkpoint import (
            CheckpointManager,
            ChecksumMismatch,
        )

        with self._swap_lock:
            self._swaps += 1
            n = self._swaps
            from_version = getattr(
                self.predictor, "model_version", "unversioned"
            )
            if faults.maybe_fail("replica_loss_rollout", swap=n):
                # Death mid-reload, no drain — the rollout orchestrator's
                # worst case: the replica vanishes while nominally
                # swapping, the manager respawns it on the OLD argv, and
                # the orchestrator must detect and roll peers back.
                os.kill(os.getpid(), signal.SIGKILL)
            t0 = time.perf_counter()
            self._reload_cordon = True
            try:
                mgr = CheckpointManager(checkpoint_dir)
                try:
                    step = mgr.latest_step()
                    if step is None:
                        raise ValueError(
                            "no finalized checkpoint step in "
                            f"{checkpoint_dir!r}"
                        )
                    if faults.maybe_fail("swap_corrupt", swap=n):
                        # The candidate arrives checksum-mismatched (bit
                        # rot / torn copy on the deploy path) — same
                        # refusal the real verification below raises.
                        raise ChecksumMismatch(
                            "injected swap_corrupt: candidate checkpoint "
                            "fails content verification"
                        )
                    mgr.verify(step)
                finally:
                    mgr.close()
                version = self.predictor.swap_params(checkpoint_dir)
            except Exception as e:
                self._reload_cordon = False
                obs.emit(
                    "swap", ok=False, from_version=from_version,
                    swap_ms=round((time.perf_counter() - t0) * 1e3, 3),
                    checkpoint_dir=str(checkpoint_dir),
                    error=f"{type(e).__name__}: {e}",
                )
                raise
            self._reload_cordon = False
            swap_ms = round((time.perf_counter() - t0) * 1e3, 3)
            obs.emit("swap", ok=True, from_version=from_version,
                     swap_ms=swap_ms, to_version=version,
                     checkpoint_dir=str(checkpoint_dir))
            return {
                "ok": True,
                "model_version": version,
                "from_version": from_version,
                "swap_ms": swap_ms,
            }

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Stop accepting, answer everything admitted, flush the final
        window cycle, and report the SLO verdict: ``exit_code`` is 2 when
        a serving alert (``alerts.is_serving_metric``) is still
        unresolved at drain time — the CI latency gate — or when the
        batcher's drain timed out with admitted requests unanswered;
        else 0."""
        # Readiness drops the moment drain BEGINS: a router probing
        # /healthz must stop routing here before the queue empties.
        self._ready = False
        st = self.batcher.drain(timeout_s)
        _windows.flush()
        # The final window cycle above may have resolved serving alerts
        # (closing their incidents through the tap); disarm AFTER it so
        # durations cover the real incident window.
        if self._incidents is not None:
            from featurenet_tpu.obs import incidents as _incidents

            st["incidents"] = self._incidents.stats()
            _incidents.disarm(self._incidents)
        if self.recorder is not None:
            self.recorder.close()
            st["capture"] = self.recorder.stats()
        if self.quality is not None:
            st["quality"] = self.quality.stats()
        active = [
            m for m in _windows.active_alerts()
            if _alerts.is_serving_metric(m)
        ]
        st["active_serving_alerts"] = active
        st["exit_code"] = 2 if (active or st["drain_timeout"]) else 0
        return st
