"""Stdlib HTTP front end for the inference service.

One endpoint that matters: ``POST /predict`` with raw STL bytes as the
body returns the prediction as JSON — the end-to-end upload path (bytes →
parse → voxelize → continuous batcher → compiled forward → response).
Status codes carry the admission contract:

- ``200`` — answered; body is ``InferenceService.format_row`` output.
- ``400`` — unparseable STL; the body names the parse failure.
- ``503`` — overload fast-reject; body is ``OverloadError.response``
  (``{"error": "overload", "queue_depth": ..., "limit": ...}``) so a
  load balancer can back off on structure, not on string-matching.
- ``504`` — admitted but not answered within the handler timeout.

``GET /stats`` (alias ``/healthz``) returns the batcher counters —
served/rejected/occupancy/queue depth — for external monitoring.

Threading model: ``ThreadingHTTPServer`` with daemon threads; each
request thread does its own STL parse + voxelization (host-side geometry
must never block the dispatch thread) and then parks on its future. The
batcher coalesces across request threads — concurrency IS the batch
shape.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from featurenet_tpu.serve.batcher import OverloadError

DEFAULT_REQUEST_TIMEOUT_S = 60.0


def make_server(service, host: str = "127.0.0.1", port: int = 0,
                request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S
                ) -> ThreadingHTTPServer:
    """Build (but do not start) the HTTP server; ``port=0`` binds an
    ephemeral port (read it back from ``server_address``). Run with
    ``serve_forever()`` — typically on a daemon thread — and stop with
    ``shutdown()`` before draining the service."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
            pass  # access logging is the obs layer's job, not stderr's

        def _json(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib name)
            if self.path in ("/stats", "/healthz"):
                self._json(200, {"ok": True, **service.stats()})
                return
            self._json(404, {"error": "not_found",
                             "endpoints": ["POST /predict", "GET /stats"]})

        def do_POST(self):  # noqa: N802 (stdlib name)
            if self.path != "/predict":
                self._json(404, {"error": "not_found",
                                 "endpoints": ["POST /predict",
                                               "GET /stats"]})
                return
            length = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(length)
            try:
                fut = service.submit_stl_bytes(data)
            except OverloadError as e:
                self._json(503, e.response)
                return
            except ValueError as e:
                self._json(400, {"error": "bad_stl", "detail": str(e)})
                return
            except RuntimeError as e:
                # A handler thread that slipped in between shutdown()
                # and drain() gets the batcher's "draining" refusal —
                # answer it structurally like any other rejection, not
                # with a dropped socket. (OverloadError is a
                # RuntimeError; its clause above must come first.)
                self._json(503, {"error": "draining", "detail": str(e)})
                return
            try:
                row = fut.result(timeout=request_timeout_s)
            except TimeoutError:
                self._json(504, {"error": "timeout",
                                 "timeout_s": request_timeout_s})
                return
            except RuntimeError as e:
                self._json(500, {"error": "forward_failed",
                                 "detail": str(e)})
                return
            self._json(200, service.format_row(row))

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.daemon_threads = True
    return srv
