"""Stdlib HTTP front end for the inference service.

One endpoint that matters: ``POST /predict`` with raw STL bytes as the
body returns the prediction as JSON — the end-to-end upload path (bytes →
parse → voxelize → continuous batcher → compiled forward → response).
Status codes carry the admission contract:

- ``200`` — answered; body is ``InferenceService.format_row`` output.
- ``400`` — unparseable STL; the body names the parse failure.
- ``503`` — overload fast-reject; body is ``OverloadError.response``
  (``{"error": "overload", "queue_depth": ..., "limit": ...,
  "lane": ..., "retry_after_s": ...}`` plus ``"replica"`` when the
  service has a fleet identity) with a ``Retry-After`` header carrying
  the same backoff hint, so a load balancer can back off on structure,
  not on string-matching.
- ``504`` — admitted but not answered within the handler timeout.

``POST /predict_voxels`` is the pre-voxelized sibling: raw float32
little-endian bytes of one ``[R,R,R]`` occupancy grid (no geometry work
server-side — the fleet load generator's path). Both POST endpoints read
the ``X-Featurenet-Priority`` header (``interactive`` default /
``batch``): batch rides the shed-first lane of the batcher's admission.

``POST /predict_voxels_stream`` is the batched sibling: FeatureNet's
real unit of work is a corpus of parts, not a singleton, and a
part-per-request protocol pays one round trip per part. The stream body
is a sequence of length-prefixed frames — ``<u32 little-endian payload
length><payload>`` repeated, each payload one ``/predict_voxels`` grid —
under one ``Content-Length``; the response streams back one JSON line
per frame (chunked transfer) in frame order as each resolves, so a
client pipelines hundreds of parts over ONE socket instead of hundreds
of handshakes. Frames fan into the continuous batcher as independent
lane-tagged requests, each with its own trace id tied to the stream id
(``<stream>.<frame>``); a per-frame overload/timeout/forward error is a
structured error LINE for that frame, never a dropped stream. A torn
frame (truncated prefix or short payload) is a structured 400 — the
byte stream is unreliable past that point, so the connection closes.

**Keep-alive contract.** The server speaks ``HTTP/1.1``: every response
carries an exact ``Content-Length`` (or chunked framing, for the stream
endpoint), so one connection serves any number of sequential requests —
the connection-churn half of fleet latency at small payloads. The
server closes a connection in exactly two cases: a *draining* 503 (the
service is going away; ``Connection: close`` tells the pool to retire
the channel, not retry it) and a torn stream. Overload 503s keep the
connection open — the rejection is transient and the polite retry
should ride the warm channel.

Trace propagation: a caller-supplied ``X-Featurenet-Trace`` request
header is adopted as the request's trace id (``obs.tracing``) and echoed
back on EVERY ``/predict`` response — 200s, overload 503s, even 400s —
so a fleet router (or any upstream) can follow one request across the
process hop. Without the header the server mints an id and the echo
tells the caller what to grep for in the run log.

``GET /stats`` returns the batcher counters — served/rejected/occupancy/
queue depth. ``GET /healthz`` is the READINESS endpoint: ``{"ready":
bool, "uptime_s": ..., "window_seq": ...}`` with HTTP 503 while not
ready — false during warmup and from the moment drain begins, so a
router's probe stops sending traffic before the queue empties (a
warming or draining server must not answer "healthy"). ``GET /metrics``
is the stdlib Prometheus-text exporter (``serve.metrics``): the same
counters and rolling-window quantiles the SLO alerts fire on, scrape-
able by the fleet router and external monitors.

Threading model: ``ThreadingHTTPServer`` with daemon threads; each
request thread does its own STL parse + voxelization (host-side geometry
must never block the dispatch thread) and then parks on its future. The
batcher coalesces across request threads — concurrency IS the batch
shape.
"""

from __future__ import annotations

import json
import struct
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from featurenet_tpu.obs.tracing import (
    TRACE_HEADER,
    mint_trace_id,
    normalize_trace_id,
)
from featurenet_tpu.serve.batcher import OverloadError, normalize_lane

DEFAULT_REQUEST_TIMEOUT_S = 60.0

# Request-priority header: "interactive" (default) or "batch". Unknown
# values normalize to interactive (the stricter admission) — a typo'd
# priority must never be treated as shed-first bulk.
PRIORITY_HEADER = "X-Featurenet-Priority"

_ENDPOINTS = ["POST /predict", "POST /predict_voxels",
              "POST /predict_voxels_stream", "POST /admin/reload",
              "GET /stats", "GET /healthz", "GET /metrics"]

# A frame trace id is "<stream>.<frame index>" and must still satisfy
# the trace-id grammar (≤64 chars): adopt the caller's stream id only
# when the suffixed form is guaranteed to fit, else mint (16 hex chars).
_MAX_STREAM_ID_LEN = 48


def _parse_voxels(data: bytes, resolution: int):
    """One ``[R,R,R]`` float32 occupancy grid from raw little-endian
    bytes (the ``/predict_voxels`` wire shape). Size-checked before the
    reshape so a short body is a 400, not a numpy traceback."""
    import numpy as np

    want = resolution ** 3 * 4
    if len(data) != want:
        raise ValueError(
            f"expected {want} bytes (float32 [{resolution}]^3 grid), "
            f"got {len(data)}"
        )
    return np.frombuffer(data, dtype="<f4").reshape(
        (resolution,) * 3
    )


def make_server(service, host: str = "127.0.0.1", port: int = 0,
                request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S
                ) -> ThreadingHTTPServer:
    """Build (but do not start) the HTTP server; ``port=0`` binds an
    ephemeral port (read it back from ``server_address``). Run with
    ``serve_forever()`` — typically on a daemon thread — and stop with
    ``shutdown()`` before draining the service."""

    class Handler(BaseHTTPRequestHandler):
        # Keep-alive by default: HTTP/1.1 + exact Content-Length on
        # every response means the connection outlives the request —
        # the pool/loadgen reuse it instead of re-handshaking.
        protocol_version = "HTTP/1.1"
        # Socket deadline: bounds how long an idle keep-alive channel
        # may park a handler thread (the pool's max-age retires its side
        # well before this; a slow client mid-upload hits it too).
        timeout = request_timeout_s + 15.0

        def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
            pass  # access logging is the obs layer's job, not stderr's

        def _json(self, code: int, payload: dict,
                  trace_id: str | None = None,
                  retry_after_s: float | None = None,
                  close: bool = False) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                # The propagation echo: whatever id this request ran
                # under (supplied or minted) comes back on every
                # outcome, so the caller can correlate and a router
                # can follow the hop.
                self.send_header(TRACE_HEADER, trace_id)
            if retry_after_s is not None:
                # Decimal seconds (our clients — loadgen and the fleet
                # router — parse float; integer-only parsers read the
                # leading digits, still a sane backoff).
                self.send_header("Retry-After", f"{retry_after_s:.3f}")
            if close:
                # The keep-alive contract's one deliberate hangup: a
                # DRAINING server (or a torn stream) ends the channel —
                # send_header("Connection", "close") also flips
                # close_connection so the handler loop exits.
                self.send_header("Connection", "close")
            self.end_headers()
            self.wfile.write(body)

        def _reject_body(self, payload: dict) -> dict:
            # Every rejection body names the replica when the service
            # has a fleet identity — the router (and a client holding a
            # 503) can then say WHICH backend refused, not just "one
            # did".
            if getattr(service, "replica", None) is not None:
                return {**payload, "replica": service.replica}
            return payload

        def do_GET(self):  # noqa: N802 (stdlib name)
            if self.path == "/stats":
                self._json(200, {"ok": True, **service.stats()})
                return
            if self.path == "/healthz":
                # Readiness split: 503 while warming or draining — the
                # status code is what a router's probe keys off, the
                # body says why.
                health = service.health()
                self._json(200 if health["ready"] else 503, health)
                return
            if self.path == "/metrics":
                from featurenet_tpu.serve.metrics import (
                    CONTENT_TYPE,
                    render_metrics,
                )

                body = render_metrics(service).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._json(404, {"error": "not_found",
                             "endpoints": _ENDPOINTS})

        def do_POST(self):  # noqa: N802 (stdlib name)
            if self.path == "/predict_voxels_stream":
                self._stream()
                return
            if self.path == "/admin/reload":
                self._admin_reload()
                return
            if self.path not in ("/predict", "/predict_voxels"):
                # Drain the body before answering: an unread body on a
                # keep-alive channel would be parsed as the NEXT
                # request's request line (channel desync).
                self.rfile.read(
                    int(self.headers.get("Content-Length") or 0)
                )
                self._json(404, {"error": "not_found",
                                 "endpoints": _ENDPOINTS})
                return
            # Adopt (or mint) the trace id BEFORE the parse: even a 400
            # echoes the id the caller keyed its bookkeeping off.
            trace_id = normalize_trace_id(
                self.headers.get(TRACE_HEADER)
            )
            lane = normalize_lane(self.headers.get(PRIORITY_HEADER))
            length = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(length)
            try:
                if self.path == "/predict_voxels":
                    # The pre-voxelized fast path (fleet loadgen, a
                    # router fronting voxel-native clients): raw float32
                    # little-endian bytes of one [R,R,R] occupancy grid.
                    fut = service.submit_voxels(
                        _parse_voxels(data, service.cfg.resolution),
                        trace_id=trace_id, lane=lane,
                    )
                else:
                    fut = service.submit_stl_bytes(
                        data, trace_id=trace_id, lane=lane
                    )
            except OverloadError as e:
                self._json(503, self._reject_body(e.response),
                           trace_id=e.trace_id,
                           retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._json(400, {"error": "bad_stl"
                                 if self.path == "/predict"
                                 else "bad_voxels", "detail": str(e)},
                           trace_id=trace_id)
                return
            except RuntimeError as e:
                # A handler thread that slipped in between shutdown()
                # and drain() gets the batcher's "draining" refusal —
                # answer it structurally like any other rejection, not
                # with a dropped socket, and CLOSE the channel: the
                # server is going away, so a pooled client must retire
                # it rather than park a retry on a corpse.
                # (OverloadError is a RuntimeError; its clause above
                # must come first.)
                self._json(503, self._reject_body(
                    {"error": "draining", "detail": str(e)}
                ), trace_id=trace_id,
                    retry_after_s=service.batcher.retry_after_s,
                    close=True)
                return
            try:
                row = fut.result(timeout=request_timeout_s)
            except TimeoutError:
                self._json(504, {"error": "timeout",
                                 "timeout_s": request_timeout_s},
                           trace_id=fut.trace_id)
                return
            except RuntimeError as e:
                self._json(500, {"error": "forward_failed",
                                 "detail": str(e)}, trace_id=fut.trace_id)
                return
            self._json(200, service.format_row(row),
                       trace_id=fut.trace_id)

        def _admin_reload(self) -> None:
            """``POST /admin/reload {"checkpoint_dir": ...}``: the
            zero-downtime weight hot-swap (``InferenceService.reload``).
            200 with the new ``model_version`` on success; 409 with a
            structured refusal when the swap is rejected (checksum
            mismatch, identity mismatch, unreadable checkpoint) — the
            replica then still serves the OLD generation, and the body
            says which one."""
            length = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(length)
            try:
                payload = json.loads(data.decode("utf-8")) if data else {}
            except ValueError as e:
                self._json(400, {"error": "bad_json", "detail": str(e)})
                return
            ckpt = payload.get("checkpoint_dir") \
                if isinstance(payload, dict) else None
            if not isinstance(ckpt, str) or not ckpt:
                self._json(400, {
                    "error": "bad_reload",
                    "detail": 'body must be {"checkpoint_dir": "<path>"}',
                })
                return
            try:
                out = service.reload(ckpt)
            except Exception as e:
                self._json(409, self._reject_body({
                    "error": "swap_refused",
                    "kind": type(e).__name__,
                    "detail": str(e),
                    "model_version": getattr(
                        service.predictor, "model_version", "unversioned"
                    ),
                }))
                return
            self._json(200, self._reject_body(out))

        # -- the streamed multi-part protocol ------------------------------
        def _read_exact(self, n: int) -> bytes:
            """Exactly ``n`` body bytes (a buffered socket read may come
            up short mid-frame); fewer means the peer hung up early —
            the torn-frame shape the caller turns into a 400."""
            chunks = []
            while n > 0:
                chunk = self.rfile.read(n)
                if not chunk:
                    break
                chunks.append(chunk)
                n -= len(chunk)
            return b"".join(chunks)

        def _chunk(self, data: bytes) -> None:
            """One chunked-transfer chunk (the response side of the
            stream: hex length, CRLF, payload, CRLF), flushed so the
            client sees each frame's line the moment it resolves."""
            self.wfile.write(f"{len(data):X}\r\n".encode("ascii")
                             + data + b"\r\n")
            self.wfile.flush()

        def _stream(self) -> None:
            """``POST /predict_voxels_stream``: length-prefixed float32
            frames in, one JSON line per frame out (chunked), every
            frame an independent lane-tagged batcher request with its
            own ``<stream>.<i>`` trace id. Framing errors are a
            structured 400 BEFORE any response line; per-frame failures
            (overload, timeout, forward error) are error LINES."""
            stream_id = normalize_trace_id(self.headers.get(TRACE_HEADER))
            if len(stream_id) > _MAX_STREAM_ID_LEN:
                stream_id = mint_trace_id()
            lane = normalize_lane(self.headers.get(PRIORITY_HEADER))
            remaining = int(self.headers.get("Content-Length") or 0)
            want = service.cfg.resolution ** 3 * 4
            frames: list = []  # (index, future | None, error dict | None)

            def torn(detail: str) -> None:
                # The byte stream is unreliable past a torn frame: the
                # channel closes with the 400 (admitted frames still
                # resolve server-side; their results are discarded).
                self._json(400, {
                    "error": "bad_stream", "detail": detail,
                    "frames_admitted": sum(
                        1 for _, fut, _ in frames if fut is not None
                    ),
                }, trace_id=stream_id, close=True)

            while remaining > 0:
                if remaining < 4:
                    torn(f"torn length prefix at frame {len(frames)}: "
                         f"{remaining} byte(s) left, need 4")
                    return
                prefix = self._read_exact(4)
                if len(prefix) < 4:
                    torn(f"body ended inside frame {len(frames)}'s "
                         "length prefix")
                    return
                remaining -= 4
                n = struct.unpack("<I", prefix)[0]
                if n != want:
                    torn(f"frame {len(frames)} declares {n} bytes; a "
                         f"[{service.cfg.resolution}]^3 float32 grid "
                         f"is {want}")
                    return
                if n > remaining:
                    torn(f"frame {len(frames)} declares {n} bytes but "
                         f"only {remaining} remain in the body")
                    return
                payload = self._read_exact(n)
                remaining -= len(payload)
                if len(payload) < n:
                    torn(f"body ended inside frame {len(frames)}'s "
                         f"payload ({len(payload)}/{n} bytes)")
                    return
                i = len(frames)
                trace_id = f"{stream_id}.{i}"
                try:
                    fut = service.submit_voxels(
                        _parse_voxels(payload, service.cfg.resolution),
                        trace_id=trace_id, lane=lane,
                    )
                    frames.append((i, fut, None))
                except OverloadError as e:
                    # A shed frame is that FRAME's structured error
                    # line, not a dead stream: the client learns which
                    # parts to resubmit without losing the socket.
                    frames.append((i, None, {
                        "trace": e.trace_id or trace_id,
                        **self._reject_body(e.response),
                    }))
                except RuntimeError as e:
                    frames.append((i, None, {
                        "trace": trace_id, "error": "draining",
                        "detail": str(e),
                    }))
            if not frames:
                self._json(400, {
                    "error": "bad_stream",
                    "detail": "empty stream (no frames in body)",
                }, trace_id=stream_id)
                return
            # Every frame read and admitted (or per-frame refused):
            # stream the response lines in frame order as each resolves.
            # One STREAM-level deadline, not one per frame: a wedged
            # service must bound the whole response at the request
            # timeout (later frames then time out immediately), never
            # frames × timeout with the client long gone.
            deadline = time.monotonic() + request_timeout_s
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header(TRACE_HEADER, stream_id)
            self.end_headers()
            for i, fut, err in frames:
                if err is not None:
                    line: dict = {"frame": i, **err}
                else:
                    try:
                        row = fut.result(timeout=max(
                            0.0, deadline - time.monotonic()
                        ))
                        line = {"frame": i, "trace": fut.trace_id,
                                **service.format_row(row)}
                    except TimeoutError:
                        line = {"frame": i, "trace": fut.trace_id,
                                "error": "timeout",
                                "timeout_s": request_timeout_s}
                    except RuntimeError as e:
                        line = {"frame": i, "trace": fut.trace_id,
                                "error": "forward_failed",
                                "detail": str(e)}
                try:
                    self._chunk(json.dumps(line).encode("utf-8") + b"\n")
                except OSError:
                    # The client hung up mid-stream: stop resolving
                    # lines for a dead socket (admitted frames still
                    # compute; their results are discarded).
                    self.close_connection = True
                    return
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.daemon_threads = True
    return srv
