"""Stdlib HTTP front end for the inference service.

One endpoint that matters: ``POST /predict`` with raw STL bytes as the
body returns the prediction as JSON — the end-to-end upload path (bytes →
parse → voxelize → continuous batcher → compiled forward → response).
Status codes carry the admission contract:

- ``200`` — answered; body is ``InferenceService.format_row`` output.
- ``400`` — unparseable STL; the body names the parse failure.
- ``503`` — overload fast-reject; body is ``OverloadError.response``
  (``{"error": "overload", "queue_depth": ..., "limit": ...,
  "lane": ..., "retry_after_s": ...}`` plus ``"replica"`` when the
  service has a fleet identity) with a ``Retry-After`` header carrying
  the same backoff hint, so a load balancer can back off on structure,
  not on string-matching.
- ``504`` — admitted but not answered within the handler timeout.

``POST /predict_voxels`` is the pre-voxelized sibling: raw float32
little-endian bytes of one ``[R,R,R]`` occupancy grid (no geometry work
server-side — the fleet load generator's path). Both POST endpoints read
the ``X-Featurenet-Priority`` header (``interactive`` default /
``batch``): batch rides the shed-first lane of the batcher's admission.

Trace propagation: a caller-supplied ``X-Featurenet-Trace`` request
header is adopted as the request's trace id (``obs.tracing``) and echoed
back on EVERY ``/predict`` response — 200s, overload 503s, even 400s —
so a fleet router (or any upstream) can follow one request across the
process hop. Without the header the server mints an id and the echo
tells the caller what to grep for in the run log.

``GET /stats`` returns the batcher counters — served/rejected/occupancy/
queue depth. ``GET /healthz`` is the READINESS endpoint: ``{"ready":
bool, "uptime_s": ..., "window_seq": ...}`` with HTTP 503 while not
ready — false during warmup and from the moment drain begins, so a
router's probe stops sending traffic before the queue empties (a
warming or draining server must not answer "healthy"). ``GET /metrics``
is the stdlib Prometheus-text exporter (``serve.metrics``): the same
counters and rolling-window quantiles the SLO alerts fire on, scrape-
able by the fleet router and external monitors.

Threading model: ``ThreadingHTTPServer`` with daemon threads; each
request thread does its own STL parse + voxelization (host-side geometry
must never block the dispatch thread) and then parks on its future. The
batcher coalesces across request threads — concurrency IS the batch
shape.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from featurenet_tpu.obs.tracing import TRACE_HEADER, normalize_trace_id
from featurenet_tpu.serve.batcher import OverloadError, normalize_lane

DEFAULT_REQUEST_TIMEOUT_S = 60.0

# Request-priority header: "interactive" (default) or "batch". Unknown
# values normalize to interactive (the stricter admission) — a typo'd
# priority must never be treated as shed-first bulk.
PRIORITY_HEADER = "X-Featurenet-Priority"

_ENDPOINTS = ["POST /predict", "POST /predict_voxels", "GET /stats",
              "GET /healthz", "GET /metrics"]


def _parse_voxels(data: bytes, resolution: int):
    """One ``[R,R,R]`` float32 occupancy grid from raw little-endian
    bytes (the ``/predict_voxels`` wire shape). Size-checked before the
    reshape so a short body is a 400, not a numpy traceback."""
    import numpy as np

    want = resolution ** 3 * 4
    if len(data) != want:
        raise ValueError(
            f"expected {want} bytes (float32 [{resolution}]^3 grid), "
            f"got {len(data)}"
        )
    return np.frombuffer(data, dtype="<f4").reshape(
        (resolution,) * 3
    )


def make_server(service, host: str = "127.0.0.1", port: int = 0,
                request_timeout_s: float = DEFAULT_REQUEST_TIMEOUT_S
                ) -> ThreadingHTTPServer:
    """Build (but do not start) the HTTP server; ``port=0`` binds an
    ephemeral port (read it back from ``server_address``). Run with
    ``serve_forever()`` — typically on a daemon thread — and stop with
    ``shutdown()`` before draining the service."""

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: N802 (stdlib name)
            pass  # access logging is the obs layer's job, not stderr's

        def _json(self, code: int, payload: dict,
                  trace_id: str | None = None,
                  retry_after_s: float | None = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if trace_id:
                # The propagation echo: whatever id this request ran
                # under (supplied or minted) comes back on every
                # outcome, so the caller can correlate and a router
                # can follow the hop.
                self.send_header(TRACE_HEADER, trace_id)
            if retry_after_s is not None:
                # Decimal seconds (our clients — loadgen and the fleet
                # router — parse float; integer-only parsers read the
                # leading digits, still a sane backoff).
                self.send_header("Retry-After", f"{retry_after_s:.3f}")
            self.end_headers()
            self.wfile.write(body)

        def _reject_body(self, payload: dict) -> dict:
            # Every rejection body names the replica when the service
            # has a fleet identity — the router (and a client holding a
            # 503) can then say WHICH backend refused, not just "one
            # did".
            if getattr(service, "replica", None) is not None:
                return {**payload, "replica": service.replica}
            return payload

        def do_GET(self):  # noqa: N802 (stdlib name)
            if self.path == "/stats":
                self._json(200, {"ok": True, **service.stats()})
                return
            if self.path == "/healthz":
                # Readiness split: 503 while warming or draining — the
                # status code is what a router's probe keys off, the
                # body says why.
                health = service.health()
                self._json(200 if health["ready"] else 503, health)
                return
            if self.path == "/metrics":
                from featurenet_tpu.serve.metrics import (
                    CONTENT_TYPE,
                    render_metrics,
                )

                body = render_metrics(service).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            self._json(404, {"error": "not_found",
                             "endpoints": _ENDPOINTS})

        def do_POST(self):  # noqa: N802 (stdlib name)
            if self.path not in ("/predict", "/predict_voxels"):
                self._json(404, {"error": "not_found",
                                 "endpoints": _ENDPOINTS})
                return
            # Adopt (or mint) the trace id BEFORE the parse: even a 400
            # echoes the id the caller keyed its bookkeeping off.
            trace_id = normalize_trace_id(
                self.headers.get(TRACE_HEADER)
            )
            lane = normalize_lane(self.headers.get(PRIORITY_HEADER))
            length = int(self.headers.get("Content-Length") or 0)
            data = self.rfile.read(length)
            try:
                if self.path == "/predict_voxels":
                    # The pre-voxelized fast path (fleet loadgen, a
                    # router fronting voxel-native clients): raw float32
                    # little-endian bytes of one [R,R,R] occupancy grid.
                    fut = service.submit_voxels(
                        _parse_voxels(data, service.cfg.resolution),
                        trace_id=trace_id, lane=lane,
                    )
                else:
                    fut = service.submit_stl_bytes(
                        data, trace_id=trace_id, lane=lane
                    )
            except OverloadError as e:
                self._json(503, self._reject_body(e.response),
                           trace_id=e.trace_id,
                           retry_after_s=e.retry_after_s)
                return
            except ValueError as e:
                self._json(400, {"error": "bad_stl"
                                 if self.path == "/predict"
                                 else "bad_voxels", "detail": str(e)},
                           trace_id=trace_id)
                return
            except RuntimeError as e:
                # A handler thread that slipped in between shutdown()
                # and drain() gets the batcher's "draining" refusal —
                # answer it structurally like any other rejection, not
                # with a dropped socket. (OverloadError is a
                # RuntimeError; its clause above must come first.)
                self._json(503, self._reject_body(
                    {"error": "draining", "detail": str(e)}
                ), trace_id=trace_id,
                    retry_after_s=service.batcher.retry_after_s)
                return
            try:
                row = fut.result(timeout=request_timeout_s)
            except TimeoutError:
                self._json(504, {"error": "timeout",
                                 "timeout_s": request_timeout_s},
                           trace_id=fut.trace_id)
                return
            except RuntimeError as e:
                self._json(500, {"error": "forward_failed",
                                 "detail": str(e)}, trace_id=fut.trace_id)
                return
            self._json(200, service.format_row(row),
                       trace_id=fut.trace_id)

    srv = ThreadingHTTPServer((host, port), Handler)
    srv.daemon_threads = True
    return srv
