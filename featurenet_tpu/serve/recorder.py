"""Flight recorder: a bounded, sampled capture ring of served requests.

The tracing layer keeps *timelines* (when a request queued, dispatched,
finished); nothing keeps the request *itself*. The recorder does: a
JSONL segment ring under ``<run_dir>/capture/`` where each line is one
served request — the voxel payload (bit-packed + base64: an occupancy
grid is 0/1, so 64³ costs ~32 KiB instead of a megabyte of float32),
its trace id, the prediction, the confidence, and why it was kept. The
ring is what ``cli replay`` re-scores against a candidate checkpoint /
precision / conv-backend: real traffic, replayable offline, bounded on
disk.

Capture policy is tail-biased like the tracing sampler: rejected
requests, forward errors, low-confidence predictions (below
``confidence_floor``), and SLO breaches are ALWAYS captured — those are
exactly the requests worth replaying — while healthy traffic is sampled
deterministically by trace-id hash (``obs.tracing.sampled``), so every
process in a fleet agrees on which requests to keep without
coordination.

Durability discipline is the tsdb's: O_APPEND fd, ONE ``os.write`` per
complete line (a crash tears at most the final line, which readers
skip), segments rotate at ``segment_bytes`` and prune oldest-first to
``max_bytes``. Capture is never load-bearing: the first OSError puts
the recorder in the dark — every later capture is a counter bump and
nothing else.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time
from typing import Optional

import numpy as np

from featurenet_tpu import obs
from featurenet_tpu.obs import tracing as _tracing

CAPTURE_DIRNAME = "capture"

DEFAULT_SAMPLE = 0.05
DEFAULT_CONFIDENCE_FLOOR = 0.35
DEFAULT_SEGMENT_BYTES = 1024 * 1024
DEFAULT_MAX_BYTES = 16 * 1024 * 1024

_SEG_PREFIX = "capture."
_SEG_SUFFIX = ".jsonl"
_SEG_WIDTH = 6


def capture_dir(run_dir: str) -> str:
    return os.path.join(run_dir, CAPTURE_DIRNAME)


def pack_grid(grid: np.ndarray) -> dict:
    """Occupancy grid → JSON-safe record: threshold to bits, pack, and
    base64. Lossless for 0/1 grids (the serving wire contract)."""
    g = np.asarray(grid)  # lint: allow-host-sync(capture serializes the grid to JSON — the readback IS the capture, and maybe_capture samples it off the p99 path)
    bits = np.packbits((g > 0.5).ravel())
    return {
        "shape": [int(s) for s in g.shape],
        "bits": base64.b64encode(bits.tobytes()).decode("ascii"),
    }


def unpack_grid(rec: dict) -> np.ndarray:
    """Inverse of ``pack_grid``: record → float32 occupancy grid."""
    shape = tuple(int(s) for s in rec["shape"])
    n = 1
    for s in shape:
        n *= s
    raw = np.frombuffer(base64.b64decode(rec["bits"]), np.uint8)
    return np.unpackbits(raw)[:n].reshape(shape).astype(np.float32)


def read_captures(path: str) -> list[dict]:
    """Every parseable capture record in a ring directory, segment order
    then line order. Torn tails and foreign lines are skipped, never
    raised — the same reader contract as the tsdb and the event loader."""
    try:
        names = os.listdir(path)
    except OSError:
        return []
    segs = []
    for n in names:
        if not (n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)):
            continue
        idx = n[len(_SEG_PREFIX): -len(_SEG_SUFFIX)]
        if idx.isdigit():
            segs.append((int(idx), os.path.join(path, n)))
    segs.sort()
    out = []
    for _idx, seg_path in segs:
        try:
            with open(seg_path, "rb") as fh:
                raw = fh.read()
        except OSError:
            continue
        lines = raw.split(b"\n")[:-1]  # drop the torn tail, if any
        for ln in lines:
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if isinstance(rec, dict) and "voxels" in rec:
                out.append(rec)
    return out


class FlightRecorder:
    """Writer half of the capture ring (one per serving process).

    ``maybe_capture`` is called once per answered request from the
    batcher's result hook (and once per rejection from the admission
    path — any thread; the lock serializes writers). It decides
    keep-or-drop (forced reasons first, then the deterministic sample)
    and appends one self-contained JSONL record. A ``capture`` event
    rides the run log per kept request so the report can count what the
    ring holds without reading it.
    """

    def __init__(self, root: str, *,
                 sample: float = DEFAULT_SAMPLE,
                 confidence_floor: float = DEFAULT_CONFIDENCE_FLOOR,
                 slo_ms: Optional[float] = None,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        if not (0.0 <= sample <= 1.0):
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.root = os.path.abspath(root)
        self.sample = float(sample)
        self.confidence_floor = float(confidence_floor)
        self.slo_ms = slo_ms
        self.segment_bytes = int(segment_bytes)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # Writer state: fd, segment index, bytes in the open segment.
        self._fd: Optional[int] = None
        self._seg = 0
        self._seg_bytes = 0
        self._dark = False
        self.captured = 0
        self.skipped = 0
        self.dropped = 0

    def reason_for(self, trace_id: Optional[str],
                   confidence: Optional[float],
                   total_ms: Optional[float],
                   outcome: str = "ok") -> Optional[str]:
        """The capture verdict: a forced reason, ``"sampled"``, or None
        (drop). Forced reasons win over sampling so the tail is always
        present whatever the rate."""
        if outcome == "rejected":
            return "rejected"
        if outcome == "error":
            return "error"
        if confidence is not None and confidence < self.confidence_floor:
            return "low_confidence"
        if self.slo_ms is not None and total_ms is not None \
                and total_ms > self.slo_ms:
            return "slo_breach"
        if trace_id and _tracing.sampled(trace_id, self.sample):
            return "sampled"
        return None

    def maybe_capture(self, voxels: np.ndarray, trace_id: Optional[str],
                      *, label: Optional[int] = None,
                      confidence: Optional[float] = None,
                      total_ms: Optional[float] = None,
                      outcome: str = "ok") -> bool:
        """Apply the capture policy to one request; True when a record
        landed in the ring."""
        reason = self.reason_for(trace_id, confidence, total_ms, outcome)
        if reason is None:
            with self._lock:
                self.skipped += 1
            return False
        rec: dict = {
            "t": round(time.time(), 3),
            "trace": trace_id,
            "reason": reason,
            "voxels": pack_grid(voxels),
        }
        if label is not None:
            rec["label"] = int(label)
        if confidence is not None:
            rec["confidence"] = round(float(confidence), 6)
        if total_ms is not None:
            rec["total_ms"] = round(float(total_ms), 3)
        line = json.dumps(rec, separators=(",", ":")).encode("utf-8") + b"\n"
        with self._lock:
            if self._dark:
                self.dropped += 1
                return False
            try:
                if self._fd is None:
                    self._open_writer_locked()
                elif self._seg_bytes + len(line) > self.segment_bytes \
                        and self._seg_bytes > 0:
                    self._rotate_locked()
                os.write(self._fd, line)
                self._seg_bytes += len(line)
                self.captured += 1
            except OSError:
                # Disk full / unlinked root: go dark for good — capture
                # must never take down the serving path it observes.
                self._go_dark_locked()
                self.dropped += 1
                return False
        # Emit outside the lock (the sink has its own): one event per
        # kept request, so the report counts the ring without reading it.
        obs.emit("capture", trace=trace_id, reason=reason)
        return True

    def stats(self) -> dict:
        with self._lock:
            return {
                "captured": self.captured,
                "skipped": self.skipped,
                "dropped": self.dropped,
                "dark": self._dark,
                "dir": self.root,
            }

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None

    # -- internals (lock held) ------------------------------------------------
    def _go_dark_locked(self) -> None:
        self._dark = True
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def _seg_path(self, seg: int) -> str:
        return os.path.join(
            self.root, f"{_SEG_PREFIX[:-1]}.{seg:0{_SEG_WIDTH}d}{_SEG_SUFFIX}"
        )

    def _segments_locked(self) -> list[tuple[int, str, int]]:
        """(index, path, size) per existing segment, index order."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for n in names:
            if not (n.startswith(_SEG_PREFIX) and n.endswith(_SEG_SUFFIX)):
                continue
            idx = n[len(_SEG_PREFIX): -len(_SEG_SUFFIX)]
            if not idx.isdigit():
                continue
            path = os.path.join(self.root, n)
            try:
                out.append((int(idx), path, os.stat(path).st_size))
            except OSError:
                continue
        out.sort()
        return out

    def _open_writer_locked(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        # Resume the highest existing segment (a respawned replica keeps
        # one ordered ring), rolling over if it is already full.
        seg = max((s[0] for s in self._segments_locked()), default=0)
        path = self._seg_path(seg)
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
        size = os.fstat(fd).st_size
        if size >= self.segment_bytes:
            os.close(fd)
            seg += 1
            path = self._seg_path(seg)
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644)
            size = os.fstat(fd).st_size
        # Terminate a predecessor's torn tail before appending, so the
        # first new record doesn't fuse with the tear into one
        # unparsable line (the tsdb writer's resume rule).
        if size > 0 and os.pread(fd, 1, size - 1) != b"\n":
            size += os.write(fd, b"\n")
        self._fd = fd
        self._seg = seg
        self._seg_bytes = size

    def _rotate_locked(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
        self._seg += 1
        self._fd = os.open(
            self._seg_path(self._seg),
            os.O_RDWR | os.O_CREAT | os.O_APPEND, 0o644,
        )
        self._seg_bytes = 0
        # Prune closed segments oldest-first to the byte budget; the
        # open segment is never deleted.
        segs = [s for s in self._segments_locked() if s[0] != self._seg]
        total = sum(s[2] for s in segs)
        for _idx, path, size in segs:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                pass
            total -= size
