"""Continuous batcher: the always-on serving front end's scheduling core.

``infer.Predictor`` is a batch-mode API — the caller brings a full array
and waits. Production traffic is an open-loop stream of single requests,
and a TPU serves it well only when requests are coalesced into the
fixed-shape batches the compiled programs were built for. This module is
that coalescing layer, deliberately backend-free: requests are numpy
arrays, the model is an injected ``forward(bucket, padded)`` callable,
and everything — flush policy, bucket selection, padding, de-mux,
admission control — is testable on a bare CPU with a fake forward.

Scheduling contract:

- **Flush policy**: a batch dispatches when the pending queue reaches the
  largest bucket (flush-on-max-batch) OR the *oldest* pending request has
  waited ``max_wait_ms`` (flush-on-max-wait), whichever comes first. A
  lone request never waits longer than the deadline; a burst never waits
  at all.
- **Bucket ladder**: the dispatch batch is padded up to the smallest
  configured bucket that fits it (``pick_bucket``). Buckets are the only
  shapes ever dispatched, so a service that pre-built one executable per
  bucket (``service.InferenceService``) never compiles after warmup.
- **De-mux**: each request's future receives exactly its own output row;
  padding rows are dropped on the floor. A forward error resolves every
  future in that batch with the error — a dead batch must not hang its
  callers.
- **Admission control**: the queue is bounded. At the bound, ``submit``
  fast-rejects with ``OverloadError`` (a structured ``response`` dict for
  the HTTP layer — carrying the server's ``retry_after_s`` backoff hint —
  and an ``overload`` event for the run log) instead of letting latency
  grow without bound — under overload the operator wants rejections they
  can count, not a queue they cannot see the end of.
- **Priority lanes** (``LANES``): every request rides a lane
  (``interactive`` default, ``batch`` for deferrable bulk). Per-lane
  queue caps (``lane_limits``) trip before the global bound, so under
  pressure ``batch`` sheds FIRST and interactive keeps its headroom —
  the fleet router applies the same shed order one level up.

Telemetry (never load-bearing, like the rest of the obs layer): each
request feeds ``queue_wait_ms`` (enqueue → dispatch) and ``serving_ms``
(enqueue → response, the end-to-end latency an SLO is written against)
into the rolling windows; each dispatch emits a ``serve_batch`` event and
a ``serve_dispatch`` span.

Request tracing (``obs.tracing``): ``submit`` mints (or adopts) a trace
context per request; the dispatcher stamps each one with the batch it
rode (``batch_seq`` — the same sequence number the ``serve_batch`` event
and ``serve_dispatch`` span carry, so one dispatch's N fanned-in trace
ids tie back to it) and completes the timeline at de-mux with the
queue/device split. Sampling is tail-biased: rejections, forward errors,
and requests breaching ``trace_slo_ms`` are always kept.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from featurenet_tpu import obs
from featurenet_tpu.obs import tracing as _tracing
from featurenet_tpu.obs import windows as _windows

DEFAULT_BUCKETS = (1, 4, 16, 64)
DEFAULT_MAX_WAIT_MS = 5.0
DEFAULT_QUEUE_LIMIT = 64

# Request-priority lanes. "interactive" is the default (a human is
# waiting); "batch" is deferrable bulk traffic — a per-lane queue limit
# caps how much of the admission bound it may occupy, so under pressure
# batch sheds FIRST and interactive keeps its headroom. Unknown lane
# strings normalize to "interactive": a misspelled priority must degrade
# to the stricter admission, never to silent bulk treatment.
LANES = ("interactive", "batch")


def normalize_lane(lane: Optional[str]) -> str:
    return lane if lane in LANES else "interactive"


class OverloadError(RuntimeError):
    """Fast rejection at the admission bound: the queue is full (or this
    request's priority lane is), and the honest answer is an immediate
    structured "try later" — not an unbounded wait. ``response`` is the
    wire shape the HTTP front end returns with a 503; ``retry_after_s``
    is the server's honest backoff hint (the queue turns over on the
    flush-deadline cadence), surfaced as the HTTP ``Retry-After``
    header and honored by the load generator and the fleet router."""

    def __init__(self, queue_depth: int, limit: int,
                 trace_id: Optional[str] = None,
                 lane: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"serving queue full ({queue_depth}/{limit})")
        self.queue_depth = int(queue_depth)
        self.limit = int(limit)
        # The rejected request's trace id (echoed by the HTTP layer so
        # the caller can correlate the 503 with its own bookkeeping; the
        # wire `response` shape is unchanged — load balancers key off
        # structure that predates tracing).
        self.trace_id = trace_id
        self.lane = lane
        self.retry_after_s = retry_after_s

    @property
    def response(self) -> dict:
        out = {
            "error": "overload",
            "queue_depth": self.queue_depth,
            "limit": self.limit,
        }
        if self.lane is not None:
            out["lane"] = self.lane
        if self.retry_after_s is not None:
            out["retry_after_s"] = round(self.retry_after_s, 3)
        return out


def normalize_buckets(buckets: Sequence[int]) -> tuple[int, ...]:
    """The one bucket-ladder validation (sorted, deduped, all >= 1) —
    shared by the batcher, the service, and the CLI so the ladder rules
    can never drift between surfaces."""
    bs = tuple(sorted({int(b) for b in buckets}))
    if not bs or bs[0] < 1:
        raise ValueError(
            f"buckets must be positive batch sizes, got {buckets!r}"
        )
    return bs


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket that fits ``n`` rows (callers cap ``n`` at the
    largest bucket, which is also the fallback)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


class PendingRequest:
    """One enqueued request: a future the batcher resolves with this
    request's own output row (or the batch's forward error)."""

    __slots__ = ("voxels", "t_enq", "t_done", "value", "error", "_event",
                 "ctx", "lane")

    def __init__(self, voxels: np.ndarray,
                 ctx: Optional[_tracing.TraceContext] = None,
                 lane: str = "interactive"):
        self.voxels = voxels
        self.lane = lane
        self.t_enq = time.perf_counter()
        self.t_done: Optional[float] = None
        self.value = None
        self.error: Optional[BaseException] = None
        self._event = threading.Event()
        # Request-scoped trace context (obs.tracing): carries the id the
        # HTTP layer echoes and the buffered timeline the tail-biased
        # sampler flushes at completion.
        self.ctx = ctx

    @property
    def trace_id(self) -> Optional[str]:
        return self.ctx.trace_id if self.ctx is not None else None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serving request not answered within {timeout}s"
            )
        if self.error is not None:
            raise RuntimeError(
                f"serving forward failed: {self.error}"
            ) from self.error
        return self.value

    @property
    def latency_ms(self) -> Optional[float]:
        """End-to-end latency (enqueue → response), once resolved."""
        if self.t_done is None:
            return None
        return (self.t_done - self.t_enq) * 1e3


class ContinuousBatcher:
    """Bounded request queue + dispatcher thread implementing the flush /
    bucket / de-mux / admission contract in the module doc.

    ``forward(bucket, padded)`` receives a ``[bucket, ...]`` array whose
    first ``n <= bucket`` rows are real requests and must return an
    indexable ``[bucket, ...]`` result (row i answers request i). The
    service layer binds this to one pre-built executable per bucket.
    """

    def __init__(self, forward: Callable, buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_ms: float = DEFAULT_MAX_WAIT_MS,
                 queue_limit: int = DEFAULT_QUEUE_LIMIT,
                 cost_for: Optional[Callable] = None,
                 peaks: Optional[dict] = None,
                 trace_sample: float = 1.0,
                 trace_slo_ms: Optional[float] = None,
                 lane_limits: Optional[dict] = None,
                 on_result: Optional[Callable] = None,
                 on_reject: Optional[Callable] = None):
        bs = normalize_buckets(buckets)
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        for lane, lim in (lane_limits or {}).items():
            if lane not in LANES:
                raise ValueError(
                    f"unknown lane {lane!r} in lane_limits; "
                    f"known lanes: {', '.join(LANES)}"
                )
            if lim < 0:
                raise ValueError(
                    f"lane_limits[{lane!r}] must be >= 0, got {lim}"
                )
        self.forward = forward
        # Performance attribution (obs.perf), injected to keep the batcher
        # backend-free: ``cost_for(bucket)`` returns that bucket's
        # compiled cost counters (or None) and ``peaks`` the device-kind
        # peak row; each dispatch then folds its measured wall into the
        # rolling mfu / achieved_bw_fraction windows. Both default off —
        # a bare-CPU test with a fake forward observes nothing.
        self.cost_for = cost_for
        self.peaks = peaks
        # Request tracing (obs.tracing): the healthy-traffic sampling
        # rate (a pure hash of the trace id — multi-host agreement is
        # free) and the SLO threshold above which a request is ALWAYS
        # sampled regardless of rate (tail bias: the slow tail is the
        # point of tracing).
        if not (0.0 <= trace_sample <= 1.0):
            raise ValueError(
                f"trace_sample must be in [0, 1], got {trace_sample}"
            )
        self.trace_sample = float(trace_sample)
        self.trace_slo_ms = trace_slo_ms
        # Observation hooks, injected to keep the batcher backend-free
        # (the service binds quality telemetry + the flight recorder):
        # ``on_result(p, row, total_ms, outcome)`` per de-muxed request
        # (row is None on a forward error), ``on_reject(p)`` per
        # admission rejection. Both are telemetry — an exception inside
        # one is counted and swallowed, never surfaced to the caller.
        self.on_result = on_result
        self.on_reject = on_reject
        self._hook_errors = 0
        self.buckets = bs
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.queue_limit = int(queue_limit)
        # Per-lane admission caps ({"batch": N}): a lane at its cap
        # rejects even while the global queue has room — the shed-first
        # discipline that keeps interactive headroom under pressure.
        self.lane_limits = dict(lane_limits or {})
        # The Retry-After hint on a rejection: the queue turns over on
        # the flush-deadline cadence, so "come back after ~2 deadlines"
        # is the honest earliest time a retry could find room.
        self.retry_after_s = max(0.05, 2.0 * self.max_wait_s)
        self._cv = threading.Condition()
        self._queue: deque[PendingRequest] = deque()
        self._lane_depth: dict[str, int] = {}
        self._lane_rejected: dict[str, int] = {}
        self._draining = False
        self._stopped = False
        self._served = 0
        self._rejected = 0
        self._errors = 0
        self._batches = 0
        self._rows = 0
        self._capacity = 0
        # Dispatch sequence number (batch attribution for tracing):
        # incremented by the single dispatcher thread only, carried by
        # serve_batch / serve_dispatch / request_dispatch so one batch's
        # fanned-in trace ids all name the same dispatch.
        self._batch_seq = 0
        self._by_bucket: dict[int, int] = {}
        self._worker = threading.Thread(
            target=self._run, name="serve-batcher", daemon=True
        )
        self._worker.start()

    # -- producer side -------------------------------------------------------
    def submit(self, voxels: np.ndarray,
               trace_id: Optional[str] = None,
               lane: str = "interactive") -> PendingRequest:
        """Enqueue one request; returns its future. Raises
        ``OverloadError`` immediately at the queue bound — or at the
        request's LANE bound (``lane_limits``), which trips first for
        ``batch`` traffic under pressure — and ``RuntimeError`` after
        ``drain()``. ``trace_id`` adopts a caller-supplied trace id (the
        HTTP propagation header); None mints one — either way the id
        rides the returned future."""
        lane = normalize_lane(lane)
        p = PendingRequest(voxels, lane=lane)
        with self._cv:
            if self._draining:
                raise RuntimeError(
                    "batcher is draining; no new requests accepted"
                )
            # Admit AFTER the draining check: a drain-race refusal must
            # not count as an admitted trace (the /metrics invariant is
            # admitted ≈ done + rejected). Cheap enough to hold the cv
            # lock across: a counter bump, a clock read, 8 random bytes.
            ctx = p.ctx = _tracing.admit(trace_id, self.trace_sample)
            depth = len(self._queue)
            lane_cap = self.lane_limits.get(lane)
            if depth >= self.queue_limit or (
                lane_cap is not None
                and self._lane_depth.get(lane, 0) >= lane_cap
            ):
                self._rejected += 1
                self._lane_rejected[lane] = \
                    self._lane_rejected.get(lane, 0) + 1
            else:
                self._queue.append(p)
                self._lane_depth[lane] = self._lane_depth.get(lane, 0) + 1
                self._cv.notify_all()
                depth = -1
        if depth >= 0:
            # Emit outside the lock: the sink has its own, and a slow
            # filesystem must not extend the admission critical section.
            obs.emit("overload", queue_depth=depth, limit=self.queue_limit,
                     lane=lane)
            # Rejections are always sampled (tail bias): the structured
            # trace is exactly what the operator chases after a 503.
            _tracing.reject(ctx, depth, self.queue_limit)
            if self.on_reject is not None:
                try:
                    self.on_reject(p)
                except Exception:
                    # Racy with _dispatch's increment (HTTP handler
                    # thread vs dispatcher thread): += on an int is a
                    # read-modify-write, so concurrent failures could
                    # drop counts without the lock.
                    with self._cv:
                        self._hook_errors += 1
            raise OverloadError(depth, self.queue_limit,
                                trace_id=ctx.trace_id, lane=lane,
                                retry_after_s=self.retry_after_s)
        return p

    # -- dispatcher thread ---------------------------------------------------
    def _run(self) -> None:
        while True:
            batch = self._next_batch()
            if batch is None:
                return
            self._dispatch(batch)

    def _next_batch(self) -> Optional[list[PendingRequest]]:
        """Block until the flush policy says dispatch; None = drained."""
        max_b = self.buckets[-1]
        with self._cv:
            while not self._queue:
                if self._draining:
                    return None
                self._cv.wait()
            # Flush when the largest bucket fills OR the oldest request's
            # wait hits the deadline — whichever first. Draining flushes
            # immediately: a shutdown must not pad out its own deadline.
            while len(self._queue) < max_b and not self._draining:
                now = time.perf_counter()
                deadline = self._queue[0].t_enq + self.max_wait_s
                if now >= deadline:
                    break
                self._cv.wait(timeout=deadline - now)
            k = min(len(self._queue), max_b)
            # Deadline flushes can catch an awkward count (say 17 on a
            # 1/4/16/64 ladder): padding it to the smallest fitting
            # bucket would run under half full. When a smaller bucket
            # can be dispatched FULL and the fitting bucket would be
            # less than half occupied, take the full bucket and leave
            # the remainder queued — its deadline has already passed,
            # so it flushes immediately on the next loop under the same
            # rule. Every dispatch is then >= 50% occupied whenever a
            # full smaller bucket existed.
            fit = pick_bucket(k, self.buckets)
            if fit > k and 2 * k < fit:
                full = [b for b in self.buckets if b <= k]
                if full:
                    k = full[-1]
            batch = [self._queue.popleft() for _ in range(k)]
            for p in batch:
                self._lane_depth[p.lane] = self._lane_depth[p.lane] - 1
            return batch

    def _dispatch(self, batch: list[PendingRequest]) -> None:
        n = len(batch)
        bucket = pick_bucket(n, self.buckets)
        # Single dispatcher thread: the sequence needs no lock, and
        # every per-request dispatch record below names this batch.
        self._batch_seq += 1
        seq = self._batch_seq
        t_disp = time.perf_counter()
        for p in batch:
            _windows.observe("queue_wait_ms", (t_disp - p.t_enq) * 1e3)
            _tracing.dispatch(p.ctx, seq, bucket, bucket - n)
        arr = np.stack([p.voxels for p in batch])
        if bucket > n:
            arr = np.concatenate(
                [arr, np.zeros((bucket - n,) + arr.shape[1:], arr.dtype)]
            )
        out = None
        err: Optional[BaseException] = None
        try:
            with obs.span("serve_dispatch", bucket=bucket, n=n,
                          batch_seq=seq):
                out = self.forward(bucket, arr)
        except Exception as e:  # resolve the batch; the batcher survives
            err = e
        t_done = time.perf_counter()
        if err is None and self.cost_for is not None:
            from featurenet_tpu.obs import perf as _perf

            # The dispatch wall here spans forward + readback (the
            # service's forward returns a host array), so the MFU sample
            # is the served batch's honest wall, not an enqueue time.
            _perf.observe_dispatch(
                self.cost_for(bucket), t_done - t_disp, peaks=self.peaks
            )
        for i, p in enumerate(batch):
            if err is not None:
                p.error = err
            else:
                p.value = out[i]
            p.t_done = t_done
            p._event.set()
            # End-to-end latency = queue wait + dispatch + device +
            # readback: the number an SLO is written against.
            _windows.observe("serving_ms", (t_done - p.t_enq) * 1e3)
            # De-mux fan-out: the trace completes with the per-request
            # queue/device split (errors and SLO breaches force-sample).
            _tracing.done(
                p.ctx,
                queue_wait_ms=(t_disp - p.t_enq) * 1e3,
                dispatch_ms=(t_done - t_disp) * 1e3,
                total_ms=(t_done - p.t_enq) * 1e3,
                outcome="error" if err is not None else "ok",
                slo_ms=self.trace_slo_ms,
            )
            if self.on_result is not None:
                try:
                    self.on_result(
                        p,
                        None if err is not None else out[i],
                        (t_done - p.t_enq) * 1e3,
                        "error" if err is not None else "ok",
                    )
                except Exception:
                    # Same counter as submit()'s reject-hook path: two
                    # threads, one int — take the lock for the
                    # read-modify-write.
                    with self._cv:
                        self._hook_errors += 1
        with self._cv:
            self._batches += 1
            self._rows += n
            self._capacity += bucket
            self._by_bucket[bucket] = self._by_bucket.get(bucket, 0) + 1
            if err is None:
                self._served += n
            else:
                self._errors += n
        obs.emit("serve_batch", bucket=bucket, n=n, pad=bucket - n,
                 batch_seq=seq, dur_ms=round((t_done - t_disp) * 1e3, 3))

    # -- lifecycle / introspection -------------------------------------------
    def stats(self) -> dict:
        with self._cv:
            cap = self._capacity
            return {
                "served": self._served,
                "rejected": self._rejected,
                "errors": self._errors,
                "batches": self._batches,
                # Mean batch occupancy: real rows / padded capacity — the
                # padding tax of the bucket ladder at this traffic shape.
                "occupancy": round(self._rows / cap, 4) if cap else None,
                "by_bucket": dict(sorted(self._by_bucket.items())),
                "queue_depth": len(self._queue),
                # Priority lanes: what is queued and what was shed, per
                # lane — the shed-order evidence (batch rejects first).
                "by_lane": {
                    lane: {
                        "queued": self._lane_depth.get(lane, 0),
                        "rejected": self._lane_rejected.get(lane, 0),
                        "limit": self.lane_limits.get(lane),
                    }
                    for lane in LANES
                    if self._lane_depth.get(lane, 0)
                    or self._lane_rejected.get(lane, 0)
                    or lane in self.lane_limits
                },
            }

    def drain(self, timeout_s: float = 30.0) -> dict:
        """Stop accepting, flush everything already admitted, stop the
        dispatcher, and return final stats. Every accepted request is
        answered before the thread exits — unless the join times out
        (a wedged forward), which the stats must not paper over:
        ``drain_timeout`` flips true, a warning lands in the run log,
        and the service turns it into a nonzero exit. Idempotent."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        self._worker.join(timeout=timeout_s)
        st = self.stats()
        st["drain_timeout"] = self._worker.is_alive()
        if st["drain_timeout"]:
            obs.warn(
                "serve_drain_timeout",
                f"dispatcher still running {timeout_s}s after drain; "
                f"{st['queue_depth']} request(s) may go unanswered",
            )
        with self._cv:
            first = not self._stopped
            self._stopped = True
        if first:
            obs.emit("serve_stop", served=st["served"],
                     rejected=st["rejected"])
        return st
