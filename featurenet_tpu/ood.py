"""Robustness / out-of-distribution evaluation harness.

The reference repo evaluates only its held-out split (SURVEY.md §4), and
every accuracy headline this rebuild had reported through round 3 was an
in-distribution draw of its own parametric generator — the round-3 verdict
named that the largest remaining epistemic gap. This module probes
distribution shift directly. Families:

  clean    — unperturbed fresh draws: the in-distribution control row.
  rotation — arbitrary (non-cube-group) SO(3) rotations applied in MESH
             space: fresh part → ``voxels_to_mesh`` (exact surface) →
             rotate about the part center → re-voxelize through the same
             rasterization pipeline the STL benchmark uses. Training
             augmentation is the 24-element cube group only
             (``ops/augment.py``), so any non-90° pose is genuinely OOD.
  noise    — iid occupancy bit-flips at rate p (scan/sensor noise model).
  morph    — one-voxel 6-neighborhood dilation or erosion (systematic
             surface over/under-estimation, e.g. tolerance drift).
  tails    — feature-parameter holdout: every generator size/position
             parameter drawn from the TAILS of its range
             (``synthetic.param_range``). Against a full-range-trained
             model this is mild shift; the stronger protocol trains on a
             ``param_range="mid"`` cache and evaluates here.
  scale    — the part re-normalized at a different margin (uniform
             shrink/grow of a few voxels). Added after the first harness
             run exposed that raw generator grids (0.08-margin stock)
             score near CHANCE against an STL-cache-trained model whose
             parts were normalized at margin 0.05 — a ~7% uniform scale
             shift, measured here as its own dose-response family.

All families evaluate FRESH generator draws (never any split of a training
cache), seeded independently of the training seeds, balanced per class —
and every family passes through the SAME mesh→voxelize pipeline the STL
benchmark uses (``voxels_to_mesh`` → ``voxelize`` at the default margin),
so the clean row is the training modality, not the raw generator grid.
"""

from __future__ import annotations

import numpy as np

from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_sample
from featurenet_tpu.data.voxel_to_mesh import (
    random_rotation_matrix,
    rotate_mesh,
    voxels_to_mesh,
)
from featurenet_tpu.data.voxelize import voxelize

NUM_CLASSES = len(CLASS_NAMES)

# (family, level) rows of the default report. Rotation uses a fixed angle
# about a random axis per sample (clean dose-response); "so3" is a uniform
# random rotation.
DEFAULT_LEVELS: tuple = (
    ("clean", None),
    ("rotation", 5.0),
    ("rotation", 15.0),
    ("rotation", 45.0),
    ("rotation", "so3"),
    ("noise", 0.005),
    ("noise", 0.01),
    ("noise", 0.02),
    ("morph", "dilate"),
    ("morph", "erode"),
    ("tails", None),
    ("scale", 0.08),
    ("scale", 0.11),
)


def rotate_part(
    grid: np.ndarray, rng: np.random.Generator, angle_deg=None
) -> np.ndarray:
    """Mesh-space rotation of a voxel part: exact surface mesh → rotate
    about the center → re-voxelize (parity fill) at the same resolution.
    The mesh stays watertight under rotation, so the parity fill is exact;
    ``voxelize`` re-normalizes into the unit cube the way the STL pipeline
    normalizes every benchmark part."""
    R = grid.shape[0]
    tris = rotate_mesh(
        voxels_to_mesh(grid.astype(bool)),
        random_rotation_matrix(rng, angle_deg),
    )
    return voxelize(tris, R, fill=True)


def _shift(g: np.ndarray, ax: int, d: int) -> np.ndarray:
    out = np.zeros_like(g)
    src = [slice(None)] * 3
    dst = [slice(None)] * 3
    if d > 0:
        dst[ax], src[ax] = slice(d, None), slice(None, -d)
    else:
        dst[ax], src[ax] = slice(None, d), slice(-d, None)
    out[tuple(dst)] = g[tuple(src)]
    return out


def dilate(g: np.ndarray) -> np.ndarray:
    """One-voxel 6-neighborhood binary dilation (zero boundary)."""
    out = g.copy()
    for ax in range(3):
        for d in (1, -1):
            out |= _shift(g, ax, d)
    return out


def erode(g: np.ndarray) -> np.ndarray:
    """One-voxel 6-neighborhood binary erosion.

    Boundary convention: implemented as ``~dilate(~g)`` with ``dilate``'s
    zero-padded shifts, so out-of-grid is treated as SOLID — a voxel on
    the grid boundary is never eroded from the outside. Harmless for this
    harness's margin-normalized parts (the stock never touches the grid
    edge), but an asymmetry vs ``dilate``'s zero boundary that a
    non-margined input would feel as silent under-erosion."""
    return ~dilate(~g)


def remesh(grid: np.ndarray, margin: float = 0.05) -> np.ndarray:
    """Pass a voxel part through the benchmark's mesh→voxel pipeline
    (exact surface extraction, re-normalization at ``margin``, parity
    fill). This is the normalization every STL-built training cache went
    through — fresh generator grids must take the same path or the
    'clean' row measures a scale shift, not the model."""
    R = grid.shape[0]
    return voxelize(voxels_to_mesh(grid.astype(bool)), R, fill=True,
                    margin=margin)


def _family_rng(seed: int, family: str, level) -> np.random.Generator:
    """Per-(family, level) stream keyed via stable CRC digests —
    reproducible across processes (Python's hash() is salted) and
    independent of which other rows a report includes. Shared by the
    classify and seg harnesses so their seeding conventions cannot
    diverge."""
    import zlib

    return np.random.default_rng(np.random.SeedSequence([
        seed,
        zlib.crc32(family.encode()),
        zlib.crc32(repr(level).encode()),
    ]))


def _annotate_delta(rows: list[dict], key: str) -> list[dict]:
    """Delta of ``key`` vs the report's own clean control row."""
    clean = next(r[key] for r in rows if r["family"] == "clean")
    for r in rows:
        r["delta_vs_clean"] = round(r[key] - clean, 4)
    return rows


def _annotate_rotation_control(rows: list[dict], key: str) -> list[dict]:
    """Seg rotation rows compose a fixed ``ROTATION_PRESCALE`` shrink so
    rotated stock stays in-grid — their clean-row delta therefore mixes
    the rotation cost with the scale cost. The matching control is the
    (scale, ROTATION_PRESCALE) row: ``delta_vs_scale_control`` is the
    rotation-only attribution the artifact should carry (advisor r5)."""
    control = next(
        (r[key] for r in rows
         if r["family"] == "scale" and r["level"] == ROTATION_PRESCALE),
        None,
    )
    if control is None:
        return rows
    for r in rows:
        if r["family"] == "rotation":
            r["delta_vs_scale_control"] = round(r[key] - control, 4)
    return rows


def _perturb(family: str, level, grid: np.ndarray, rng) -> np.ndarray:
    g = grid.astype(bool)
    if family in ("clean", "tails"):
        return remesh(g)
    if family == "rotation":
        # rotate_part re-voxelizes at the default margin itself.
        return rotate_part(g, rng, None if level == "so3" else float(level))
    if family == "noise":
        return remesh(g) ^ (rng.random(g.shape) < float(level))
    if family == "morph":
        g = remesh(g)
        return dilate(g) if level == "dilate" else erode(g)
    if family == "scale":
        return remesh(g, margin=float(level))
    raise ValueError(f"unknown OOD family {family!r}")


def evaluate_ood(
    checkpoint_dir: str,
    per_class: int = 50,
    seed: int = 777,
    levels=None,
    families=None,
    batch: int = 64,
    progress=None,
    canonicalize: bool = False,
    tta_rotations: bool = False,
) -> list[dict]:
    """Run the robustness report on a classification checkpoint.

    Returns one row per (family, level): accuracy, mean/min per-class
    accuracy, the worst class, and the degradation vs this report's own
    ``clean`` control row (always included so the delta is computed against
    the same fresh-draw protocol, not a cache split).
    """
    from featurenet_tpu.infer import Predictor

    p = Predictor.from_checkpoint(checkpoint_dir, batch=batch)
    if p.cfg.task != "classify":
        raise ValueError("evaluate_ood runs on classification checkpoints")
    R = p.cfg.resolution

    known = {"clean", "rotation", "noise", "morph", "tails", "scale"}
    if families:
        bad = sorted(set(families) - known)
        if bad:
            raise ValueError(
                f"unknown OOD families {bad}; known: {sorted(known)}"
            )
    levels = list(levels if levels is not None else DEFAULT_LEVELS)
    if families:
        levels = [lv for lv in levels if lv[0] in families]
    if ("clean", None) not in levels:
        levels.insert(0, ("clean", None))

    rows = []
    for family, level in levels:
        # Independent of every training seed; the clean row and a perturbed
        # row see different draws of the same distribution (fresh-draw
        # variance, a few tenths of a point at per_class=50, is part of
        # the quoted delta).
        rng = _family_rng(seed, family, level)
        confusion = np.zeros((NUM_CLASSES, NUM_CLASSES), np.int64)
        for c in range(NUM_CLASSES):
            grids = np.empty((per_class, R, R, R), np.float32)
            for i in range(per_class):
                part, _, _ = generate_sample(
                    rng, R, label=c,
                    param_range="tails" if family == "tails" else None,
                )
                grids[i] = _perturb(family, level, part, rng)
            pred, _ = p.predict_voxels(
                grids, canonicalize=canonicalize,
                tta_rotations=tta_rotations,
            )
            for q in pred:
                confusion[c, int(q)] += 1
            if progress:
                progress(family, level, c)
        per_cls = confusion.diagonal() / confusion.sum(axis=1)
        worst = int(per_cls.argmin())
        rows.append({
            "family": family,
            "level": level,
            "n": int(confusion.sum()),
            "accuracy": round(float(confusion.diagonal().sum()
                                    / confusion.sum()), 4),
            "mean_class_accuracy": round(float(per_cls.mean()), 4),
            "min_class_accuracy": round(float(per_cls[worst]), 4),
            "worst_class": CLASS_NAMES[worst],
        })
    return _annotate_delta(rows, "accuracy")


# --- segmentation robustness -------------------------------------------------
# The seg modality is aligned-unit-cube (labels live in the part's own grid
# frame — data/offline.build_seg_cache), so fresh generator draws ARE the
# clean control; no margin re-normalization is involved. Geometry families
# therefore warp in GRID space (the same space the training augmentation
# uses), with trilinear+threshold resampling for voxels and nearest for
# labels so input and ground truth move together. Rotation rows compose a
# fixed 0.7 pre-scale so rotated stock stays in-grid (the classify
# harness's mesh pipeline shrinks rotated parts the same way, up to 1/√3);
# the scale-0.7 row is the matching control, so rotation deltas read
# against it, not against clean.

SEG_DEFAULT_LEVELS: tuple = (
    ("clean", None),
    ("rotation", 5.0),
    ("rotation", 15.0),
    ("rotation", 45.0),
    ("rotation", "so3"),
    ("scale", 0.7),
    ("scale", 0.9),
    ("scale", 1.1),
    ("noise", 0.005),
    ("noise", 0.01),
    ("morph", "dilate"),
    ("morph", "erode"),
    ("tails", None),
)

ROTATION_PRESCALE = 0.7


def _trilinear(vol: np.ndarray, src: np.ndarray) -> np.ndarray:
    """Sample float ``vol`` [R,R,R] at ``src`` [3, N] (zero outside)."""
    R = vol.shape[0]
    f = np.floor(src).astype(np.int64)
    t = src - f
    out = np.zeros(src.shape[1], np.float32)
    for dz in (0, 1):
        for dy in (0, 1):
            for dx in (0, 1):
                idx = f + np.array([[dz], [dy], [dx]])
                w = (
                    (t[0] if dz else 1 - t[0])
                    * (t[1] if dy else 1 - t[1])
                    * (t[2] if dx else 1 - t[2])
                )
                valid = ((idx >= 0) & (idx < R)).all(axis=0)
                ic = np.clip(idx, 0, R - 1)
                out += w * np.where(
                    valid, vol[ic[0], ic[1], ic[2]], 0.0
                )
    return out


def affine_resample_pair(
    vox: np.ndarray,
    seg: np.ndarray | None,
    rot: np.ndarray | None = None,
    scale: float = 1.0,
) -> tuple[np.ndarray, np.ndarray | None]:
    """Grid-space affine about the center: voxels trilinear + 0.5
    threshold (≈ re-rasterization of the implicit surface), labels
    nearest — the numpy eval-side mirror of
    ``ops.augment.random_affine_batch_paired``'s per-sample warp."""
    R = vox.shape[0]
    c = (R - 1) / 2.0
    grid = np.stack(np.meshgrid(
        np.arange(R, dtype=np.float64),
        np.arange(R, dtype=np.float64),
        np.arange(R, dtype=np.float64),
        indexing="ij",
    )).reshape(3, -1)
    src = (grid - c) / scale
    if rot is not None:
        src = rot.T @ src
    src = src + c
    out_v = (_trilinear(vox.astype(np.float32), src) > 0.5).reshape(
        (R, R, R)
    )
    out_s = None
    if seg is not None:
        n = np.rint(src).astype(np.int64)
        valid = ((n >= 0) & (n < R)).all(axis=0)
        nc = np.clip(n, 0, R - 1)
        out_s = np.where(
            valid, seg[nc[0], nc[1], nc[2]], 0
        ).reshape((R, R, R)).astype(seg.dtype)
    return out_v, out_s


def _perturb_seg(family, level, vox, seg, rng):
    """(input voxels, ground truth) for one seg-OOD row. Geometry families
    warp both; corruption families (noise/morph) perturb the input only —
    the model should recover the underlying part's segmentation."""
    if family in ("clean", "tails"):
        return vox, seg
    if family == "rotation":
        rot = random_rotation_matrix(
            rng, None if level == "so3" else float(level)
        )
        return affine_resample_pair(vox, seg, rot, ROTATION_PRESCALE)
    if family == "scale":
        return affine_resample_pair(vox, seg, None, float(level))
    if family == "noise":
        return vox ^ (rng.random(vox.shape) < float(level)), seg
    if family == "morph":
        g = dilate(vox) if level == "dilate" else erode(vox)
        return g, seg
    raise ValueError(f"unknown seg OOD family {family!r}")


def evaluate_ood_seg(
    checkpoint_dir: str,
    parts: int = 60,
    seed: int = 777,
    levels=None,
    families=None,
    batch: int = 16,
    progress=None,
) -> list[dict]:
    """Robustness report for a segmentation checkpoint: one row per
    (family, level) with exact summed per-class IoU over ``parts`` fresh
    generator draws (never a cache split; the canonical-label seg
    generator, ambient ``param_range`` for the tails row)."""
    from featurenet_tpu.data.offline import _generate_seg_sample
    from featurenet_tpu.data.synthetic import param_range
    from featurenet_tpu.infer import Predictor

    p = Predictor.from_checkpoint(checkpoint_dir, batch=batch)
    if p.cfg.task != "segment":
        raise ValueError("evaluate_ood_seg runs on segment checkpoints")
    R = p.cfg.resolution
    nf = p.cfg.num_features
    n_cls = NUM_CLASSES + 1

    known = {lv[0] for lv in SEG_DEFAULT_LEVELS}
    if families:
        bad = sorted(set(families) - known)
        if bad:
            raise ValueError(
                f"unknown seg OOD families {bad}; known: {sorted(known)}"
            )
    levels = list(levels if levels is not None else SEG_DEFAULT_LEVELS)
    if families:
        levels = [lv for lv in levels if lv[0] in families]
    if ("clean", None) not in levels:
        levels.insert(0, ("clean", None))
    # Rotation rows are only interpretable against their pre-scale
    # control: force the (scale, ROTATION_PRESCALE) row into the report
    # whenever any rotation row runs (e.g. --families rotation).
    if (any(lv[0] == "rotation" for lv in levels)
            and ("scale", ROTATION_PRESCALE) not in levels):
        levels.append(("scale", ROTATION_PRESCALE))

    rows = []
    for family, level in levels:
        rng = _family_rng(seed, family, level)
        inter = np.zeros(n_cls, np.float64)
        union = np.zeros(n_cls, np.float64)
        correct = total = 0
        for start in range(0, parts, batch):
            n = min(batch, parts - start)
            vox = np.empty((n, R, R, R), np.float32)
            gt = np.empty((n, R, R, R), np.int32)
            for i in range(n):
                with param_range("tails" if family == "tails" else None):
                    part, s = _generate_seg_sample(
                        rng, R, nf, "canonical"
                    )
                v, s2 = _perturb_seg(
                    family, level, part.astype(bool), s, rng
                )
                vox[i] = v.astype(np.float32)
                gt[i] = s2
            pred = p.predict_voxels_seg(vox).astype(np.int32)
            for c in range(n_cls):
                pc, tc = pred == c, gt == c
                inter[c] += (pc & tc).sum()
                union[c] += (pc | tc).sum()
            correct += (pred == gt).sum()
            total += pred.size
            if progress:
                progress(family, level, start + n)
        present = union > 0
        iou = np.where(present, inter / np.maximum(union, 1), 0.0)
        rows.append({
            "family": family,
            "level": level,
            "n": parts,
            "mean_iou": round(float(iou.sum() / max(present.sum(), 1)), 4),
            "voxel_accuracy": round(float(correct / total), 4),
        })
    return _annotate_rotation_control(
        _annotate_delta(rows, "mean_iou"), "mean_iou"
    )


def main(argv=None) -> None:
    import argparse
    import json

    ap = argparse.ArgumentParser(prog="featurenet_tpu.ood")
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--per-class", type=int, default=50)
    ap.add_argument("--seed", type=int, default=777)
    ap.add_argument("--families", default=None,
                    help="comma list: clean,rotation,noise,morph,tails,scale")
    ap.add_argument("--out", default=None, help="also write rows as JSON")
    args = ap.parse_args(argv)
    fams = args.families.split(",") if args.families else None
    rows = evaluate_ood(
        args.checkpoint_dir, per_class=args.per_class, seed=args.seed,
        families=fams,
    )
    for r in rows:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(rows, fh, indent=1)


if __name__ == "__main__":
    main()
