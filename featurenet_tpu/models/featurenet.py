"""The FeatureNet 3D-CNN voxel classifier, designed TPU-first.

Capability parity target: the reference's ``featurenet/model.py`` — a torch
``Conv3d``/``BatchNorm3d``/``MaxPool3d`` stack ending in a 24-way classifier
(SURVEY.md §2 C1, §3.3; exact reference file:line unavailable — the mount was
empty at survey time, see SURVEY.md header). The *contract* preserved here:
binary ``R³`` occupancy grid in, 24 logits out, a few million parameters.

TPU-first design decisions (none of these mirror the torch reference):

- **Layout**: NDHWC (channels-last), the native layout for XLA:TPU convs —
  the MXU consumes the contraction over (kernel-volume × C_in) directly,
  no transposes.
- **Precision**: bf16 activations/compute, fp32 parameters and BatchNorm
  statistics. The MXU natively multiplies bf16 with fp32 accumulation, so
  this is the full-throughput configuration with fp32-quality sums.
- **Stem**: the paper-style 7³/stride-2 stem is kept as the default arch but
  computed via the space-to-depth reformulation (``ops/stem.py``) — XLA
  lowers a 1-channel conv at 1/128th MXU occupancy (measured 10 TF/s), while
  the s2d-equivalent stride-1 conv runs 5.3x faster (slope-timed,
  BASELINE.md). Numerically identical; ``FeatureNetArch.stem_s2d=False``
  restores the direct conv. Note the two formulations produce different
  Flax param tree paths (``SpaceToDepthConv_0`` vs ``Conv_0``), so a
  checkpoint restores only under the setting it was trained with.
- **BatchNorm**: stats are computed over whatever batch the compiled program
  sees. Under ``jit`` with the batch sharded on a mesh axis, XLA inserts the
  cross-device reduction automatically — global-batch statistics with no
  hand-written ``psum`` (the torch analog, SyncBatchNorm+NCCL, is a separate
  wrapper; here it is the default semantics of the compiler).
- **Static shapes only**: every forward is shape-monomorphic; resolution is a
  construction-time constant, so each (R, batch) pair compiles once and runs
  from cache.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from featurenet_tpu.data.synthetic import NUM_CLASSES


@dataclasses.dataclass(frozen=True)
class FeatureNetArch:
    """Architecture hyperparameters (a frozen, hashable config).

    The default matches the paper-shape stack (SURVEY.md §3.3):
    conv 32×7³/s2 → conv 32×5³ → pool → conv 64×3³ → conv 64×3³ → pool
    → FC-128 → dropout → FC-24.
    """

    features: Sequence[int] = (32, 32, 64, 64)
    kernels: Sequence[int] = (7, 5, 3, 3)
    strides: Sequence[int] = (2, 1, 1, 1)
    pool_after: Sequence[bool] = (False, True, False, True)
    hidden: int = 128
    dropout: float = 0.5
    num_classes: int = NUM_CLASSES
    # Strided convs via the space-to-depth reformulation (ops/stem.py):
    # numerically identical to the direct conv, measured 5.3x faster for the
    # 7³/s2/1-channel stem on TPU v5e (XLA lowers C_in=1 convs at 1/128th
    # MXU occupancy; BASELINE.md). Default ON; off reproduces the naive
    # lowering — the two settings have different param tree paths, so pick
    # per run, not per restore.
    stem_s2d: bool = True
    # Backend for the stride-1 conv blocks: "xla" (default), "pallas"
    # (ops/conv3d.py, fp32 all-Pallas reference), or "hybrid_dw" (XLA
    # fwd/dx + the Pallas tap-folded weight-grad kernel, ops/conv_dw.py —
    # targets the Cout-starved dW contraction, the measured pod64
    # bottleneck). The microbench (ops/bench_ops.py) re-decides defaults.
    conv_backend: str = "xla"
    # Head: flatten (paper-shape; correct for the shallow 64³ stack) or
    # global-average-pool (deep stacks: a flattened 8³×256 head is 33M
    # params of dropout-starved dense layer — the measured cause of the
    # abc128 uniform-output collapse; GAP heads are also pose-robust).
    head_gap: bool = False
    # Residual skips around stride-1 blocks whose input/output channel
    # counts match (pooling stays outside the skip). Identity branches keep
    # deep stacks trainable; no-op for the paper-shape 4-block stack.
    residual: bool = False

    def __post_init__(self):
        n = len(self.features)
        if not (len(self.kernels) == len(self.strides) == len(self.pool_after) == n):
            raise ValueError("arch lists must have equal length")


def tiny_arch(num_classes: int = NUM_CLASSES) -> FeatureNetArch:
    """The smoke16 config: 2 conv blocks + head, fast on CPU (SURVEY.md §7.2)."""
    return FeatureNetArch(
        features=(16, 32),
        kernels=(3, 3),
        strides=(1, 1),
        pool_after=(True, True),
        hidden=64,
        dropout=0.2,
        num_classes=num_classes,
    )


def deep_arch(num_classes: int = NUM_CLASSES) -> FeatureNetArch:
    """The abc128 stretch config: deeper net for 128³ inputs (BASELINE config 5).

    GAP head + residual skips: the original flatten head put 33.6 M of the
    35.3 M params in one dropout-starved dense layer and the net collapsed
    into the uniform-output absorbing state at every tried lr (BASELINE.md
    training-dynamics note); with GAP + skips the same conv tower trains.
    """
    return FeatureNetArch(
        features=(32, 64, 64, 128, 128, 256),
        kernels=(7, 3, 3, 3, 3, 3),
        strides=(2, 1, 1, 1, 1, 1),
        pool_after=(False, True, False, True, False, True),
        hidden=256,
        dropout=0.5,
        num_classes=num_classes,
        head_gap=True,
        residual=True,
    )


class ConvBNRelu(nn.Module):
    """conv → batchnorm → relu, bf16 compute / fp32 BN.

    Pooling deliberately lives at the call site (FeatureNet pools after the
    optional residual add; the segmenter strides instead) so the window
    config exists in exactly one place per model."""

    features: int
    kernel: int
    stride: int = 1
    dtype: jnp.dtype = jnp.bfloat16
    stem_s2d: bool = True
    conv_backend: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool):
        if self.stride > 1 and self.stem_s2d and self.kernel >= self.stride:
            from featurenet_tpu.ops.stem import SpaceToDepthConv

            x = SpaceToDepthConv(
                self.features, self.kernel, self.stride, dtype=self.dtype
            )(x)
        elif self.stride == 1 and self.conv_backend == "pallas":
            from featurenet_tpu.ops.conv3d import PallasConv

            x = PallasConv(self.features, self.kernel, dtype=self.dtype)(x)
        elif self.stride == 1 and self.conv_backend == "hybrid_dw":
            from featurenet_tpu.ops.conv3d import HybridConv

            x = HybridConv(self.features, self.kernel, dtype=self.dtype)(x)
        elif (self.stride == 1 and self.conv_backend == "fused33"
                and self.kernel == 3):
            # Layout-specialized 3^3 path (ops/conv33.py): tap-unrolled
            # channels-last matmuls. Non-3^3 stride-1 blocks under the
            # same backend fall through to nn.Conv below — the
            # specialization is per-shape, not per-network. The explicit
            # name pins the param scope to nn.Conv's auto-name, so the
            # param TREE (not just the leaf shapes) matches the xla
            # backend's and a checkpoint restores under either — the
            # A/B-one-trained-run use the conv_backend identity
            # exemption exists for.
            from featurenet_tpu.ops.conv33 import Fused33Conv

            x = Fused33Conv(self.features, dtype=self.dtype,
                            name="Conv_0")(x)
        else:
            x = nn.Conv(
                self.features,
                kernel_size=(self.kernel,) * 3,
                strides=(self.stride,) * 3,
                padding="SAME",
                use_bias=False,  # BN immediately follows; bias is redundant
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(x)
        # BN statistics in fp32 regardless of activation dtype: running
        # moments must not accumulate in bf16.
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
        )(x)
        x = nn.relu(x)
        return x.astype(self.dtype)


class FeatureNet(nn.Module):
    """24-class voxel classifier.

    Input  ``voxels``: float ``[B, R, R, R, 1]`` (NDHWC occupancy grid).
    Output logits: fp32 ``[B, num_classes]``.

    Variable collections: ``params`` (fp32), ``batch_stats`` (fp32 BN moments).
    Dropout needs an rng under the ``"dropout"`` key when ``train=True``.
    """

    arch: FeatureNetArch = FeatureNetArch()
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, voxels, train: bool = False):
        a = self.arch
        x = voxels.astype(self.dtype)
        for f, k, s, p in zip(a.features, a.kernels, a.strides, a.pool_after):
            y = ConvBNRelu(
                f, k, s,
                dtype=self.dtype,
                stem_s2d=a.stem_s2d,
                conv_backend=a.conv_backend,
            )(x, train)
            if a.residual and s == 1 and x.shape[-1] == f:
                y = y + x  # identity skip; pooling stays outside the branch
            x = (
                nn.max_pool(y, window_shape=(2, 2, 2), strides=(2, 2, 2))
                if p
                else y
            )
        if a.head_gap:
            # fp32 accumulation for the spatial mean, back to compute dtype.
            x = jnp.mean(
                x, axis=(1, 2, 3), dtype=jnp.float32
            ).astype(self.dtype)
        else:
            x = x.reshape((x.shape[0], -1))
        x = nn.Dense(a.hidden, dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = nn.Dropout(rate=a.dropout, deterministic=not train)(x)
        x = nn.Dense(a.num_classes, dtype=self.dtype, param_dtype=jnp.float32)(x)
        # Logits in fp32: softmax/cross-entropy wants full precision.
        return x.astype(jnp.float32)
