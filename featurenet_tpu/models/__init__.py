"""Flax model families: voxel classifier and per-voxel segmenter."""

from featurenet_tpu.models.featurenet import FeatureNet, FeatureNetArch
from featurenet_tpu.models.segmenter import FeatureNetSegmenter

__all__ = ["FeatureNet", "FeatureNetArch", "FeatureNetSegmenter"]
