"""Per-voxel segmentation head (BASELINE.json config 4: ``seg64``).

The reference repo has no segmentation model — this is a *new capability*
listed in the driver's config ladder ("64^3 multi-feature per-voxel
segmentation head (dense output)", BASELINE.json:10). Design: a small
U-Net-shaped encoder/decoder over the same ConvBNRelu blocks as the
classifier. Encoder downsamples by stride-2 convs (not pools — the decoder
mirrors them with transposed convs), skip connections concatenate at equal
resolution, and the head emits ``num_classes + 1`` per-voxel logits
(class 0 = background / not-a-feature, matching
``featurenet_tpu.data.synthetic.generate_sample``'s ``seg`` encoding).

Round-4 levers (driven by ``train/seg_diagnose.py``'s attribution of the
round-3 IoU gap — BASELINE.md):

- ``input_context``: the 0.050 through/blind family confusion is a GLOBAL
  property — whether a carve reaches the opposite face — that an 8³
  bottleneck sees only weakly. ``"proj"`` appends three axis-projection
  channels (mean occupancy along each axis, broadcast back), which encode
  "does an empty column run all the way through here" directly at the
  input; ``"proj_coords"`` adds three normalized coordinate channels on
  top. Pure reductions + broadcasts — negligible TPU cost.
- ``decoder_blocks`` / ``bottleneck_blocks``: capacity for the ~0.14
  inter-feature boundary-assignment term (extra refine convs per decoder
  stage / bottleneck).

TPU notes: everything stays NDHWC/bf16 like the classifier; transposed convs
lower to regular convs on TPU (XLA rewrites them), so the whole decoder is
MXU work. Skip concatenation is on the channel (minor) axis — free layout-wise.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from featurenet_tpu.data.synthetic import NUM_CLASSES
from featurenet_tpu.models.featurenet import ConvBNRelu

INPUT_CONTEXTS = ("none", "proj", "proj_coords")


class FeatureNetSegmenter(nn.Module):
    """Dense per-voxel classifier.

    Input  ``voxels``: float ``[B, R, R, R, 1]``; R must be divisible by
    ``2**len(features)``.
    Output logits: fp32 ``[B, R, R, R, num_classes + 1]``.
    """

    features: Sequence[int] = (32, 64, 128)
    num_classes: int = NUM_CLASSES
    dtype: jnp.dtype = jnp.bfloat16
    input_context: str = "none"
    decoder_blocks: int = 1
    bottleneck_blocks: int = 1

    @nn.compact
    def __call__(self, voxels, train: bool = False):
        if self.input_context not in INPUT_CONTEXTS:
            raise ValueError(
                f"input_context {self.input_context!r} not in "
                f"{INPUT_CONTEXTS}"
            )
        v = voxels.astype(jnp.float32)
        chans = [v]
        if self.input_context != "none":
            # Axis-projection channels: mean occupancy along each spatial
            # axis, broadcast back over it. A through-feature is an empty
            # column spanning the whole part — visible here at the input,
            # not only after the encoder has compressed it away.
            for ax in (1, 2, 3):
                chans.append(
                    jnp.broadcast_to(v.mean(axis=ax, keepdims=True), v.shape)
                )
        if self.input_context == "proj_coords":
            for ax, n in zip((1, 2, 3), v.shape[1:4]):
                shape = [1, 1, 1, 1, 1]
                shape[ax] = n
                coord = jnp.linspace(0.0, 1.0, n).reshape(shape)
                chans.append(jnp.broadcast_to(coord, v.shape))
        x = jnp.concatenate(chans, axis=-1).astype(self.dtype)
        skips = []
        # Encoder: each stage = refine at-res, then strided downsample.
        for f in self.features:
            x = ConvBNRelu(f, kernel=3, stride=1, dtype=self.dtype)(x, train)
            skips.append(x)
            x = ConvBNRelu(f, kernel=3, stride=2, dtype=self.dtype)(x, train)
        # Bottleneck.
        for _ in range(self.bottleneck_blocks):
            x = ConvBNRelu(
                self.features[-1] * 2, kernel=3, dtype=self.dtype
            )(x, train)
        # Decoder: transposed-conv upsample, concat skip, refine.
        for f, skip in zip(reversed(self.features), reversed(skips)):
            x = nn.ConvTranspose(
                f,
                kernel_size=(2, 2, 2),
                strides=(2, 2, 2),
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(x)
            x = jnp.concatenate([x, skip], axis=-1)
            for _ in range(self.decoder_blocks):
                x = ConvBNRelu(f, kernel=3, dtype=self.dtype)(x, train)
        x = nn.Conv(
            self.num_classes + 1,
            kernel_size=(1, 1, 1),
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)
        return x.astype(jnp.float32)
