"""Per-voxel segmentation head (BASELINE.json config 4: ``seg64``).

The reference repo has no segmentation model — this is a *new capability*
listed in the driver's config ladder ("64^3 multi-feature per-voxel
segmentation head (dense output)", BASELINE.json:10). Design: a small
U-Net-shaped encoder/decoder over the same ConvBNRelu blocks as the
classifier. Encoder downsamples by stride-2 convs (not pools — the decoder
mirrors them with transposed convs), skip connections concatenate at equal
resolution, and the head emits ``num_classes + 1`` per-voxel logits
(class 0 = background / not-a-feature, matching
``featurenet_tpu.data.synthetic.generate_sample``'s ``seg`` encoding).

TPU notes: everything stays NDHWC/bf16 like the classifier; transposed convs
lower to regular convs on TPU (XLA rewrites them), so the whole decoder is
MXU work. Skip concatenation is on the channel (minor) axis — free layout-wise.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn

from featurenet_tpu.data.synthetic import NUM_CLASSES
from featurenet_tpu.models.featurenet import ConvBNRelu


class FeatureNetSegmenter(nn.Module):
    """Dense per-voxel classifier.

    Input  ``voxels``: float ``[B, R, R, R, 1]``; R must be divisible by
    ``2**len(features)``.
    Output logits: fp32 ``[B, R, R, R, num_classes + 1]``.
    """

    features: Sequence[int] = (32, 64, 128)
    num_classes: int = NUM_CLASSES
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, voxels, train: bool = False):
        x = voxels.astype(self.dtype)
        skips = []
        # Encoder: each stage = refine at-res, then strided downsample.
        for f in self.features:
            x = ConvBNRelu(f, kernel=3, stride=1, dtype=self.dtype)(x, train)
            skips.append(x)
            x = ConvBNRelu(f, kernel=3, stride=2, dtype=self.dtype)(x, train)
        # Bottleneck.
        x = ConvBNRelu(self.features[-1] * 2, kernel=3, dtype=self.dtype)(x, train)
        # Decoder: transposed-conv upsample, concat skip, refine.
        for f, skip in zip(reversed(self.features), reversed(skips)):
            x = nn.ConvTranspose(
                f,
                kernel_size=(2, 2, 2),
                strides=(2, 2, 2),
                dtype=self.dtype,
                param_dtype=jnp.float32,
            )(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBNRelu(f, kernel=3, dtype=self.dtype)(x, train)
        x = nn.Conv(
            self.num_classes + 1,
            kernel_size=(1, 1, 1),
            dtype=self.dtype,
            param_dtype=jnp.float32,
        )(x)
        return x.astype(jnp.float32)
