// Native voxelizer: exact triangle-box surface rasterization + parity fill.
//
// The reference's native analog is the third-party `binvox` binary many
// FeatureNet forks shell out to (SURVEY.md §2 C2 / native ledger); this is a
// first-party replacement with two entry points matching the Python
// semantics in featurenet_tpu/data/voxelize.py:
//
//   fill=0  -> surface shell: voxel marked iff its axis-aligned box
//              geometrically intersects any triangle (Akenine-Möller SAT —
//              exact, a superset of the Python sampling rasterizer).
//   fill=1  -> center-inside solid: vertical-ray parity per voxel-center
//              column, identical jitter constants to the numpy path so the
//              two backends agree bit-for-bit on watertight meshes.
//
// Parallelism: OpenMP over triangles; toggles accumulate with atomics
// (surface writes are idempotent |=, races are benign by value).
//
// Build: g++ -O3 -shared -fPIC -fopenmp (driven by featurenet_tpu/native/__init__.py).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct V3 {
  double x, y, z;
};

inline V3 sub(V3 a, V3 b) { return {a.x - b.x, a.y - b.y, a.z - b.z}; }
inline V3 cross(V3 a, V3 b) {
  return {a.y * b.z - a.z * b.y, a.z * b.x - a.x * b.z, a.x * b.y - a.y * b.x};
}
inline double dot(V3 a, V3 b) { return a.x * b.x + a.y * b.y + a.z * b.z; }

inline void minmax3(double a, double b, double c, double& lo, double& hi) {
  lo = a < b ? (a < c ? a : c) : (b < c ? b : c);
  hi = a > b ? (a > c ? a : c) : (b > c ? b : c);
}

// Akenine-Möller triangle/AABB overlap. Box centered at `c` with half-size h
// (cubic). Vertices are pre-translated into box space by the caller.
bool tri_box_overlap(const V3& c, double h, V3 v0, V3 v1, V3 v2) {
  v0 = sub(v0, c);
  v1 = sub(v1, c);
  v2 = sub(v2, c);
  V3 e0 = sub(v1, v0), e1 = sub(v2, v1), e2 = sub(v0, v2);

  double lo, hi;
  // 1) AABB overlap on the three coordinate axes.
  minmax3(v0.x, v1.x, v2.x, lo, hi);
  if (lo > h || hi < -h) return false;
  minmax3(v0.y, v1.y, v2.y, lo, hi);
  if (lo > h || hi < -h) return false;
  minmax3(v0.z, v1.z, v2.z, lo, hi);
  if (lo > h || hi < -h) return false;

  // 2) Plane of the triangle vs box.
  V3 n = cross(e0, e1);
  double d = -dot(n, v0);
  double r = h * (std::fabs(n.x) + std::fabs(n.y) + std::fabs(n.z));
  if (std::fabs(d) > r) return false;

  // 3) Nine cross-product axes a_ij = e_i x unit_j.
  auto axis_test = [&](double ax, double ay, double az) {
    double p0 = ax * v0.x + ay * v0.y + az * v0.z;
    double p1 = ax * v1.x + ay * v1.y + az * v1.z;
    double p2 = ax * v2.x + ay * v2.y + az * v2.z;
    double mn = std::fmin(p0, std::fmin(p1, p2));
    double mx = std::fmax(p0, std::fmax(p1, p2));
    double rad = h * (std::fabs(ax) + std::fabs(ay) + std::fabs(az));
    return mn <= rad && mx >= -rad;
  };
  const V3 es[3] = {e0, e1, e2};
  for (const V3& e : es) {
    if (!axis_test(0, -e.z, e.y)) return false;   // e x X
    if (!axis_test(e.z, 0, -e.x)) return false;   // e x Y
    if (!axis_test(-e.y, e.x, 0)) return false;   // e x Z
  }
  return true;
}

}  // namespace

extern "C" {

// tris: float32 [n, 3, 3] already normalized into [0,1]^3 (voxelize.py does
// normalize_mesh first). out: uint8 [R*R*R], C-order [x][y][z]. Returns 0.
int fn_voxelize_surface(const float* tris, long n_tris, int R, uint8_t* out) {
  // Conservative: boxes are inflated by EPS voxels so float32 rounding in
  // callers (mesh data is fp32) can never make a genuinely-touched voxel
  // test negative. Keeps the shell a guaranteed superset of any on-triangle
  // point sampling.
  const double EPS = 1e-4;
  std::memset(out, 0, (size_t)R * R * R);
#pragma omp parallel for schedule(dynamic, 64)
  for (long t = 0; t < n_tris; ++t) {
    const float* p = tris + t * 9;
    // Voxel coordinates: voxel i spans [i, i+1).
    V3 v0{p[0] * R, p[1] * R, p[2] * R};
    V3 v1{p[3] * R, p[4] * R, p[5] * R};
    V3 v2{p[6] * R, p[7] * R, p[8] * R};
    double lo, hi;
    int x0, x1, y0, y1, z0, z1;
    minmax3(v0.x, v1.x, v2.x, lo, hi);
    x0 = std::max(0, (int)std::floor(lo - EPS));
    x1 = std::min(R - 1, (int)std::floor(hi + EPS));
    minmax3(v0.y, v1.y, v2.y, lo, hi);
    y0 = std::max(0, (int)std::floor(lo - EPS));
    y1 = std::min(R - 1, (int)std::floor(hi + EPS));
    minmax3(v0.z, v1.z, v2.z, lo, hi);
    z0 = std::max(0, (int)std::floor(lo - EPS));
    z1 = std::min(R - 1, (int)std::floor(hi + EPS));
    for (int x = x0; x <= x1; ++x)
      for (int y = y0; y <= y1; ++y)
        for (int z = z0; z <= z1; ++z) {
          V3 c{x + 0.5, y + 0.5, z + 0.5};
          if (tri_box_overlap(c, 0.5 + EPS, v0, v1, v2))
            out[((size_t)x * R + y) * R + z] = 1;  // idempotent; race-benign
        }
  }
  return 0;
}

// Center-inside parity fill; numerically identical to
// voxelize.py::_voxelize_parity (same jitter, same ceil rule).
int fn_voxelize_fill(const float* tris, long n_tris, int R, uint8_t* out) {
  const double ex = 7.3e-7, ey = 3.1e-7;
  std::vector<int> toggles((size_t)R * R * (R + 1), 0);
#pragma omp parallel for schedule(dynamic, 64)
  for (long t = 0; t < n_tris; ++t) {
    const float* p = tris + t * 9;
    double x0 = p[0] * R, y0 = p[1] * R, z0 = p[2] * R;
    double x1 = p[3] * R, y1 = p[4] * R, z1 = p[5] * R;
    double x2 = p[6] * R, y2 = p[7] * R, z2 = p[8] * R;
    double det = (y1 - y2) * (x0 - x2) + (x2 - x1) * (y0 - y2);
    if (std::fabs(det) < 1e-12) continue;
    int ix_lo = std::max(0, (int)std::ceil(std::fmin(x0, std::fmin(x1, x2)) - 0.5 - ex));
    int ix_hi = std::min(R - 1, (int)std::floor(std::fmax(x0, std::fmax(x1, x2)) - 0.5 - ex));
    int iy_lo = std::max(0, (int)std::ceil(std::fmin(y0, std::fmin(y1, y2)) - 0.5 - ey));
    int iy_hi = std::min(R - 1, (int)std::floor(std::fmax(y0, std::fmax(y1, y2)) - 0.5 - ey));
    for (int ix = ix_lo; ix <= ix_hi; ++ix) {
      double px = ix + 0.5 + ex;
      for (int iy = iy_lo; iy <= iy_hi; ++iy) {
        double py = iy + 0.5 + ey;
        double a = ((y1 - y2) * (px - x2) + (x2 - x1) * (py - y2)) / det;
        double b = ((y2 - y0) * (px - x2) + (x0 - x2) * (py - y2)) / det;
        double c = 1.0 - a - b;
        if (a < 0 || b < 0 || c < 0) continue;
        double zstar = a * z0 + b * z1 + c * z2;
        long k = (long)std::ceil(zstar - 0.5);
        if (k < 0) k = 0;
        if (k > R) k = R;
#pragma omp atomic
        toggles[((size_t)ix * R + iy) * (R + 1) + k] += 1;
      }
    }
  }
  for (int x = 0; x < R; ++x)
    for (int y = 0; y < R; ++y) {
      int par = 0;
      const int* col = &toggles[((size_t)x * R + y) * (R + 1)];
      uint8_t* o = &out[((size_t)x * R + y) * R];
      for (int z = 0; z < R; ++z) {
        par ^= (col[z] & 1);
        o[z] = (uint8_t)par;
      }
    }
  return 0;
}

}  // extern "C"
