"""Native (C++) components, bound via ctypes — no pybind11 dependency.

Currently: the voxelizer (`voxelize.cpp`) — exact SAT surface rasterization
and parity solid fill, OpenMP-parallel over triangles. The shared library is
compiled on first use with g++ (and cached next to the source, keyed on
source mtime), so the repo needs no build step and no installed wheel.

Public API: ``voxelize_native(tris, resolution, fill) -> bool [R,R,R]``.
``featurenet_tpu.data.voxelize`` auto-dispatches here when the toolchain is
available and falls back to numpy when it is not.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(__file__)
_SRC = os.path.join(_HERE, "voxelize.cpp")
_LIB = os.path.join(_HERE, "_libfnvox.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _build() -> None:
    # Per-process temp name: concurrent cold builds (multi-process pytest,
    # multi-host shared FS) each write their own file; os.replace is atomic,
    # last writer wins with a complete .so either way.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-fopenmp", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            # A stale/foreign-ABI .so (e.g. committed from another platform,
            # with checkout mtimes masking it as fresh): rebuild and retry.
            _build()
            lib = ctypes.CDLL(_LIB)
        for fn in (lib.fn_voxelize_surface, lib.fn_voxelize_fill):
            fn.restype = ctypes.c_int
            fn.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_long,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
            ]
        _lib = lib
        return lib


def available() -> bool:
    """True if the native backend is (or can be) built on this machine."""
    try:
        _load()
        return True
    except Exception:
        return False


def voxelize_native(
    triangles: np.ndarray, resolution: int, fill: bool = True
) -> np.ndarray:
    """Native-path voxelization. Expects normalized [0,1]³ triangles.

    ``fill=True`` matches the numpy parity fill bit-for-bit on watertight
    meshes; ``fill=False`` is the *exact* surface shell (a superset of the
    numpy sampling rasterizer, which can only under-mark).
    """
    lib = _load()
    tris = np.ascontiguousarray(triangles, dtype=np.float32)
    if tris.ndim != 3 or tris.shape[1:] != (3, 3):
        raise ValueError(f"expected [n,3,3] triangles, got {tris.shape}")
    R = int(resolution)
    out = np.zeros(R * R * R, dtype=np.uint8)
    fn = lib.fn_voxelize_fill if fill else lib.fn_voxelize_surface
    rc = fn(
        tris.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.c_long(tris.shape[0]),
        ctypes.c_int(R),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc != 0:
        raise RuntimeError(f"native voxelizer failed with code {rc}")
    return out.reshape(R, R, R).astype(bool)
