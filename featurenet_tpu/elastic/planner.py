"""The re-mesh planner: which world the surviving hosts should form.

The one invariant a re-form must keep is the **global batch**: the
training trajectory is defined by ``Config.global_batch``, so a shrink
from N hosts to N-1 must rescale the per-host share, never the global
number (the Trainer refuses a global batch the data axis doesn't divide,
so an infeasible world would die at startup — the planner refuses it
here, before any process is spawned). FeatureNet training is pure data
parallelism over the classifier, so any world size whose device count
divides the global batch is admissible down to ``min_world_size``.
"""

from __future__ import annotations

from typing import Iterable


class InfeasibleWorld(RuntimeError):
    """No admissible mesh can be formed from the surviving hosts."""


def feasible_world_sizes(global_batch: int, local_devices: int,
                         max_hosts: int) -> list[int]:
    """Every world size ``1..max_hosts`` whose data axis
    (``n * local_devices``) divides ``global_batch``, ascending."""
    if global_batch < 1 or local_devices < 1:
        raise ValueError(
            f"global_batch ({global_batch}) and local_devices "
            f"({local_devices}) must be >= 1"
        )
    return [
        n for n in range(1, max_hosts + 1)
        if global_batch % (n * local_devices) == 0
    ]


def per_host_batch(global_batch: int, world_size: int) -> int:
    """The per-host share of a preserved global batch at ``world_size``."""
    if world_size < 1 or global_batch % world_size:
        raise ValueError(
            f"global_batch {global_batch} does not split over "
            f"{world_size} host(s)"
        )
    return global_batch // world_size


def plan_world(available: Iterable[int], *, min_world_size: int,
               global_batch: int, local_devices: int) -> tuple[int, ...]:
    """The member slots of the next generation: the largest feasible
    world over the available hosts, keeping the LOWEST slot ids (slot
    order is rank order, and rank 0 owns the primary event stream +
    ``run.json`` — stability there keeps the merged report anchored).

    Raises ``InfeasibleWorld`` when no world of at least
    ``min_world_size`` hosts divides the global batch — the caller's
    give-up verdict, not a crash deep inside a spawned child.
    """
    slots = sorted(set(available))
    if min_world_size < 1:
        raise ValueError(f"min_world_size must be >= 1, got {min_world_size}")
    for n in range(len(slots), 0, -1):
        if n < min_world_size:
            break
        if global_batch % (n * local_devices) == 0:
            return tuple(slots[:n])
    raise InfeasibleWorld(
        f"no feasible world from {len(slots)} available host(s): need >= "
        f"{min_world_size} host(s) whose {local_devices}-device data axis "
        f"divides global_batch {global_batch}"
    )
