"""Elastic multi-host training (ROADMAP item 3).

The plain supervisor (``train.supervisor``) respawns a fixed-shape world:
lose one host of an N-host mesh and training is dead until that exact
host returns. This package makes the world **elastic**:

- ``membership``: the durable membership file + generation counter in
  ``run_dir`` — which host slots form the current mesh, and why.
- ``planner``: the re-mesh planner — given the surviving hosts, pick the
  largest world the global batch divides over (global batch is
  *preserved* across re-forms; the per-host share rescales).
- ``coordinator``: the elastic coordinator — an N-child supervisor that
  detects host loss (death or stalled heartbeat), **shrinks** the mesh to
  the survivors (respawn from the latest checksummed checkpoint at the
  new world shape), and **grows** it back by re-admitting recovered
  hosts at the next generation boundary (an exit-75 planned cut).

FeatureNet training is pure data parallelism over the classifier, so the
model admits any mesh size >= 1; the pieces this composes — per-host
event streams, exit-75 planned restarts, crash-loop backoff, checksummed
checkpoints, the runtime registry's rebuild-on-any-mesh — shipped in the
ops-layer PRs and are reused here, not reimplemented.
"""

from featurenet_tpu.elastic.coordinator import (  # noqa: F401
    ElasticCoordinator,
    ElasticResult,
    heartbeat_path,
)
from featurenet_tpu.elastic.membership import (  # noqa: F401
    MEMBERSHIP_FILENAME,
    Membership,
    read_membership,
    ready_slots,
    signal_ready,
    write_membership,
)
from featurenet_tpu.elastic.planner import (  # noqa: F401
    InfeasibleWorld,
    feasible_world_sizes,
    per_host_batch,
    plan_world,
)
