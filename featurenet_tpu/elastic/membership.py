"""The membership file: which host slots form the mesh, and since when.

One JSON document (``membership.json``) in the run directory, rewritten
atomically at every mesh re-form. It is the durable half of the elastic
protocol: children are told their world shape on their argv (the
coordinator owns the live decision), but the *file* is what an external
host agent — or an operator mid-incident — reads to answer "what
generation is this run on, at what size, and why": a recovered host's
agent polls it to learn that the mesh shrank without it and that it
should ask to rejoin, and the post-mortem reads the final generation
straight from the run dir next to the event streams that explain it.

Stdlib-only, like the rest of the run-dir protocol (heartbeat files,
fault markers, gate baselines): the coordinator process never imports a
backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

MEMBERSHIP_FILENAME = "membership.json"


@dataclasses.dataclass(frozen=True)
class Membership:
    """One generation's mesh: the slot ids that form it, ordered — the
    rank of a member is its index in ``members``.

    ``ready`` is the external-agent re-admission channel: a recovered
    host's agent writes its slot here (``signal_ready``) to ask back in,
    and a coordinator running ``readmit="agent"`` re-admits ONLY
    signaled slots at the next generation boundary — a still-dead host
    is never blindly re-offered a rank it can't fill. The serving
    fleet's roster reuses the same document shape (members = ready
    replicas, reason = replica_loss / replica_rejoin)."""

    generation: int
    members: tuple[int, ...]
    min_world_size: int
    reason: str  # "start" | "host_loss" | "host_rejoin" | "planned" | ...
    ready: tuple[int, ...] = ()  # slots that signaled recovery

    @property
    def world_size(self) -> int:
        return len(self.members)


def membership_path(run_dir: str) -> str:
    return os.path.join(os.path.abspath(run_dir), MEMBERSHIP_FILENAME)


def write_membership(run_dir: str, m: Membership) -> str:
    """Atomically persist ``m`` (tmp + rename — a coordinator killed
    mid-write must never leave half a membership for an agent to act on).
    Returns the path written."""
    path = membership_path(run_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "generation": m.generation,
        "world_size": m.world_size,
        "members": list(m.members),
        "min_world_size": m.min_world_size,
        "reason": m.reason,
        "ready": list(m.ready),
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
    os.replace(tmp, path)
    return path


def read_membership(run_dir: str) -> Optional[Membership]:
    """The persisted membership, or ``None`` when the run never wrote one
    (a non-elastic run, or a coordinator that died before generation 0).
    A torn/garbled file also reads as ``None`` — the writer is atomic, so
    garbage means something else wrote here; acting on it would be worse
    than "unknown"."""
    try:
        with open(membership_path(run_dir), encoding="utf-8") as fh:
            doc = json.load(fh)
        return Membership(
            generation=int(doc["generation"]),
            members=tuple(int(s) for s in doc["members"]),
            min_world_size=int(doc.get("min_world_size", 1)),
            reason=str(doc.get("reason", "")),
            # Absent in pre-agent documents: an old membership.json must
            # keep reading (no signals is exactly what it means).
            ready=tuple(int(s) for s in doc.get("ready", ())),
        )
    except (OSError, ValueError, KeyError, TypeError):
        return None


def signal_ready(run_dir: str, slot: int) -> bool:
    """The external host agent's half of the re-admission protocol: mark
    ``slot`` ready in the membership file. Returns True when the signal
    is durably recorded (or the slot already serves in the current
    generation — nothing to signal); False when no membership exists yet
    to signal against (the agent should poll again).

    The write is read-modify-replace on the atomic writer. A coordinator
    re-form racing this write can drop a just-landed signal — the agent
    polls ``membership.json`` anyway (that is how it learned it was shed)
    and re-signals until a generation admits it, so a lost signal costs
    one boundary, never the run."""
    m = read_membership(run_dir)
    if m is None:
        return False
    slot = int(slot)
    if slot in m.members or slot in m.ready:
        return True
    write_membership(run_dir, dataclasses.replace(
        m, ready=tuple(sorted(set(m.ready) | {slot}))
    ))
    return True


def ready_slots(run_dir: str) -> set[int]:
    """The slots whose agents signaled recovery (empty when no
    membership exists or none signaled) — what a ``readmit="agent"``
    coordinator consults at each generation boundary."""
    m = read_membership(run_dir)
    return set(m.ready) if m is not None else set()
