"""The elastic coordinator: an N-child supervisor that re-forms the mesh.

``train.supervisor.supervise`` watches ONE child and respawns the same
world shape; this coordinator owns a *generation* of N training
processes (one per host slot) and makes the world shape itself a
recovery lever:

- **Host loss** (a child dies without the planned exit code, or its
  heartbeat goes stale): the whole generation is killed — a lockstep
  mesh with a dead member is wedged in its next collective, nothing
  softer than SIGKILL is guaranteed to land — the lost slot is removed,
  and the survivors are respawned as generation G+1 at the smaller
  world. Each child resumes from the latest checksummed checkpoint
  (``CheckpointManager.restore`` walks back past torn steps and the
  state template carries the *new* mesh's shardings, so the restore is
  the reshard) with the per-host batch rescaled — the global batch is
  preserved by the planner's feasibility rule.
- **Host recovery**: a lost slot is re-admitted at the next generation
  boundary — an all-exit-75 planned cut (``restart_every_steps``, a
  drained SIGTERM) — so growth never interrupts a healthy generation.
  A re-admitted host that fails to come up is shed again as a startup
  loss; it does not take the run down.
- **Full-world loss** (the crash took every remaining slot below
  ``min_world_size``): every lost slot is re-admitted immediately and
  the world restarts at full shape on the reform budget — the
  degenerate case is exactly the plain supervisor's respawn.

One loss verdict per reform: when several children die near-
simultaneously, only the FIRST observed death is charged as a host loss
— the rest are the cascade of a mesh losing a member (peers error out
of their collectives within the same poll window) and of the
coordinator's own kill, and shedding them too would shrink a healthy
fleet to nothing on one bad host.

Every decision lands in host 0's event stream (the supervisor's
convention): ``supervisor`` phase events for spawn/stall/backoff/
planned_restart/done/giving_up, plus the elastic kinds —
``mesh_reform{generation, from_n, to_n, reason}`` on every shape change,
``host_leave``/``host_join`` per slot. ``cli report`` folds them into
the recovery section; ``membership.json`` in the run dir is the durable
snapshot an external host agent polls.

Stdlib-only: the coordinator process never initializes a backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import socket
import subprocess
import sys
import time
from typing import Callable, Optional, Sequence

from featurenet_tpu import faults
from featurenet_tpu.elastic.membership import (
    Membership,
    read_membership,
    ready_slots,
    write_membership,
)
from featurenet_tpu.elastic.planner import InfeasibleWorld, plan_world
# One heartbeat/stall state machine for both watchers: the coordinator
# drives one HeartbeatMonitor per slot, the plain supervisor drives one
# for its single child — the duplicated fresh-baseline/grace/re-read
# logic lives only in train.heartbeat now.
from featurenet_tpu.train.heartbeat import HeartbeatMonitor
from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE, _kill_tree


def heartbeat_path(run_dir: str, slot: int) -> str:
    """Per-slot heartbeat file (the coordinator and the spawn-argv
    builder must agree on the path, so it is a convention, not a
    parameter)."""
    return os.path.join(os.path.abspath(run_dir), f"heartbeat.{int(slot)}")


def _free_port() -> int:
    """An ephemeral port for the generation's jax.distributed
    coordinator (rank 0 binds it; each generation gets a fresh one so a
    SIGKILLed generation's half-dead service can never confuse the
    next)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class ElasticResult:
    exit_code: int      # 0 = the run completed its full step budget
    generations: int    # generations formed (including generation 0)
    reforms: int        # shape-changing re-forms (shrink + grow)
    losses: int         # host-loss verdicts
    rejoins: int        # slots re-admitted
    planned: int        # all-exit-75 generation boundaries


@dataclasses.dataclass
class _GenOutcome:
    kind: str           # "done" | "planned" | "loss" | "startup"
    dead: set           # slots charged as lost (kind == "loss")
    beats: set          # slots that produced at least one heartbeat
    exits: dict         # slot -> exit code (kill victims included)
    reason: str


class ElasticCoordinator:
    """Supervise an elastic world of up to ``n_hosts`` training
    processes.

    Args:
      n_hosts: host slots at full strength (slot ids ``0..n_hosts-1``).
      spawn: ``(members, rank, generation, port) -> argv`` — the child
        command for ``members[rank]``. The child must touch
        ``heartbeat_path(run_dir, members[rank])``, run its
        ``jax.distributed`` world over ``127.0.0.1:<port>`` when
        ``len(members) > 1``, and follow the supervisor exit protocol
        (0 done, 75 planned restart, anything else a crash).
      run_dir: the shared run directory — membership file, heartbeat
        files, fault markers, and host 0's event stream all live here.
      min_world_size: smallest admissible world; fewer surviving hosts
        than this forces the full-restart path (and, if even full
        strength can't form, the give-up verdict).
      global_batch / local_devices: the planner's feasibility inputs —
        the preserved global batch must divide every admitted world's
        data axis.
      stall_timeout_s / grace_s / poll_s / backoff_*: the plain
        supervisor's knobs, applied per slot.
      max_reforms: unplanned re-forms (loss, full restart, startup
        retry) allowed before giving up; planned boundaries are free.
      readmit: boundary re-admission policy — "auto" re-offers every
        lost slot, "agent" only slots that signaled recovery via
        ``membership.signal_ready`` (external host agents).
      env: environment for every child (None = inherit).
    """

    def __init__(
        self,
        n_hosts: int,
        spawn: Callable[[Sequence[int], int, int, int], list],
        run_dir: str,
        *,
        min_world_size: int = 1,
        global_batch: int = 1,
        local_devices: int = 1,
        stall_timeout_s: float = 600.0,
        grace_s: Optional[float] = None,
        poll_s: float = 5.0,
        max_reforms: int = 8,
        backoff_base_s: float = 1.0,
        backoff_cap_s: float = 60.0,
        readmit: str = "auto",
        env: Optional[dict] = None,
        log=print,
    ):
        if n_hosts < 1:
            raise ValueError(f"n_hosts must be >= 1, got {n_hosts}")
        if readmit not in ("auto", "agent"):
            raise ValueError(
                f"readmit must be 'auto' or 'agent', got {readmit!r}"
            )
        self.n_hosts = n_hosts
        self.spawn = spawn
        self.run_dir = os.path.abspath(run_dir)
        self.min_world_size = min_world_size
        self.global_batch = global_batch
        self.local_devices = local_devices
        self.stall_timeout_s = stall_timeout_s
        self.grace_s = grace_s if grace_s is not None else max(
            stall_timeout_s, 600.0
        )
        self.poll_s = poll_s
        self.max_reforms = max_reforms
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        # Re-admission policy at generation boundaries: "auto" blindly
        # re-offers every lost slot (a still-dead host fails startup and
        # is shed again — costs a reform); "agent" re-admits only slots
        # whose external agent signaled recovery via the membership file
        # (membership.signal_ready) — the carried ROADMAP follow-on.
        self.readmit = readmit
        self.env = env
        self.log = log
        self._spawns = 0
        self._rng = random.Random()  # backoff jitter; never test-visible

    # -- one generation -------------------------------------------------------

    def _run_generation(self, members: Sequence[int], generation: int,
                        port: int, record) -> _GenOutcome:
        # One shared heartbeat monitor per slot (train.heartbeat): reset
        # gives each spawn a fresh baseline — only a NEWER mtime proves
        # this generation's child beat (the supervisor's protocol).
        mons = {
            slot: HeartbeatMonitor(
                heartbeat_path(self.run_dir, slot),
                self.stall_timeout_s, self.grace_s,
            )
            for slot in members
        }
        for mon in mons.values():
            mon.reset()
        procs: dict[int, subprocess.Popen] = {}
        for rank, slot in enumerate(members):
            self._spawns += 1
            argv = list(self.spawn(list(members), rank, generation, port))
            if faults.maybe_fail("spawn_fail", spawn=self._spawns):
                argv = [sys.executable, "-c", "raise SystemExit(13)"]
            procs[slot] = subprocess.Popen(
                argv, start_new_session=True, env=self.env
            )
            self.log(json.dumps({
                "coordinator": "spawn", "host": slot, "rank": rank,
                "generation": generation, "pid": procs[slot].pid,
            }))
            record("spawn", host=slot, rank=rank, generation=generation,
                   pid=procs[slot].pid)
        self_exits: dict[int, int] = {}
        stalled: Optional[int] = None
        first_crash: Optional[int] = None
        while True:
            # Complete the sweep before judging: breaking at the first
            # dead slot would make the loss verdict an artifact of dict
            # order — a preempted slot 1 whose rank-0 peer errored out of
            # the wedged collective inside the same poll window would
            # read as "slot 0 died first" and shed the healthy host that
            # owns the primary event stream.
            sweep_dead: list[int] = []
            for slot, p in procs.items():
                if slot in self_exits:
                    continue
                rc = p.poll()
                if rc is not None:
                    self_exits[slot] = rc
                    if rc not in (0, RESTART_EXIT_CODE):
                        sweep_dead.append(slot)
            if sweep_dead and first_crash is None:
                # One loss verdict per reform: THE loss is the first
                # observed death; peers dead in the same sweep are its
                # cascade (see module docstring). Within one sweep the
                # order is unobservable, so prefer the death that LOOKS
                # like a host loss — killed by a signal (preemption,
                # OOM-kill, yanked node), not a collective/runtime error
                # exiting through Python.
                first_crash = next(
                    (s for s in sweep_dead if self_exits[s] < 0),
                    sweep_dead[0],
                )
            if first_crash is not None or len(self_exits) == len(procs):
                break
            time.sleep(self.poll_s)
            for slot in members:
                if slot in self_exits:
                    continue
                # Deleted-file recreate, first-beat-vs-grace, and the
                # re-read-before-verdict double check all live in the
                # shared monitor (a SIGKILL on a live mesh costs a
                # whole-generation restart for nothing).
                if mons[slot].poll() == "stall":
                    stalled = slot
                    break
            if stalled is not None:
                self.log(json.dumps({
                    "coordinator": "stall", "host": stalled,
                    "generation": generation,
                }))
                record("stall", host=stalled, generation=generation)
                break
        if first_crash is not None:
            # A fast-failing WORLD (bad flag, broken cache) staggers its
            # self-exits across spawn order; give the peers one short
            # window to also die on their own before the kill below
            # would turn them into "survivors we killed" — the
            # startup-vs-loss discriminator. A genuinely isolated crash
            # leaves peers mid-compile/mid-step; they never exit here.
            deadline = time.monotonic() + min(self.poll_s, 0.5)
            while time.monotonic() < deadline \
                    and any(s not in self_exits for s in procs):
                for slot, p in procs.items():
                    if slot not in self_exits:
                        rc = p.poll()
                        if rc is not None:
                            self_exits[slot] = rc
                time.sleep(0.02)
        # Final beat sweep (a beat may have landed inside the last poll
        # window) BEFORE the kills below can freeze the mtimes.
        for mon in mons.values():
            mon.recheck()
        beats = {slot for slot, mon in mons.items() if mon.beaten}
        exits = dict(self_exits)
        if first_crash is not None or stalled is not None:
            survivors_killed = 0
            for slot, p in procs.items():
                if p.poll() is None:
                    survivors_killed += 1
                    _kill_tree(p)
                exits.setdefault(slot, p.returncode)
            dead = {stalled} if stalled is not None else {first_crash}
            reason = ("stall" if stalled is not None
                      else f"exit_{self_exits[first_crash]}")
            if not beats and not survivors_killed:
                # Every member self-exited before anyone came up — a
                # deterministic whole-generation startup failure (bad
                # flag, broken cache), not a host dying under load;
                # shrinking would misdiagnose it. If the coordinator had
                # to kill live peers, the crash was ISOLATED — one bad
                # host in an otherwise-healthy world still climbing
                # through backend init/compile/restore — and that host
                # must be shed (kind "loss"), not allowed to take the
                # whole run down via the startup-fails-twice verdict.
                return _GenOutcome("startup", set(), beats, exits, reason)
            return _GenOutcome("loss", dead, beats, exits, reason)
        for slot, p in procs.items():
            p.wait()
        if all(rc == 0 for rc in exits.values()):
            return _GenOutcome("done", set(), beats, exits, "done")
        if beats:
            # Uniform exit-75 (or a 0/75 mix at the budget edge): the
            # generation checkpointed and asked for a fresh world — the
            # boundary where growth happens.
            return _GenOutcome("planned", set(), beats, exits, "planned")
        return _GenOutcome("startup", set(), beats, exits,
                           "exit_75_before_first_heartbeat")

    # -- the generation loop --------------------------------------------------

    def run(self) -> ElasticResult:
        from featurenet_tpu.obs.events import EventSink, events_filename

        sink = EventSink(self.run_dir, filename=events_filename(0))

        def record(phase: str, **fields) -> None:
            sink.emit("supervisor", phase=phase, **fields)

        avail = set(range(self.n_hosts))
        lost: dict[int, int] = {}  # slot -> generation it was lost in
        generation = 0
        prev_n = 0
        reason = "start"
        reforms = losses = rejoins = planned = 0
        reforms_used = 0
        startup_fails = 0
        consec_failures = 0

        def give_up(why: str, code: int) -> ElasticResult:
            self.log(json.dumps({"coordinator": "giving_up", "reason": why}))
            record("giving_up", reason=why, generation=generation,
                   losses=losses, reforms=reforms)
            sink.close()
            return ElasticResult(code if code else 1, generation + 1,
                                 reforms, losses, rejoins, planned)

        while True:
            try:
                members = plan_world(
                    avail,
                    min_world_size=self.min_world_size,
                    global_batch=self.global_batch,
                    local_devices=self.local_devices,
                )
            except InfeasibleWorld as e:
                return give_up(str(e), 1)
            if len(members) != prev_n:
                sink.emit("mesh_reform", generation=generation,
                          from_n=prev_n, to_n=len(members), reason=reason)
                self.log(json.dumps({
                    "coordinator": "mesh_reform", "generation": generation,
                    "from_n": prev_n, "to_n": len(members), "reason": reason,
                }))
                if prev_n:
                    reforms += 1
            # Preserve the agent readiness signals of slots still out of
            # the mesh (the write replaces the whole document); a signal
            # for a slot now serving is consumed by its admission.
            prev = read_membership(self.run_dir)
            pending = tuple(sorted(
                set(prev.ready) - set(members)
            )) if prev is not None else ()
            write_membership(self.run_dir, Membership(
                generation=generation,
                members=tuple(members),
                min_world_size=self.min_world_size,
                reason=reason,
                ready=pending,
            ))
            out = self._run_generation(
                members, generation, _free_port(), record
            )
            if out.kind == "done":
                self.log(json.dumps({
                    "coordinator": "done", "generation": generation,
                    "world_size": len(members), "losses": losses,
                    "rejoins": rejoins, "planned": planned,
                }))
                record("done", generation=generation,
                       world_size=len(members), losses=losses,
                       rejoins=rejoins, planned=planned)
                sink.close()
                return ElasticResult(0, generation + 1, reforms, losses,
                                     rejoins, planned)
            if out.kind == "planned":
                planned += 1
                consec_failures = 0
                startup_fails = 0
                record("planned_restart", count=planned,
                       generation=generation)
                generation += 1
                prev_n = len(members)
                if lost:
                    # The generation boundary is where recovered hosts
                    # rejoin. "auto" offers every lost slot the next
                    # world (one still dead fails startup and is shed
                    # again without taking the run down); "agent" admits
                    # only the slots whose recovery agent signaled
                    # readiness into membership.json — the rest stay
                    # shed until they do.
                    back = sorted(lost) if self.readmit == "auto" else \
                        sorted(s for s in lost
                               if s in ready_slots(self.run_dir))
                    for slot in back:
                        sink.emit("host_join", host=slot,
                                  generation=generation)
                        rejoins += 1
                        del lost[slot]
                    avail |= set(back)
                    reason = "host_rejoin" if back else "planned"
                else:
                    reason = "planned"
                continue
            # Unplanned: a loss or a whole-generation startup failure.
            reforms_used += 1
            if out.kind == "startup":
                startup_fails += 1
                if startup_fails >= 2:
                    return give_up(
                        f"{out.reason} twice — deterministic startup "
                        "failure", max(out.exits.values(), default=1),
                    )
                reason = "restart"
            else:
                startup_fails = 0
                for slot in sorted(out.dead):
                    losses += 1
                    sink.emit("host_leave", host=slot,
                              generation=generation, reason=out.reason)
                    avail.discard(slot)
                    lost[slot] = generation
                reason = "host_loss"
                if len(avail) < self.min_world_size:
                    # Full-world loss: below the floor there is no mesh
                    # to shrink to — re-admit everything and restart at
                    # strength (the plain supervisor's move), still on
                    # the reform budget. Even under readmit="agent":
                    # waiting for signals here would idle the whole run
                    # on agents that may never come; a still-dead slot
                    # fails startup and is shed again.
                    for slot in sorted(lost):
                        sink.emit("host_join", host=slot,
                                  generation=generation + 1)
                        rejoins += 1
                    avail |= set(lost)
                    lost.clear()
                    reason = "restart"
            if reforms_used > self.max_reforms:
                return give_up(
                    f"reform budget exhausted ({self.max_reforms})",
                    max(out.exits.values(), default=1),
                )
            # Crash-loop backoff, shared shape with the supervisor's: a
            # deterministic crash at full respawn speed would burn the
            # reform budget in seconds.
            consec_failures += 1
            delay = min(self.backoff_cap_s,
                        self.backoff_base_s * (2 ** (consec_failures - 1)))
            delay *= 0.5 + 0.5 * self._rng.random()
            if delay > 0:
                record("backoff", delay_s=round(delay, 3),
                       consecutive_failures=consec_failures)
                time.sleep(delay)
            generation += 1
            prev_n = len(members)
