"""Deterministic fault injection for the recovery paths (chaos layer).

The supervisor + obs stack claims to recover from hung collectives, dead
ranks, corrupt checkpoints, and preempted processes — but until this module
every recovery path was exercised only by synthetic unit tests, never by a
real injected failure inside a real run. ``faults`` makes "handles as many
scenarios as you can imagine" (ROADMAP north star) a *tested* property: a
run configured with ``Config.inject_faults`` / ``--inject-faults`` fails in
a precisely scripted way, and the e2e tests assert the run still completes
with the matching recovery event in its ``events.jsonl``.

Spec DSL (comma-separated, one entry per site)::

    checkpoint_corrupt@save=2,producer_hang@batch=40,sigterm@step=120

Each entry is ``site[@counter=N[:every=M]]``: the fault fires the first
time the site calls ``maybe_fail(site, counter=value)`` with ``value >= N``
(counters are site-defined ordinals — the step number, the Nth save, the
Nth emit; see ``SITES`` — and may stride past N: fused dispatch advances
the step by k, worker w's tickets go w, w+W, …). A bare ``site`` fires on
the site's first check. Without ``:every=``, every fault
fires **once**: in-memory for the process, and — when ``install`` is given
a ``state_dir`` — once per *run*, via a ``fault_<site>.fired`` marker file
that respawned children (supervisor restarts re-exec the same argv, so the
same spec) see and skip. That one-shot-per-run contract is what lets a
supervised e2e inject a crash and still assert the run completes: attempt
1 dies, attempt 2 finds the marker and runs clean.

``:every=M`` makes the trigger *repeatable* (soak testing: a run that
must survive a fault every N steps, not just one): thresholds form the
arithmetic ladder N, N+M, N+2M, … and the site fires once per rung, at
the first check whose counter reaches it (several rungs crossed in one
stride — a fused dispatch jumping k steps — collapse into ONE firing at
the highest rung crossed, so injection rate never exceeds the check
rate). The one-shot marker becomes per-firing: ``fault_<site>.fired.<T>``
records rung ``T``, so a respawned child skips the rungs already fired
this run but still fires the later ones as its counters reach them.

Zero overhead when off: ``maybe_fail`` with no plan installed is one module
attribute load and a ``None`` check — no counters, no dict lookups, nothing
in the step loop. The module imports only the stdlib so every layer
(including ``obs.events``, which must stay backend-free) can use it.

What firing *means* is owned by each injection site — this registry only
answers "should site X fail now?". The sites and their recovery matrix are
documented in README "Fault tolerance"; ``InjectedFault`` is the exception
sites raise when the fault is an error (vs. a behavior like hanging or
sending SIGTERM).
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Optional


class InjectedFault(RuntimeError):
    """Stand-in for a real failure, raised by an injection site."""


# Every site wired through the stack, with the counter its caller passes.
# A spec naming an unknown site is a hard error at parse time: a typo'd
# site would otherwise silently never fire and the chaos test would pass
# by testing nothing.
SITES = {
    "checkpoint_corrupt": "save",        # Nth CheckpointManager.save
    "checkpoint_restore_error": "restore",  # Nth restore attempt
    "sigterm": "step",                   # exact train-loop step number
    "producer_crash": "batch",           # prefetch ticket ordinal
    "producer_hang": "batch",            # prefetch ticket ordinal
    "producer_slow": "batch",            # prefetch ticket ordinal (latency)
    "cache_read_error": "read",          # Nth cache _gather call
    "sink_enospc": "emit",               # Nth EventSink.emit
    "spawn_fail": "spawn",               # Nth supervisor child spawn
    # Nth CheckpointManager.save (latency; sleeps inside the background
    # writer's checkpoint_write span — the double-buffered save keeps
    # the host-blocking enqueue bounded while this write drags).
    "save_slow": "save",
    # A host vanishing mid-mesh (preempted VM, kernel panic, yanked node):
    # the LAST host of the process group SIGKILLs itself at the first step
    # boundary >= N — no drain, no exit protocol, exactly the shape the
    # elastic coordinator must detect and shrink around. Last host (not
    # first) so host 0's event stream and run.json survive the loss.
    "host_loss": "step",                 # exact train-loop step number
    # Serving-fleet sites (featurenet_tpu.fleet). replica_loss fires in
    # the ROUTER process at the Nth routed request and SIGKILLs a live
    # replica mid-stream — no drain, in-flight requests die with it;
    # exactly what the router's re-submit-once path must absorb with
    # zero admitted-request drops. replica_slow fires in a REPLICA
    # (InferenceService._forward) at its Nth dispatched batch and drags
    # the forward by SLOW_SLEEP_S — latency, not death: the shape the
    # least-queue-depth routing and the p99 gate must ride out.
    "replica_loss": "request",           # Nth routed fleet request
    "replica_slow": "request",           # Nth replica forward dispatch
    # Rollout sites (zero-downtime weight hot-swap). Both count the
    # replica's Nth /admin/reload attempt. swap_corrupt hands the swap a
    # checksum-mismatched checkpoint: the replica must refuse with a
    # structured error BEFORE any reference flips (never half-swapped)
    # and the rollout orchestrator must roll already-swapped peers back.
    # replica_loss_rollout SIGKILLs the replica mid-reload — death at
    # the worst moment, which the orchestrator must detect and answer
    # with the same rollback + re-convergence to one version.
    "swap_corrupt": "swap",              # Nth replica reload attempt
    "replica_loss_rollout": "swap",      # Nth replica reload attempt
}

# How long the latency-injection sites (producer_slow, save_slow) sleep
# per firing. Latency, not death: slow is the failure mode the SLO alert
# layer exists for — a producer that merely drags starves the device
# without ever tripping a crash/stall recovery path. Long enough to
# dominate a smoke-sized step so the data-wait alert provably fires;
# short enough that a :every= soak stays cheap.
SLOW_SLEEP_S = 0.25


def parse_spec(spec: str) -> dict[str, Optional[tuple]]:
    """``"a@k=1,b,c@k=5:every=2"`` →
    ``{"a": ("k", 1), "b": None, "c": ("k", 5, 2)}`` — a 2-tuple is a
    one-shot threshold, a 3-tuple adds the re-fire stride. Validates
    sites, counter names, and stride syntax so a typo fails the run at
    config time, not silently."""
    out: dict[str, Optional[tuple]] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        site, sep, trigger = entry.partition("@")
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} in inject spec {spec!r}; "
                f"known sites: {', '.join(sorted(SITES))}"
            )
        if site in out:
            raise ValueError(f"duplicate fault site {site!r} in {spec!r}")
        if not sep:
            out[site] = None
            continue
        trigger, colon, stride = trigger.partition(":")
        name, eq, value = trigger.partition("=")
        if not eq or not name:
            raise ValueError(
                f"malformed trigger {entry!r}: expected "
                "site@counter=N[:every=M]"
            )
        if name != SITES[site]:
            raise ValueError(
                f"site {site!r} counts {SITES[site]!r}, not {name!r} "
                f"(in {entry!r})"
            )
        try:
            n = int(value)
        except ValueError:
            raise ValueError(
                f"trigger value in {entry!r} must be an integer"
            ) from None
        if not colon:
            out[site] = (name, n)
            continue
        skey, seq, svalue = stride.partition("=")
        if skey != "every" or not seq:
            raise ValueError(
                f"malformed stride {entry!r}: expected "
                "site@counter=N:every=M"
            )
        try:
            every = int(svalue)
        except ValueError:
            raise ValueError(
                f"every value in {entry!r} must be an integer"
            ) from None
        if every <= 0:
            raise ValueError(
                f"every in {entry!r} must be positive (a zero/negative "
                "stride would re-fire on every check)"
            )
        out[site] = (name, n, every)
    if not out:
        raise ValueError(f"empty fault spec {spec!r}")
    return out


class FaultPlan:
    """One parsed spec + its fired-state (in-memory and on-disk markers).

    ``only``: restrict the plan to these sites (the supervisor installs
    the shared spec with ``only={"spawn_fail"}`` — the child-side sites
    must fire in the *training* process, not in the supervisor whose
    EventSink also counts emits)."""

    def __init__(self, spec: str, state_dir: Optional[str] = None,
                 only: Optional[set] = None):
        self.spec = spec
        self.sites = parse_spec(spec)
        if only is not None:
            self.sites = {k: v for k, v in self.sites.items() if k in only}
        self.state_dir = os.path.abspath(state_dir) if state_dir else None
        self._fired: set[str] = set()
        # Repeatable sites: highest rung fired so far (per site), so a
        # counter that runs backwards (a restarted worker's tickets) can
        # never re-fire a rung below one already taken.
        self._floor: dict[str, int] = {}
        self._lock = threading.Lock()

    def _marker(self, site: str, rung: Optional[int] = None) -> Optional[str]:
        """One-shot sites keep the legacy ``fault_<site>.fired`` name (old
        run dirs and tests stay valid); repeatable sites get one marker per
        rung — ``fault_<site>.fired.<rung>`` — so a respawned child skips
        exactly the firings this run already took, not the whole ladder."""
        if self.state_dir is None:
            return None
        name = (f"fault_{site}.fired" if rung is None
                else f"fault_{site}.fired.{rung}")
        return os.path.join(self.state_dir, name)

    def check(self, site: str, counter: dict) -> bool:
        entry = self.sites.get(site, False)
        if entry is False:
            return False
        rung: Optional[int] = None  # None = one-shot (bare or N-threshold)
        if entry is not None:
            name, value = entry[0], entry[1]
            every = entry[2] if len(entry) > 2 else None
            got = counter.get(name)
            # Threshold crossing, not equality: counters may stride past N
            # (a fused-dispatch loop advances step by k; worker w's prefetch
            # tickets are w, w+W, …) and a trigger that can silently never
            # fire makes a chaos test pass by testing nothing. One-shot
            # state (in-memory + run-dir marker) bounds this to a single
            # firing — a resumed run whose counter restarts past N relies
            # on the marker, which is why Trainer anchors state_dir in
            # run_dir/checkpoint_dir.
            if got is None or got < value:
                return False
            if every is not None:
                # Repeatable ladder N, N+M, …: fire at the highest rung
                # this counter has crossed — several rungs crossed in one
                # stride collapse into one firing.
                rung = value + ((got - value) // every) * every
        key = site if rung is None else f"{site}@{rung}"
        if key in self._fired:
            return False
        with self._lock:
            if key in self._fired:
                return False
            if rung is not None and self._floor.get(site, rung - 1) >= rung:
                return False
            marker = self._marker(site, rung)
            if marker is not None and os.path.exists(marker):
                # Fired by an earlier process of this run (a respawned
                # child re-executes the same argv/spec) — the one-shot /
                # per-rung contract holds across restarts.
                self._fired.add(key)
                if rung is not None:
                    self._floor[site] = rung
                return False
            self._fired.add(key)
            if rung is not None:
                self._floor[site] = rung
            if marker is not None:
                os.makedirs(self.state_dir, exist_ok=True)
                with open(marker, "w") as fh:
                    fh.write(json.dumps({"site": site, "pid": os.getpid(),
                                         "rung": rung, "counter": counter}))
        # stderr, never obs.warn: sink_enospc fires *inside* EventSink.emit
        # and an obs re-entry would recurse.
        record = {"fault_injected": site, "pid": os.getpid(), **counter}
        if rung is not None:
            record["rung"] = rung
        print(json.dumps(record), file=sys.stderr)
        return True


_plan: Optional[FaultPlan] = None


def install(spec: Optional[str], state_dir: Optional[str] = None,
            only: Optional[set] = None) -> None:
    """Install the process-wide fault plan (replacing any previous one).
    ``state_dir``: directory for cross-process one-shot markers — pass the
    run_dir so a supervised run's respawned children don't re-fire.
    ``only``: keep just these sites of the spec (see ``FaultPlan``). A
    falsy ``spec`` uninstalls."""
    global _plan
    _plan = FaultPlan(spec, state_dir, only=only) if spec else None


def uninstall() -> None:
    global _plan
    _plan = None


def active() -> bool:
    return _plan is not None


def maybe_fail(site: str, **counter) -> bool:
    """True when the installed plan says this site should fail now.

    The off path — no plan installed — is a single attribute check, so
    injection sites can live inside the train step loop and the event
    sink's emit without measurable overhead.
    """
    plan = _plan
    if plan is None:
        return False
    return plan.check(site, counter)
