"""FeatureNet-TPU: a TPU-native machining-feature-recognition framework.

A ground-up JAX/Flax/XLA re-design of the capabilities of the FeatureNet
reference (yqtianust/FeatureNet — 3D-CNN recognition of 24 machining feature
classes over voxelized CAD parts; see SURVEY.md). Nothing here is a port: the
compute path is Flax modules lowered to XLA (MXU-friendly NDHWC, bf16 compute /
fp32 state), the distributed path is `jax.sharding.Mesh` + `jit`/`shard_map`
with XLA collectives over ICI (not NCCL), and the data path is a first-party
STL→voxel pipeline with a native C++ rasterizer option.

Subpackages
-----------
- ``featurenet_tpu.data``     — STL parsing, voxelization, synthetic dataset
- ``featurenet_tpu.models``   — Flax model families (classifier, segmentation)
- ``featurenet_tpu.ops``      — custom ops / Pallas TPU kernels
- ``featurenet_tpu.parallel`` — mesh, sharding, collectives, spatial partitioning
- ``featurenet_tpu.train``    — configs, train state, steps, loop, checkpointing
- ``featurenet_tpu.utils``    — metrics, logging, misc
"""

__version__ = "0.1.0"
