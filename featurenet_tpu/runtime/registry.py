"""The compiled-program runtime registry: every XLA program, one front door.

``train/loop.py``, ``infer.py``, and ``benchmark.py`` (the core under
``ops/bench_arch.py`` and root ``bench.py``) used to hand-build their own
jitted callables — their own sharding/donation decisions, their own compile
timing, no way to enumerate what a config compiles or to rebuild it
elsewhere. This module is the refactor unlock (ROADMAP item 5): a
``Program`` is a named record — pure function, abstract input
shapes/dtypes, shardings, donation, precision — and the ``Runtime`` builds
it ``build → lower → compile`` with:

- **Enumeration**: ``Runtime.programs()`` / ``list_programs(cfg)`` say
  exactly which programs a config runs (``cli programs`` renders it), and
  ``Runtime.warmup()`` compiles them ahead of traffic — serving cold
  starts pay compilation before the first request, not during it.
- **A persistent AOT executable cache** (``runtime.cache``): with
  ``Config.exec_cache_dir`` set, compiled executables are serialized to
  disk keyed by a full fingerprint (jax/jaxlib, backend, program, arch
  hash, shapes/dtypes, precision) and respawns/resumes/cold starts
  deserialize instead of recompiling. Loads are guarded (see the cache
  module's hazard note): any failure degrades to a fresh compile with a
  ``cache_reject`` event — never a crash.
- **Observability**: ``program_compile`` / ``cache_hit`` / ``cache_miss``
  / ``cache_reject`` events make time-to-first-step attributable from the
  run log alone (bench pins cold vs warm TTFS in its gate summary), and
  every build emits a ``program_cost`` event (``obs.perf``) carrying the
  executable's XLA cost/memory counters — the report's per-program
  flops/peak-HBM/roofline table and the rolling MFU metrics read from it.
- **An int8 serving path** (``runtime.quantize``): ``serve_int8`` /
  ``serve_packed_int8`` run the same forward over per-channel-quantized
  int8 weights, dequantized on device — the serving throughput rung of
  ROADMAP item 2, accuracy-gated in tests against the paper's 96.7%
  target.

Program catalog (availability depends on the config):

==================  =========================================================
``init``            sharded state init (params/opt-state materialized
                    directly on their devices)
``train_step``      one fused fwd+bwd+optimizer+BN step (donated state);
                    precision-variant per ``Config.train_precision``
                    (fp32 | bf16_master | fp16_scaled — the policy is in
                    the cache fingerprint, so a cross-precision hit is
                    impossible; fp16_scaled adds dynamic loss scaling)
``multi_train_step``  ``k`` steps fused into one executable
                    (``steps_per_dispatch > 1``); precision-variant
``hbm_train_step``  steps that sample batches from the HBM-resident split
                    (``hbm_cache``; needs the resident arrays' shapes)
``eval_step``       exact-sum eval forward; serve-precision-variant per
                    ``Config.serve_precision`` (the cast is baked into
                    the traced step and fingerprinted like the train
                    policies)
``serve``           the Predictor forward: fp32 weights → probs (classify)
                    or int8 per-voxel labels (segment); single-device
``serve_bf16``      same forward with the bf16 working-copy cast compiled
                    inside (masters stay fp32; 2-byte weight reads)
``serve_int8``      same forward over int8-quantized weights
``serve_packed``    the bench serving program: packed voxels → labels,
                    sharded over the mesh (classify only)
``serve_packed_bf16``  its bf16 working-copy variant
``serve_packed_int8``  its int8-weight variant
==================  =========================================================
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from featurenet_tpu import obs
from featurenet_tpu.config import IDENTITY_FIELDS, Config, config_to_dict
from featurenet_tpu.runtime.cache import (
    ExecutableCache,
    cache_from_config,
    meta_digest,
    program_fingerprint,
)

# Serving weight precisions (Config.serve_precision / Predictor
# precision). Mirrors train.precision.SERVE_PRECISIONS — importing it at
# module scope would cycle through train/__init__ → train.loop → this
# module, so the literal is duplicated here and pinned equal by
# tests/test_runtime.py.
PRECISIONS = ("fp32", "bf16", "int8")

_FROM_CONFIG = object()  # sentinel: derive the cache from cfg.exec_cache_dir


def build_model(cfg: Config):
    """The module tree a config trains/serves (single source of truth —
    the Trainer, Predictor, and every registry program build through
    here)."""
    from featurenet_tpu.models.featurenet import FeatureNet
    from featurenet_tpu.models.segmenter import FeatureNetSegmenter

    if cfg.task == "segment":
        return FeatureNetSegmenter(
            features=tuple(cfg.seg_features),
            input_context=cfg.seg_input_context,
            decoder_blocks=cfg.seg_decoder_blocks,
            bottleneck_blocks=cfg.seg_bottleneck_blocks,
        )
    return FeatureNet(arch=cfg.arch)


def hbm_rows_estimate(cfg: Config) -> int:
    """Train-split row count ``hbm_cache`` mode will hold resident — read
    from the cache's index metadata (cheap; needed before the dataset is
    built, e.g. for the dispatch-k clamp)."""
    if not (cfg.hbm_cache and cfg.data_cache):
        return 0
    import json
    import os

    try:
        with open(os.path.join(cfg.data_cache, "index.json")) as fh:
            index = json.load(fh)
        if index.get("kind") == "segment":
            total = sum(s["count"] for s in index["shards"])
        else:
            total = sum(index["counts"].values())
        return int(total * (1.0 - cfg.test_fraction))
    except (OSError, KeyError, ValueError):
        return 0  # the Trainer's own cache open will raise the real error


def _key_aval():
    return jax.eval_shape(lambda: jax.random.key(0))


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _aval_of(x):
    """ShapeDtypeStruct view of an array or an existing aval."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def _meta_avals(tree) -> Any:
    """JSON-able shapes/dtypes summary of an abstract-args pytree — the
    shape signature half of the cache fingerprint."""
    leaves = jax.tree_util.tree_leaves(tree)
    return [[list(map(int, l.shape)), str(l.dtype)] for l in leaves]


@dataclasses.dataclass
class ProgramSpec:
    """One compiled program, described before compilation: the pure
    function, its abstract inputs, its sharding/donation decisions, and
    the precision of the weights it runs."""

    name: str
    fn: Callable
    abstract_args: tuple
    precision: str = "fp32"
    in_shardings: Any = None
    out_shardings: Any = None
    donate_argnums: tuple = ()
    meta: dict = dataclasses.field(default_factory=dict)

    def jit_kwargs(self) -> dict:
        kw: dict = {}
        if self.in_shardings is not None:
            kw["in_shardings"] = self.in_shardings
        if self.out_shardings is not None:
            kw["out_shardings"] = self.out_shardings
        if self.donate_argnums:
            kw["donate_argnums"] = self.donate_argnums
        return kw


@dataclasses.dataclass
class CompiledProgram:
    """A built program: call it like the function it wraps."""

    spec: ProgramSpec
    compiled: Any  # jax.stages.Compiled
    source: str    # "fresh" (XLA compiled it now) or "cache" (deserialized)
    build_s: float
    # Compiled cost/memory counters (obs.perf.program_cost): flops, bytes
    # accessed, peak_bytes, … — whatever the backend could say, possibly
    # empty. The train loop and the serving layer fold measured wall
    # times against these into the rolling MFU/bandwidth metrics.
    cost: dict = dataclasses.field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.spec.name

    @property
    def precision(self) -> str:
        return self.spec.precision

    def __call__(self, *args):
        return self.compiled(*args)


# --- program builders --------------------------------------------------------
# name -> (builder(rt, **kw) -> ProgramSpec, one-line doc,
#          applicable(cfg) -> bool)

def _always(cfg: Config) -> bool:
    return True


def _spec_init(rt: "Runtime") -> ProgramSpec:
    # Precision-variant like the train steps: the initialized TrainState
    # CARRIES the policy as static pytree metadata, and an AOT-cached
    # executable's output treedef is baked at lowering — a cached fp32
    # init served to a bf16_master run would silently hand back a state
    # whose every later step trains at the wrong precision.
    prec = rt.cfg.train_precision
    return ProgramSpec(
        name="init",
        fn=rt._init_fn,
        abstract_args=(_key_aval(),),
        precision=prec,
        out_shardings=rt.state_sh,
        meta={"kind": "init", "precision": prec,
              "avals": _meta_avals(rt.abstract_state)},
    )


def _spec_train_step(rt: "Runtime") -> ProgramSpec:
    from featurenet_tpu.train.steps import make_train_step

    args = (rt.abstract_state, rt.batch_avals(), _key_aval())
    # The precision policy lands in the meta (and so in the cache
    # fingerprint AND the entry filename digest): the fp32 and
    # bf16_master executables have IDENTICAL avals — fp32 masters in,
    # fp32 masters out — and only the policy baked into the traced step
    # distinguishes them. A bf16-master world must never load an fp32
    # program (or vice versa), so a cross-precision cache hit must be
    # impossible by construction.
    prec = rt.cfg.train_precision
    return ProgramSpec(
        name="train_step",
        fn=make_train_step(rt.model, rt.cfg.task, **rt.step_kwargs()),
        abstract_args=args,
        precision=prec,
        in_shardings=(rt.state_sh, rt.batch_sh, rt.rep),
        out_shardings=(rt.state_sh, rt.rep),
        donate_argnums=(0,),
        meta={"kind": "train_step", "precision": prec,
              "avals": _meta_avals(args)},
    )


def _spec_multi_train_step(rt: "Runtime",
                           num_steps: Optional[int] = None) -> ProgramSpec:
    from featurenet_tpu.train.steps import make_multi_train_step

    if num_steps is None:
        # Default (warmup path) to the k the Trainer actually dispatches:
        # the requested steps_per_dispatch clamped against the analytic
        # HBM byte model. An unclamped default would risk the compile-time
        # OOM the clamp exists to prevent AND warm a cache entry whose
        # digest (meta num_steps) no real run ever looks up.
        from featurenet_tpu.train.state import param_count

        num_steps = rt.dispatch_k(param_count(rt.abstract_state.params))
    k = max(2, num_steps)
    args = (rt.abstract_state, (rt.batch_avals(),) * k, _key_aval())
    prec = rt.cfg.train_precision
    return ProgramSpec(
        name="multi_train_step",
        fn=make_multi_train_step(
            rt.model, rt.cfg.task, num_steps=k, **rt.step_kwargs()
        ),
        abstract_args=args,
        precision=prec,
        in_shardings=(rt.state_sh, (rt.batch_sh,) * k, rt.rep),
        out_shardings=(rt.state_sh, rt.rep),
        donate_argnums=(0,),
        meta={"kind": "multi_train_step", "num_steps": k,
              "precision": prec, "avals": _meta_avals(args)},
    )


def _spec_hbm_train_step(rt: "Runtime", num_steps: int = 1,
                         data=None, targets=None) -> ProgramSpec:
    """Needs the RESIDENT arrays (or their avals): the executable bakes the
    uploaded split's row count into its sampling, so the shapes must be
    the materialized ones, not an index estimate."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from featurenet_tpu.train.steps import make_hbm_multi_train_step

    if data is None or targets is None:
        raise ValueError(
            "hbm_train_step needs the resident arrays (data=, targets=) — "
            "their shapes come from materialize_split, not the cache index"
        )
    cfg = rt.cfg
    d_sh = NamedSharding(rt.mesh, P("data"))
    args = (rt.abstract_state, _aval_of(data), _aval_of(targets), _key_aval())
    prec = cfg.train_precision
    return ProgramSpec(
        name="hbm_train_step",
        fn=make_hbm_multi_train_step(
            rt.model, rt.mesh, cfg.global_batch, cfg.task,
            cfg.label_smoothing,
            augment_groups=(
                cfg.augment_groups if cfg.device_augment else 0
            ),
            num_steps=num_steps,
            seg_loss=cfg.seg_loss,
            augment_noise=cfg.augment_noise,
            augment_affine=cfg.augment_affine,
            affine_opts=rt.step_kwargs()["affine_opts"],
        ),
        abstract_args=args,
        precision=prec,
        in_shardings=(rt.state_sh, d_sh, d_sh, rt.rep),
        out_shardings=(rt.state_sh, rt.rep),
        donate_argnums=(0,),
        meta={"kind": "hbm_train_step", "num_steps": num_steps,
              "precision": prec, "avals": _meta_avals(args)},
    )


def _spec_eval_step(rt: "Runtime") -> ProgramSpec:
    from featurenet_tpu.train.steps import make_eval_step

    # Precision-variant per Config.serve_precision, exactly as the train
    # steps are per train_precision: the avals are identical across
    # variants (fp32 masters in either way) and only the cast baked into
    # the traced step distinguishes them — the policy lands in the spec
    # precision AND the meta, so the exec-cache fingerprint (and entry
    # filename) separate them and a cross-precision cache hit is
    # impossible by construction.
    prec = rt.cfg.serve_precision
    args = (rt.abstract_state.params, rt.abstract_state.batch_stats,
            rt.batch_avals())
    return ProgramSpec(
        name="eval_step",
        fn=make_eval_step(rt.model, rt.cfg.task, packed=True,
                          serve_precision=prec),
        abstract_args=args,
        precision=prec,
        in_shardings=(rt.state_sh.params, rt.state_sh.batch_stats,
                      rt.batch_sh),
        out_shardings=rt.rep,
        meta={"kind": "eval_step", "precision": prec,
              "avals": _meta_avals(args)},
    )


def serve_program_name(precision: str, packed: bool = False) -> str:
    """The catalog name of the serving program at one precision — THE
    mapping (Predictor.program_for, measure_inference, measure_ttfs, and
    the spec builders all resolve through here, so a new rung lands in
    one place)."""
    if precision not in PRECISIONS:
        raise ValueError(
            f"unknown serving precision {precision!r}; one of "
            f"{', '.join(PRECISIONS)}"
        )
    base = "serve_packed" if packed else "serve"
    return base if precision == "fp32" else f"{base}_{precision}"


def _bf16_params_aval(params_aval):
    """Abstract bf16 working copy of a params tree — the serving
    programs' param avals under the bf16 rung: the 2-byte tree is a
    program ARGUMENT (cast once at Predictor construction, resident in
    serving HBM), not an in-program cast of the fp32 masters, so every
    dispatch reads half the weight bytes — the int8 path's
    quantize-at-construction pattern applied to bf16. (eval_step is the
    deliberate exception: it compiles the cast inside, because its job
    is accuracy-faithful eval of the rung, not serving bandwidth.)"""
    from featurenet_tpu.train.precision import serve_params_cast

    return jax.eval_shape(lambda p: serve_params_cast(p, "bf16"),
                          params_aval)


def _serve_fn(rt: "Runtime"):
    """The Predictor forward: probs for classify, on-device argmax to int8
    labels for segment (so labels, not a 25-channel fp32 volume, cross
    back to the host). Params arrive at the program's own precision (the
    fp32 masters, or the pre-cast bf16 working copy)."""
    import jax.numpy as jnp

    model, task = rt.model, rt.cfg.task

    def forward(params, batch_stats, voxels):
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, voxels,
            train=False,
        )
        if task == "segment":
            return jnp.argmax(logits, axis=-1).astype(jnp.int8)
        return jax.nn.softmax(logits, axis=-1)

    return forward


def _spec_serve(rt: "Runtime", batch: int = 32,
                precision: str = "fp32") -> ProgramSpec:
    """The batch-shaped Predictor forward; ``precision="bf16"`` is the
    same spec over bf16 param avals (the catalog's ``serve_bf16`` — one
    builder, two entries; int8 stays its own spec because its
    quantized-argument signature differs structurally)."""
    R = rt.cfg.resolution
    name = serve_program_name(precision)
    params_aval = rt.abstract_state.params
    if precision == "bf16":
        params_aval = _bf16_params_aval(params_aval)
    args = (params_aval, rt.abstract_state.batch_stats,
            _sds((batch, R, R, R, 1), np.float32))
    return ProgramSpec(
        name=name,
        fn=_serve_fn(rt),
        abstract_args=args,
        precision=precision,
        meta={"kind": name, "batch": batch, "avals": _meta_avals(args)},
    )


def _spec_serve_bf16(rt: "Runtime", batch: int = 32) -> ProgramSpec:
    return _spec_serve(rt, batch=batch, precision="bf16")


def _spec_serve_int8(rt: "Runtime", batch: int = 32) -> ProgramSpec:
    from featurenet_tpu.runtime.quantize import dequantize_tree, quantize_tree

    R = rt.cfg.resolution
    fwd = _serve_fn(rt)

    def forward(q_params, scales, batch_stats, voxels):
        return fwd(dequantize_tree(q_params, scales), batch_stats, voxels)

    q_aval, s_aval = jax.eval_shape(quantize_tree, rt.abstract_state.params)
    args = (q_aval, s_aval, rt.abstract_state.batch_stats,
            _sds((batch, R, R, R, 1), np.float32))
    return ProgramSpec(
        name="serve_int8",
        fn=forward,
        abstract_args=args,
        precision="int8",
        meta={"kind": "serve_int8", "batch": batch,
              "avals": _meta_avals(args)},
    )


def _packed_sharding(rt: "Runtime"):
    from featurenet_tpu.parallel.mesh import batch_shardings

    return batch_shardings(rt.mesh, keys=("voxels",))["voxels"]


def _spec_serve_packed(rt: "Runtime", global_batch: Optional[int] = None,
                       precision: str = "fp32") -> ProgramSpec:
    """The packed-wire serving forward; ``precision="bf16"`` is the same
    spec over bf16 param avals (catalog ``serve_packed_bf16``) — the
    caller feeds the pre-cast working copy (see ``_bf16_params_aval``)."""
    import jax.numpy as jnp

    from featurenet_tpu.train.steps import unpack_voxels

    model = rt.model
    B = global_batch or rt.cfg.global_batch
    R = rt.cfg.resolution
    name = serve_program_name(precision, packed=True)

    def serve(variables, packed):
        x = unpack_voxels(packed)  # [B,R,R,R,1] f32; model casts onward
        logits = model.apply(variables, x, train=False)
        return jnp.argmax(logits, axis=-1)

    var_aval = dict(rt.abstract_variables())
    if precision == "bf16":
        var_aval["params"] = _bf16_params_aval(var_aval["params"])
    args = (var_aval, _sds((B, R, R, R // 8), np.uint8))
    return ProgramSpec(
        name=name,
        fn=serve,
        abstract_args=args,
        precision=precision,
        in_shardings=(rt.rep, _packed_sharding(rt)),
        meta={"kind": name, "avals": _meta_avals(args)},
    )


def _spec_serve_packed_bf16(rt: "Runtime",
                            global_batch: Optional[int] = None
                            ) -> ProgramSpec:
    return _spec_serve_packed(rt, global_batch=global_batch,
                              precision="bf16")


def _spec_serve_packed_int8(rt: "Runtime",
                            global_batch: Optional[int] = None
                            ) -> ProgramSpec:
    import jax.numpy as jnp

    from featurenet_tpu.runtime.quantize import dequantize_tree, quantize_tree
    from featurenet_tpu.train.steps import unpack_voxels

    model = rt.model
    B = global_batch or rt.cfg.global_batch
    R = rt.cfg.resolution
    var_aval = rt.abstract_variables()
    q_aval, s_aval = jax.eval_shape(quantize_tree, var_aval["params"])

    def serve(q_params, scales, batch_stats, packed):
        x = unpack_voxels(packed)
        logits = model.apply(
            {"params": dequantize_tree(q_params, scales),
             "batch_stats": batch_stats},
            x, train=False,
        )
        return jnp.argmax(logits, axis=-1)

    args = (q_aval, s_aval, var_aval["batch_stats"],
            _sds((B, R, R, R // 8), np.uint8))
    return ProgramSpec(
        name="serve_packed_int8",
        fn=serve,
        abstract_args=args,
        precision="int8",
        in_shardings=(rt.rep, rt.rep, rt.rep, _packed_sharding(rt)),
        meta={"kind": "serve_packed_int8", "avals": _meta_avals(args)},
    )


PROGRAMS: dict[str, tuple[Callable, str, Callable[[Config], bool]]] = {
    "init": (_spec_init, "sharded state init", _always),
    "train_step": (_spec_train_step,
                   "one fused fwd+bwd+optimizer+BN step", _always),
    "multi_train_step": (
        _spec_multi_train_step, "k train steps fused into one executable",
        lambda cfg: cfg.steps_per_dispatch > 1),
    "hbm_train_step": (
        _spec_hbm_train_step,
        "train steps sampling batches from the HBM-resident split",
        lambda cfg: cfg.hbm_cache),
    "eval_step": (
        _spec_eval_step,
        "exact-sum eval forward; serve-precision-variant", _always),
    "serve": (_spec_serve, "serving forward, fp32 weights", _always),
    "serve_bf16": (
        _spec_serve_bf16,
        "serving forward, bf16 working-copy weights", _always),
    "serve_int8": (_spec_serve_int8,
                   "serving forward, int8 per-channel weights", _always),
    "serve_packed": (
        _spec_serve_packed, "packed-wire serving forward (bench/mesh)",
        lambda cfg: cfg.task == "classify"),
    "serve_packed_bf16": (
        _spec_serve_packed_bf16,
        "packed-wire serving forward, bf16 working-copy weights",
        lambda cfg: cfg.task == "classify"),
    "serve_packed_int8": (
        _spec_serve_packed_int8,
        "packed-wire serving forward, int8 weights",
        lambda cfg: cfg.task == "classify"),
}

# Programs warmup() skips without extra arguments: the resident-split
# shapes only exist once the dataset is materialized.
_NEEDS_RUNTIME_ARGS = frozenset({"hbm_train_step"})


# Programs whose compiled executable embeds the TRAINING precision
# policy (Config.train_precision): the train steps cast/apply under it,
# and init bakes it into the returned state's static metadata. The
# serving catalog is precision-variant by NAME (serve / serve_bf16 /
# serve_int8 and their packed forms), while eval_step embeds the
# SERVING precision policy (Config.serve_precision) the same way the
# train steps embed theirs.
TRAIN_PRECISION_PROGRAMS = frozenset(
    {"init", "train_step", "multi_train_step", "hbm_train_step"}
)

SERVE_PRECISION_PROGRAMS = frozenset({"eval_step"})


def program_precision(cfg: Config, name: str) -> str:
    """The weight-precision label of one catalog program under ``cfg`` —
    the ``cli programs`` column and the listing half of the precision
    variants (the build half lives in each spec's meta/fingerprint)."""
    if name.endswith("int8"):
        return "int8"
    if name.endswith("bf16"):
        return "bf16"
    if name in TRAIN_PRECISION_PROGRAMS:
        return cfg.train_precision
    if name in SERVE_PRECISION_PROGRAMS:
        return cfg.serve_precision
    return "fp32"


def list_programs(cfg: Config) -> list[dict]:
    """Enumerate the catalog for ``cfg`` WITHOUT building anything — the
    ``cli programs`` listing (name, doc, precision, applicability)."""
    rows = []
    for name, (_, doc, applicable) in PROGRAMS.items():
        rows.append({
            "program": name,
            "doc": doc,
            "precision": program_precision(cfg, name),
            "applicable": bool(applicable(cfg)),
        })
    return rows


class Runtime:
    """Per-config runtime context: model, mesh, shardings, and the
    compiled-program front door (``build`` / ``warmup`` / ``programs``).

    The Trainer, the Predictor, and the benchmark all construct one of
    these; what each of them compiles is by construction the same program
    the others would."""

    def __init__(self, cfg: Config, mesh=None, spatial: Optional[bool] = None,
                 cache=_FROM_CONFIG):
        import jax.numpy as jnp

        from featurenet_tpu.data.synthetic import WIRE_KEYS
        from featurenet_tpu.parallel.mesh import (
            batch_shardings,
            clamp_model_axis,
            make_mesh,
            replicated,
            state_shardings,
        )
        from featurenet_tpu.train.state import create_state
        from featurenet_tpu.train.steps import make_optimizer

        self.cfg = cfg.validate()
        self.spatial = cfg.spatial if spatial is None else spatial
        if mesh is not None:
            self.mesh = mesh
        else:
            model_axis = clamp_model_axis(cfg.mesh_model, len(jax.devices()))
            if model_axis != cfg.mesh_model:
                # Presets carry pod-scale mesh shapes; on smaller hardware
                # degrade to the widest feasible model axis instead of
                # refusing to start.
                obs.warn(
                    "mesh_warning",
                    f"mesh_model={cfg.mesh_model} does not divide the "
                    f"{len(jax.devices())} available device(s); running "
                    f"with mesh_model={model_axis}",
                )
            self.mesh = make_mesh(cfg.mesh_data, model_axis)
        self.model = build_model(cfg)
        self.tx = make_optimizer(cfg)
        R = cfg.resolution
        sample_shape = (cfg.global_batch, R, R, R, 1)

        def init_fn(rng):
            sample = jnp.zeros(sample_shape, jnp.float32)
            return create_state(self.model, self.tx, sample, rng,
                                precision=cfg.train_precision)

        self._init_fn = init_fn
        self.abstract_state = jax.eval_shape(init_fn, _key_aval())
        self.state_sh = state_shardings(self.abstract_state, self.mesh)
        self.batch_sh = batch_shardings(
            self.mesh, spatial=self.spatial, keys=WIRE_KEYS[cfg.task]
        )
        self.rep = replicated(self.mesh)
        self.cache: Optional[ExecutableCache] = (
            cache_from_config(cfg) if cache is _FROM_CONFIG else cache
        )
        self._abstract_variables = None
        # Fingerprint identity: the full config identity fields (arch
        # INCLUDING conv_backend — a different lowering is a different
        # executable) plus the mesh/layout decisions baked into
        # shardings. mesh_summary, not mesh.shape: the same axis sizes
        # laid over a different process count compile different
        # cross-host collectives, and an elastic re-form at a new world
        # shape must never be served the old world's executable.
        from featurenet_tpu.parallel.mesh import mesh_summary

        ident = config_to_dict(cfg)
        self._identity = {f: ident[f] for f in IDENTITY_FIELDS}
        self._identity["mesh"] = mesh_summary(self.mesh)
        self._identity["spatial"] = bool(self.spatial)

    # -- shared abstract structures ------------------------------------------
    def batch_avals(self) -> dict:
        """Abstract wire batch (``data.synthetic.to_wire`` format) at the
        config's global batch."""
        cfg = self.cfg
        B, R = cfg.global_batch, cfg.resolution
        avals = {
            "voxels": _sds((B, R, R, R // 8), np.uint8),
            "mask": _sds((B,), np.float32),
        }
        if cfg.task == "segment":
            avals["seg"] = _sds((B, R, R, R), np.int8)
        else:
            avals["label"] = _sds((B,), np.int32)
        return avals

    def abstract_variables(self) -> dict:
        """Abstract ``{"params", "batch_stats"}`` of a bare ``model.init``
        (what the packed serving programs take)."""
        if self._abstract_variables is None:
            import jax.numpy as jnp

            R = self.cfg.resolution
            sample = _sds((1, R, R, R, 1), jnp.float32)
            self._abstract_variables = jax.eval_shape(
                lambda rng, x: self.model.init(rng, x, train=False),
                _key_aval(), sample,
            )
        return self._abstract_variables

    def step_kwargs(self) -> dict:
        """The train-step construction knobs shared by every train program
        (single, fused, HBM-resident) — one source so they cannot drift."""
        cfg = self.cfg
        return dict(
            label_smoothing=cfg.label_smoothing,
            augment_groups=(
                cfg.augment_groups if cfg.device_augment else 0
            ),
            packed=True,
            seg_loss=cfg.seg_loss,
            augment_noise=cfg.augment_noise,
            augment_affine=cfg.augment_affine,
            affine_opts=dict(
                prob=cfg.augment_affine_prob,
                ramp_steps=cfg.augment_ramp_steps,
                rotate=cfg.augment_affine_rotate,
                scale_range=cfg.augment_scale_range,
                translate_vox=cfg.augment_translate_vox,
            ),
        )

    def dispatch_k(self, params_n: int) -> int:
        """The fused-dispatch width this config actually runs: the
        requested ``steps_per_dispatch`` clamped against the analytic HBM
        byte model (``ops/membytes``) — degrade with a warning, never
        crash, never silently under-dispatch. An explicit CLI request
        (``clamp_dispatch_k=False``) is honored with the OOM-risk
        warning."""
        cfg = self.cfg
        k = max(1, cfg.steps_per_dispatch)
        if k <= 1:
            return k
        from featurenet_tpu.ops.membytes import max_feasible_k

        k_fit = max_feasible_k(cfg, params_n, n_rows=hbm_rows_estimate(cfg))
        if k_fit < k and cfg.clamp_dispatch_k:
            obs.warn(
                "dispatch_warning",
                f"steps_per_dispatch={cfg.steps_per_dispatch} does not "
                f"fit the analytic HBM byte model for this config; "
                f"clamped to {k_fit} (ops/membytes.max_feasible_k)",
            )
            return k_fit
        if k_fit < k:
            obs.warn(
                "dispatch_warning",
                f"steps_per_dispatch={cfg.steps_per_dispatch} exceeds "
                f"the analytic HBM byte model's k={k_fit} but was "
                "requested explicitly (clamp_dispatch_k=False); "
                "honoring it — the fused executable may OOM",
            )
        return k

    # -- the front door ------------------------------------------------------
    def programs(self) -> list[str]:
        """The program names this config can build, catalog order."""
        return [
            name for name, (_, _, applicable) in PROGRAMS.items()
            if applicable(self.cfg)
        ]

    def spec(self, name: str, **kw) -> ProgramSpec:
        if name not in PROGRAMS:
            raise KeyError(
                f"unknown program {name!r}; have {sorted(PROGRAMS)}"
            )
        builder, _, applicable = PROGRAMS[name]
        if not applicable(self.cfg):
            raise ValueError(
                f"program {name!r} is not applicable to config "
                f"{self.cfg.name!r} (see runtime.registry.PROGRAMS)"
            )
        return builder(self, **kw)

    def build(self, name: str, **kw) -> CompiledProgram:
        """``build → lower → compile`` with the guarded cache in front:
        a verified cache hit skips XLA entirely; a miss compiles and
        stores; any reject compiles fresh and says why."""
        spec = self.spec(name, **kw)
        t0 = time.perf_counter()
        jitted = jax.jit(spec.fn, **spec.jit_kwargs())
        lowered = jitted.lower(*spec.abstract_args)
        compiled = None
        source = "fresh"
        fp = digest = None
        if self.cache is not None:
            fp = program_fingerprint(spec.name, self._identity, spec.meta)
            digest = meta_digest(spec.meta, self._identity)
            compiled, reason = self.cache.load(spec.name, fp, digest, lowered)
            if reason == "hit":
                source = "cache"
                obs.emit("cache_hit", program=spec.name)
            elif reason == "miss":
                obs.emit("cache_miss", program=spec.name)
            else:
                # Stale fingerprint, torn file, failed/refused probe — the
                # fresh compile below is the degradation path; the event
                # is the record that the cache did NOT serve this program.
                obs.emit("cache_reject", program=spec.name, reason=reason)
        if compiled is None:
            t1 = time.perf_counter()
            compiled = self._compile(lowered)
            obs.emit(
                "program_compile", program=spec.name,
                dur_s=round(time.perf_counter() - t1, 3),
                precision=spec.precision,
            )
            if self.cache is not None:
                self.cache.store(spec.name, fp, digest, compiled, spec.meta)
        # Performance attribution (obs.perf): capture the executable's
        # cost/memory analyses and emit the program_cost event — cache
        # hits included (a deserialized executable's counters are the
        # same program's). Guarded capture: a backend that cannot answer
        # yields an honestly partial (possibly empty) cost dict.
        from featurenet_tpu.obs import perf as _perf

        cost = _perf.emit_program_cost(spec.name, compiled,
                                       precision=spec.precision)
        return CompiledProgram(
            spec, compiled, source, round(time.perf_counter() - t0, 3),
            cost,
        )

    def _compile(self, lowered):
        """``lowered.compile()``, with jax's OWN persistent compilation
        cache suspended while the exec cache will store the result: an
        executable jax deserialized from its cache re-serializes into a
        blob whose compiled symbols are missing ("Symbols not found" at
        deserialize — the probe guard rejects every such entry), so a
        stored payload must always come from a real XLA compile. With the
        exec cache configured, it subsumes jax's cache anyway; without
        one, jax's cache behavior is untouched."""
        if self.cache is None:
            return lowered.compile()
        import jax as _jax
        from jax._src import compilation_cache as _cc

        prev = bool(_jax.config.jax_enable_compilation_cache)
        if not prev:
            return lowered.compile()
        # The enable flag is only consulted when the cache object
        # initializes, so each flip must be paired with reset_cache().
        _jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
        try:
            return lowered.compile()
        finally:
            _jax.config.update("jax_enable_compilation_cache", True)
            _cc.reset_cache()

    def warmup(self, names: Optional[list[str]] = None) -> dict[str, dict]:
        """Build every (requested) applicable program — the AOT warmup a
        serving process runs before taking traffic, and the path that
        populates a cold executable cache. Returns per-program build
        records; programs needing runtime-only arguments (the resident
        HBM split) are reported skipped, not errored."""
        out: dict[str, dict] = {}
        for name in (names if names is not None else self.programs()):
            if name in _NEEDS_RUNTIME_ARGS:
                out[name] = {"skipped": "needs resident-split arrays"}
                continue
            prog = self.build(name)
            out[name] = {
                "source": prog.source,
                "build_s": prog.build_s,
                "precision": prog.precision,
            }
        return out
