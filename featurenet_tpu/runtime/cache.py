"""Persistent AOT executable cache: compile once, deserialize on respawn.

FeatureNet's workload is shape-monomorphic by design (fixed grids, fixed
batch, 24 classes), so every supervisor respawn, preemption resume, and
serving cold start re-pays an XLA compile for a program that is bit-for-bit
the one the previous process already built. This module keeps the compiled
executables on disk — serialized via ``jax.experimental.serialize_executable``
(the machinery under ``jax.export``/``compiled.serialize``) — keyed by a
fingerprint of everything that could invalidate them: jax/jaxlib version,
backend platform and device topology, program name, the config's identity
fields (arch hash), and the program's input shapes/dtypes/precision.

**Load-bearing hazard (PR 1):** executing an executable DESERIALIZED from a
persistent cache can FATALLY ABORT this sandbox — the XLA AOT loader's
machine-feature validation escalates from a logged SIGILL-class complaint
to a process abort, which no in-process ``try`` can catch. Cache loads are
therefore guarded:

- The cache as a whole is opt-in (``Config.exec_cache_dir`` /
  ``--exec-cache-dir`` / ``FEATURENET_EXEC_CACHE_DIR``); no directory, no
  deserialization anywhere.
- Before an entry is deserialized in-process, a throwaway SUBPROCESS
  deserializes and loads it first (``python -m featurenet_tpu.runtime.cache
  --probe <entry>``). The AOT loader's validation runs there; if the child
  dies — by exit code or by signal — the parent records the entry as
  rejected and falls back to a fresh compile. A passed probe is remembered
  in a ``.ok`` sidecar (keyed by env fingerprint + entry digest) so later
  cold starts skip the spawn.
- Every in-process read/deserialize is wrapped: a corrupt file, a stale
  fingerprint, a version-skewed payload — each degrades to a fresh compile
  with a ``cache_reject`` event, never a crash.

``FEATURENET_EXEC_CACHE_PROBE`` overrides the guard policy: ``subprocess``
(default), ``trust`` (skip the probe — for environments proven good), or
``reject`` (never load; still *store*, so a later environment can warm up).

File format (one file per program × shape signature, atomic rename on
write): ``MAGIC | u64 header length | header JSON | payload``. The header
carries the full fingerprint; a mismatch (e.g. a jax upgrade) is a
``stale_fingerprint`` reject and the entry is recompiled and overwritten.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional

MAGIC = b"FNXC1\n"
PROBE_ENV = "FEATURENET_EXEC_CACHE_PROBE"
DIR_ENV = "FEATURENET_EXEC_CACHE_DIR"
PROBE_MODES = ("subprocess", "trust", "reject")
PROBE_TIMEOUT_S = 300.0


def env_fingerprint() -> str:
    """Everything environmental that invalidates a serialized executable:
    jax/jaxlib versions, backend platform, device kind and count."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    parts = (
        jax.__version__,
        jaxlib.__version__,
        dev.platform,
        getattr(dev, "device_kind", ""),
        str(len(jax.devices())),
    )
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def program_fingerprint(name: str, identity: dict, meta: dict) -> str:
    """Full cache key: environment + config identity (arch hash) + the
    program's own meta (input shapes/dtypes, precision, donation)."""
    blob = json.dumps(
        {"env": env_fingerprint(), "program": name,
         "identity": identity, "meta": meta},
        sort_keys=True, default=str,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


def meta_digest(meta: dict, identity: Optional[dict] = None) -> str:
    """Short digest used in the entry FILENAME: the program's shape
    signature plus the config identity, so two batch sizes of one program
    — and two CONFIGS sharing one cache directory (e.g. different
    conv_backend presets warmed into a fleet-wide dir) — coexist instead
    of stale-reject-evicting each other. Deliberately excludes the
    environment, so a jax upgrade lands on the SAME file and is detected
    as a ``stale_fingerprint`` reject rather than silently orphaning the
    old entry."""
    blob = json.dumps({"meta": meta, "identity": identity},
                      sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def _read_entry(path: str) -> tuple[dict, bytes]:
    """Parse an entry file; raises ValueError on any corruption."""
    with open(path, "rb") as fh:
        magic = fh.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(f"bad magic {magic!r}")
        raw_len = fh.read(8)
        if len(raw_len) != 8:
            raise ValueError("truncated header length")
        n = int.from_bytes(raw_len, "little")
        if not (0 < n < 10_000_000):
            raise ValueError(f"implausible header length {n}")
        raw = fh.read(n)
        if len(raw) != n:
            raise ValueError("truncated header")
        header = json.loads(raw.decode("utf-8"))
        payload = fh.read()
    if not isinstance(header, dict) or not payload:
        raise ValueError("empty header or payload")
    return header, payload


def _write_entry(path: str, header: dict, payload: bytes) -> None:
    raw = json.dumps(header, sort_keys=True, default=str).encode("utf-8")
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(MAGIC)
            fh.write(len(raw).to_bytes(8, "little"))
            fh.write(raw)
            fh.write(payload)
        os.replace(tmp, path)  # atomic: a killed run never leaves half a file
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def probe_load(path: str) -> None:
    """Deserialize + LOAD the entry's executable (no execution) — the AOT
    loader's machine-feature validation runs here. Meant to run in a
    throwaway subprocess: this is exactly the step that can fatally abort
    a poisoned environment."""
    import io
    import pickle

    import jax
    from jax._src.lib import xla_client as xc  # noqa: F401 (backend init)

    _, payload = _read_entry(path)
    backend = jax.devices()[0].client

    class _Unpickler(pickle.Unpickler):
        def __init__(self, file):
            super().__init__(file)
            self.devices_by_id = {d.id: d for d in backend.devices()}

        def persistent_load(self, pid):
            if pid[0] == "exec":
                return backend.deserialize_executable(pid[1])
            if pid[0] == "device":
                return self.devices_by_id[pid[1]]
            if pid[0] == "client":
                return backend
            raise pickle.UnpicklingError(str(pid[0]))

    unloaded, _, _ = _Unpickler(io.BytesIO(payload)).load()
    if hasattr(unloaded, "load"):
        unloaded.load()


class ExecutableCache:
    """On-disk executable cache with guarded loads (module docstring)."""

    def __init__(self, directory: str, probe: Optional[str] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.probe = probe or os.environ.get(PROBE_ENV) or "subprocess"
        if self.probe not in PROBE_MODES:
            raise ValueError(
                f"unknown exec-cache probe mode {self.probe!r}; one of "
                f"{', '.join(PROBE_MODES)}"
            )
        # In-process probe memo: entry path -> verdict for this process.
        self._probed: dict[str, bool] = {}

    # -- paths ---------------------------------------------------------------
    def entry_path(self, name: str, digest: str) -> str:
        return os.path.join(self.directory, f"{name}-{digest}.jexec")

    def entries(self) -> list[str]:
        try:
            return sorted(
                n for n in os.listdir(self.directory) if n.endswith(".jexec")
            )
        except OSError:
            return []

    # -- guarded load --------------------------------------------------------
    def load(self, name: str, fingerprint: str, digest: str, lowered):
        """``(compiled, "hit")`` on a verified cache hit; ``(None, reason)``
        otherwise — ``reason`` is ``"miss"`` for a simple absence and a
        reject cause (``stale_fingerprint`` / ``corrupt_entry`` /
        ``probe_failed`` / ``probe_rejected`` / ``deserialize_error``) for
        everything that falls back to a fresh compile with a
        ``cache_reject`` event."""
        path = self.entry_path(name, digest)
        if not os.path.exists(path):
            return None, "miss"
        try:
            header, payload = _read_entry(path)
        except (ValueError, OSError) as e:
            return None, f"corrupt_entry:{type(e).__name__}"
        if header.get("fingerprint") != fingerprint:
            return None, "stale_fingerprint"
        if self.probe == "reject":
            return None, "probe_rejected"
        if self.probe == "subprocess" and not self._probe_entry(path):
            return None, "probe_failed"
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            compiled = deserialize_and_load(
                payload, lowered.in_tree, lowered.out_tree
            )
        except Exception as e:  # version-skewed payload, tree mismatch, …
            return None, f"deserialize_error:{type(e).__name__}"
        return compiled, "hit"

    def store(self, name: str, fingerprint: str, digest: str, compiled,
              meta: dict) -> bool:
        """Serialize + write an entry; False when this executable kind does
        not support serialization (never an error — the cache is an
        optimization, the fresh compile already happened)."""
        try:
            from jax.experimental.serialize_executable import serialize

            payload, _, _ = serialize(compiled)
        except (ValueError, TypeError):
            return False
        header = {
            "program": name,
            "fingerprint": fingerprint,
            "meta": meta,
            "created": time.time(),
        }
        try:
            _write_entry(self.entry_path(name, digest), header, payload)
        except OSError:
            return False  # full/read-only disk: cache quietly absent
        self._probed.pop(self.entry_path(name, digest), None)
        self._drop_marker(self.entry_path(name, digest))
        return True

    # -- the subprocess probe ------------------------------------------------
    def _marker_path(self, path: str) -> str:
        return path + ".ok"

    def _drop_marker(self, path: str) -> None:
        try:
            os.unlink(self._marker_path(path))
        except OSError:
            pass

    def _probe_entry(self, path: str) -> bool:
        if path in self._probed:
            return self._probed[path]
        ok = self._check_marker(path)
        if ok is None:
            ok = self._run_probe(path)
            if ok:
                try:
                    with open(self._marker_path(path), "w") as fh:
                        json.dump({"env": env_fingerprint(),
                                   "entry_sha": _file_digest(path)}, fh)
                except OSError:
                    pass
        self._probed[path] = ok
        return ok

    def _check_marker(self, path: str) -> Optional[bool]:
        """True when a previous probe of this exact entry (same bytes, same
        environment) passed; None when there is no trustworthy verdict."""
        try:
            with open(self._marker_path(path)) as fh:
                marker = json.load(fh)
        except (OSError, ValueError):
            return None
        if (marker.get("env") == env_fingerprint()
                and marker.get("entry_sha") == _file_digest(path)):
            return True
        return None

    def _run_probe(self, path: str) -> bool:
        """Deserialize+load the entry in a throwaway child; a child death —
        exit code OR signal — is the abort the guard exists to absorb."""
        try:
            r = subprocess.run(
                [sys.executable, "-m", "featurenet_tpu.runtime.cache",
                 "--probe", path],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            )
        except (subprocess.TimeoutExpired, OSError):
            return False
        return r.returncode == 0 and "probe-ok" in (r.stdout or "")


def cache_from_config(cfg) -> Optional[ExecutableCache]:
    """The configured cache, or None: ``Config.exec_cache_dir`` wins, then
    the ``FEATURENET_EXEC_CACHE_DIR`` environment (so a supervisor fleet
    can be warmed without touching every launch command)."""
    directory = getattr(cfg, "exec_cache_dir", None) or os.environ.get(DIR_ENV)
    return ExecutableCache(directory) if directory else None


def main(argv=None) -> int:
    """``python -m featurenet_tpu.runtime.cache --probe <entry>`` — the
    subprocess side of the guarded load. Prints ``probe-ok`` and exits 0
    only when the entry deserializes AND the AOT loader accepts it."""
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) != 2 or args[0] != "--probe":
        print("usage: python -m featurenet_tpu.runtime.cache --probe <entry>",
              file=sys.stderr)
        return 2
    try:
        probe_load(args[1])
    except Exception as e:
        print(f"probe-failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    print("probe-ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
