"""Post-training int8 weight quantization for the serving path.

Weight-only PTQ with per-channel scales: every weight tensor of rank >= 2
(conv kernels, dense matrices) is mapped to ``int8`` with one symmetric
scale per OUTPUT channel (the trailing axis in Flax's kernel layout), and
dequantized on device inside the compiled serving program::

    w ≈ w_q.astype(f32) * scale        # scale shape (1, …, 1, C_out)

Per-channel beats per-tensor because conv channels' dynamic ranges differ
by orders of magnitude after BN folding pressure — one tensor-wide scale
would crush the quiet channels to a handful of levels. Biases, BatchNorm
parameters, and running statistics stay fp32: they are a rounding error of
the weight bytes and their precision is what keeps the argmax stable.

Why this is the serving win: the serving forward is memory-bound on weight
traffic for small batches, and int8 weights are 4x smaller than fp32 in
HBM (the dequantize multiply fuses into the convolution's weight read).
Accuracy is gated, not assumed: ``agreement`` measures top-1 match
between any two serving precisions (fp32 / bf16 / int8 — the
precision-agnostic gate every reduced rung passes through) on
held-out-style synthetic data, and the test suite pins it above the
paper's 96.7% target (``PAPER_TOP1_TARGET``, tests/test_runtime.py).

Everything here is pure ``jnp`` so the same functions serve eager
quantization (once, at ``Predictor`` construction) and abstract
``eval_shape`` tracing (the registry needs the quantized tree's avals to
lower the int8 program before real weights exist).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _is_weight(x) -> bool:
    """Quantize matrices and conv kernels; leave vectors/scalars (bias, BN
    scale/mean/var) in fp32."""
    return getattr(x, "ndim", 0) >= 2


def quantize_tree(params):
    """``params`` → ``(q_tree, scale_tree)`` with identical structure.

    Weight leaves become int8 with a per-output-channel symmetric scale
    (shape ``(1, …, 1, C_out)``); non-weight leaves pass through unchanged
    with a scalar 1.0 placeholder scale so the two trees stay congruent
    (jit arguments must be regular pytrees).
    """

    def scale_of(x):
        if not _is_weight(x):
            # Scalar 1.0 placeholder keeps the trees congruent.
            return jnp.ones((), x.dtype if hasattr(x, "dtype") else jnp.float32)
        axes = tuple(range(x.ndim - 1))
        amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
        return jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)

    def q(x, scale):
        if not _is_weight(x):
            return x
        return jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)

    scales = jax.tree_util.tree_map(scale_of, params)
    return jax.tree_util.tree_map(q, params, scales), scales


def dequantize_tree(q_tree, scale_tree):
    """Inverse of ``quantize_tree`` — runs INSIDE the compiled serving
    program, so int8 is what sits in HBM and the multiply fuses into the
    first use of each weight."""

    def d(q, s):
        if q.dtype == jnp.int8:
            return q.astype(jnp.float32) * s
        return q

    return jax.tree_util.tree_map(d, q_tree, scale_tree)


# The paper's held-out top-1 bar (PAPERS.md #1): every reduced-precision
# serving rung — int8 AND bf16 — is gated against it by the tests via
# ``agreement`` (a prediction the precision change did not flip cannot
# have moved held-out accuracy below the bar the fp32 model clears).
PAPER_TOP1_TARGET = 0.967


def agreement(model, params, batch_stats, voxels,
              reference_precision: str = "fp32",
              candidate_precision: str = "int8"):
    """Top-1 (classify) or per-voxel (segment) agreement fraction between
    two serving precisions of the SAME weights on ``voxels`` — the
    precision-agnostic, CPU-testable stand-in for the held-out accuracy
    gate. Each side's forward runs the inference working-copy transform
    (``train.precision.serve_params_cast``): fp32 identity, bf16
    boundary cast, int8 per-channel quantize→dequantize — numerically
    what the corresponding ``serve``/``serve_bf16``/``serve_int8``
    program computes. The trailing-axis argmax covers both tasks."""
    from featurenet_tpu.train.precision import serve_params_cast

    def fwd(precision):
        return model.apply(
            {"params": serve_params_cast(params, precision),
             "batch_stats": batch_stats},
            voxels, train=False,
        )

    ref = jnp.argmax(fwd(reference_precision), axis=-1)
    got = jnp.argmax(fwd(candidate_precision), axis=-1)
    return float(jnp.mean((ref == got).astype(jnp.float32)))
