"""Compiled-program runtime: registry, persistent AOT cache, int8 serving.

``runtime.registry`` is the one front door every entry point builds its
XLA programs through (enumerable, rebuildable, warmable);
``runtime.cache`` keeps the compiled executables on disk behind a
probe-in-subprocess guard; ``runtime.quantize`` is the int8 post-training
weight quantizer the ``*_int8`` serving programs run on. See each
module's docstring — and README "Runtime registry" — for the contract.
"""

from featurenet_tpu.runtime.cache import (
    ExecutableCache,
    cache_from_config,
    env_fingerprint,
    program_fingerprint,
)
from featurenet_tpu.runtime.registry import (
    PROGRAMS,
    CompiledProgram,
    ProgramSpec,
    Runtime,
    build_model,
    hbm_rows_estimate,
    list_programs,
)

__all__ = [
    "PROGRAMS",
    "CompiledProgram",
    "ExecutableCache",
    "ProgramSpec",
    "Runtime",
    "build_model",
    "cache_from_config",
    "env_fingerprint",
    "hbm_rows_estimate",
    "list_programs",
    "program_fingerprint",
]
