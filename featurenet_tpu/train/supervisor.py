"""Stall detection + auto-restart for training runs (failure recovery).

The reference has no failure handling at all — a hung NCCL collective or a
dead rank freezes the job until someone notices (SURVEY.md §5 "failure
detection"). The TPU-native rebuild keeps the same fail-fast *device* posture
(no elastic resharding — a classifier never needs it) but adds the piece that
actually bites in practice: a **supervisor process** that watches a heartbeat
file the Trainer touches at every confirmed point of device progress, and
kills + restarts the training process from its latest Orbax checkpoint when
the heartbeat goes stale or the process dies.

Why a separate process: a stalled step is a thread blocked inside the runtime
waiting on the device transport (observed here: a hung tunnel read parks the
main thread in a futex with signals undeliverable). No in-process watchdog
can interrupt that reliably — only SIGKILL from outside can. This is the
moral equivalent of torchrun's elastic agent, reduced to the single-node
fail-fast case.

Used via ``python -m featurenet_tpu.cli train --supervise [...]``; the
supervisor re-execs the identical CLI command minus the supervision flags,
plus ``--heartbeat-file``. Requires ``--checkpoint-dir`` (restart without
resume would silently retrain from scratch — refused).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import subprocess
import sys
import time
from typing import Optional, Sequence

from featurenet_tpu import faults
# The heartbeat/stall state machine lives in train.heartbeat — ONE
# implementation driven by this supervisor and by the elastic
# coordinator's per-slot monitors (a fix in one watcher used to be able
# to silently miss the other). touch_heartbeat is re-exported here: the
# Trainer's beat path and older callers import it from this module.
from featurenet_tpu.train.heartbeat import (  # noqa: F401
    HeartbeatMonitor,
    touch_heartbeat,
)


# Child exit code meaning "checkpointed and asking to be respawned" (the
# planned-restart protocol, Config.restart_every_steps). Chosen as BSD's
# EX_TEMPFAIL: distinct from 0 (done) and from crash codes, so an
# unsupervised run exiting this way is visibly "not finished".
RESTART_EXIT_CODE = 75


@dataclasses.dataclass
class SuperviseResult:
    exit_code: int  # final child exit code (0 = success)
    restarts: int  # how many times the child was restarted
    stalls: int  # how many restarts were due to a stale heartbeat
    planned: int = 0  # planned (restart_every_steps) respawns, not counted


def _stream_offsets(run_dir: str) -> dict[str, int]:
    """Byte size of every event stream right now — the window start for
    per-child telemetry validation."""
    from featurenet_tpu.obs.report import discover_event_files

    return {
        path: os.path.getsize(path)
        for path, _ in discover_event_files(run_dir)
    }


# validate_events checks that count as crash evidence for the restart
# verdict: records that are structurally corrupt (torn/garbage lines,
# fields the report cannot fold, impossible durations). Span-nesting /
# orphan-parent findings are deliberately EXCLUDED here: a sink that
# degrades mid-run (real ENOSPC — by design "training continues") leaves
# open parents whose close lines never landed, and restarting a run that
# finished its budget because its telemetry went dark would invert the
# "telemetry is never load-bearing" contract.
_CORRUPTION_CHECKS = frozenset({
    "parse", "unknown_kind", "missing_fields", "negative_duration",
})


def _window_events(run_dir: str,
                   offsets: dict[str, int]) -> tuple[list[dict], int]:
    """Parse only the event lines appended since ``offsets`` (one child's
    lifetime). A torn TRAILING fragment (no newline at EOF) is the
    legitimate signature of the sink's ENOSPC degrade path: the short
    write that killed the sink is the last thing the stream ever got, and
    the child then finished dark by design. Drop it uncounted (the same
    partial-trailing-line convention as the live tail's EventTail) —
    "telemetry went dark" must not be condemned as "telemetry is
    corrupt". Garbage *followed by more lines* still counts. One parser
    for the stream format (obs.report._parse_lines): the report's
    --validate, the telemetry verdict, and the segment gate must never
    disagree on the same bytes."""
    from featurenet_tpu.obs.report import _parse_lines, discover_event_files

    events: list[dict] = []
    bad = 0
    for path, idx in discover_event_files(run_dir):
        try:
            with open(path, "rb") as fh:
                fh.seek(offsets.get(path, 0))
                data = fh.read()
        except OSError:
            continue
        if data and not data.endswith(b"\n"):
            data = data[:data.rfind(b"\n") + 1] if b"\n" in data else b""
        bad += _parse_lines(
            data.decode("utf-8", errors="replace").splitlines(), idx, events
        )
    events.sort(key=lambda e: e["t"])
    return events, bad


def _telemetry_findings(run_dir: str, offsets: dict[str, int]) -> list[dict]:
    """Schema-lint only the event lines appended since ``offsets`` (this
    child's lifetime — an old torn line must not condemn every later
    child). Same lint as ``cli report --validate``, narrowed to the
    structural-corruption checks (``_CORRUPTION_CHECKS``)."""
    from featurenet_tpu.obs.report import validate_events

    events, bad = _window_events(run_dir, offsets)
    return [f for f in validate_events(events, bad_lines=bad)
            if f.get("check") in _CORRUPTION_CHECKS]


# --- segment gating (self-pinning regression gates) --------------------------

# Baseline the supervisor auto-pins from the first clean segment's report
# (obs.gates JSON shape — `cli report --gate <run_dir>/gate_baseline.json`
# works on it directly).
GATE_BASELINE_FILENAME = "gate_baseline.json"

# Metrics pinned/judged per segment: the ones a training segment always
# records. Restart/stall counts are supervisor-cumulative (segment 2
# would always "regress" them) and serving/heartbeat metrics are absent
# from short segments — a pin that a later clean segment structurally
# cannot satisfy would cry wolf on every run.
SEGMENT_GATE_METRICS = ("step_ms", "data_wait_fraction", "data_wait_spread",
                        "bad_lines")

# Segments are short and include each child's own compile warmup, so the
# per-segment tolerance is loose — this gate exists to catch drift
# (a config change that doubled step time, a host gone sideways), not to
# re-measure the benchmark.
SEGMENT_GATE_TOLERANCE = 0.35


def segment_gate_values(run_dir: str, offsets: dict[str, int]) -> dict:
    """Gateable scalars of ONE segment: the report of only the event
    lines appended during the child's lifetime, narrowed to the metrics
    every training segment records (``SEGMENT_GATE_METRICS``)."""
    from featurenet_tpu.obs.gates import report_gate_values
    from featurenet_tpu.obs.report import build_report

    events, bad = _window_events(run_dir, offsets)
    rep = build_report(events, bad_lines=bad)
    vals = report_gate_values(rep)
    return {k: v for k, v in vals.items() if k in SEGMENT_GATE_METRICS}


def _gate_segment(run_dir: str, offsets: dict[str, int], record, log) -> None:
    """Close the judge loop the post-hoc gate leaves open: after each
    CLEAN segment, pin a baseline from the first one and judge every
    later one against it — alerting (``gate_regression`` supervisor
    event) instead of drifting silently until a human reads a report.
    Never load-bearing: a gate failure changes no verdict, burns no
    restart budget, and any internal error degrades to a log line."""
    from featurenet_tpu.obs import gates as obs_gates

    try:
        vals = segment_gate_values(run_dir, offsets)
        if "step_ms" not in vals:
            return  # no loop ran in this segment: nothing to judge
        path = os.path.join(run_dir, GATE_BASELINE_FILENAME)
        if not os.path.exists(path):
            baseline = obs_gates.make_baseline(
                vals, tolerance=SEGMENT_GATE_TOLERANCE
            )
            # Near-zero baselines (a well-fed pipeline's data-wait
            # fraction, a tight mesh's spread) get an absolute slack —
            # a relative tolerance on ~0 pins "never change" and cries
            # wolf on noise-level wiggles of a tiny number.
            for name, slack in (("data_wait_fraction", 0.05),
                                ("data_wait_spread", 0.1)):
                pin = baseline["gates"].get(name)
                if pin is not None:
                    pin["tolerance_abs"] = slack
            tmp = path + ".tmp"  # atomic: never half a baseline
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(baseline, fh, indent=1)
            os.replace(tmp, path)
            log(json.dumps({"supervisor": "auto_pin", "baseline": path,
                            "metrics": sorted(vals)}))
            record("auto_pin", baseline=path, metrics=sorted(vals))
            return
        result = obs_gates.evaluate_gates(vals, obs_gates.load_baseline(path))
        if result["ok"]:
            log(json.dumps({"supervisor": "gate", "ok": True}))
            record("gate", ok=True)
        else:
            log(json.dumps({"supervisor": "gate_regression",
                            "failed": result["failed"]}))
            record("gate_regression", failed=result["failed"],
                   values={k: vals.get(k) for k in result["failed"]})
    except Exception as e:  # the judge must never kill the run
        log(json.dumps({"supervisor": "gate_error",
                        "error": repr(e)[:300]}))


def _kill_tree(proc: subprocess.Popen) -> None:
    """SIGKILL the child's whole process group (it may own worker threads
    blocked in native code; nothing softer is guaranteed to land)."""
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass
    proc.wait()


def supervise(
    argv: Sequence[str],
    heartbeat_file: str,
    stall_timeout_s: float = 600.0,
    max_restarts: int = 5,
    poll_s: float = 5.0,
    grace_s: Optional[float] = None,
    log=print,
    run_dir: Optional[str] = None,
    backoff_base_s: float = 1.0,
    backoff_cap_s: float = 60.0,
    validate_telemetry: bool = True,
) -> SuperviseResult:
    """Run ``argv`` under stall supervision; restart on stall or crash.

    Args:
      argv: full child command (e.g. ``[sys.executable, "-m",
        "featurenet_tpu.cli", "train", ...]``) WITHOUT supervision flags but
        WITH ``--heartbeat-file`` pointing at ``heartbeat_file``. (Required
        and explicit because the caller builds argv: a path invented in here
        could never be the one the child touches — a guaranteed kill-loop.)
      heartbeat_file: path the child touches; refreshed before each spawn.
      stall_timeout_s: heartbeat staleness that counts as a hang.
      max_restarts: restarts allowed before giving up (crash-looping run).
      poll_s: supervisor polling interval.
      grace_s: stall clock allowance for the child's cold start (compile can
        dwarf a step); defaults to ``max(stall_timeout_s, 600)``.
      log: sink for one-line JSON status records.
      run_dir: when set (the child's ``--run-dir``), every supervisor
        decision — spawn, stall verdict, restart, giving up — is also
        appended as a ``supervisor`` event to the run's shared
        ``events.jsonl``, so ``cli report`` reconstructs the restart/stall
        timeline next to the child's own spans. Appends are line-atomic
        across processes (obs.events), so the two writers interleave
        safely. The judge loop closes here too: the first clean segment's
        report auto-pins ``<run_dir>/gate_baseline.json``
        (``SEGMENT_GATE_METRICS`` at a loose per-segment tolerance) and
        every later clean segment is evaluated against it — ``auto_pin``
        / ``gate`` / ``gate_regression`` supervisor events, never a
        restart verdict.
      backoff_base_s / backoff_cap_s: crash-loop backoff. Every *unplanned*
        restart sleeps ``min(cap, base * 2**(n-1))`` (n = consecutive
        unplanned restarts) with jitter in [0.5x, 1x) before respawning,
        recorded as a ``backoff`` supervisor event — a deterministic crash
        at full respawn speed would otherwise hammer the device/tunnel
        and burn the whole restart budget in seconds. Planned restarts
        (exit 75 after progress) respawn immediately and reset the streak.
      validate_telemetry: with ``run_dir``, a child that exits 0 has the
        event lines it appended schema-linted (the ``cli report
        --validate`` rules); corrupt telemetry is crash evidence — the
        "success" is not trusted, a ``telemetry_corrupt`` supervisor event
        is recorded, and the child is restarted on the failure budget.

    Returns a ``SuperviseResult``; ``exit_code`` 0 means the child finished.
    """
    grace = grace_s if grace_s is not None else max(stall_timeout_s, 600.0)

    sink = None
    if run_dir:
        from featurenet_tpu.obs.events import EventSink, events_filename

        # The supervisor lives on host 0 and appends to host 0's stream —
        # its child appends there too, from a different process, which is
        # safe because every EventSink emit is one O_APPEND write() of one
        # complete line (obs.events). The report treats the terminal
        # "done"/"giving_up" phases as run-over, which is what stops a
        # live `report --follow`.
        sink = EventSink(run_dir, filename=events_filename(0))

    def record(phase: str, **fields) -> None:
        if sink is not None:
            sink.emit("supervisor", phase=phase, **fields)

    restarts = stalls = planned = 0
    # Consecutive nonzero exits before any heartbeat: a child that dies
    # during startup (argparse error, missing cache dir, out-of-range label)
    # is deterministic — retrying it max_restarts times pays full JAX/device
    # init each round for the same exit. One retry tolerates a transient
    # (tunnel lease mid-release); two in a row is permanent.
    early_fails = 0
    # Consecutive UNPLANNED respawns — the crash-loop backoff exponent.
    consec_failures = 0
    spawns = 0
    rng = random.Random()  # jitter source; never drives test-visible counts
    # The shared heartbeat/stall state machine (train.heartbeat): baseline
    # touch, first-beat-vs-grace split, deleted-file recreate, and the
    # re-read-before-verdict double check all live there — the elastic
    # coordinator drives the identical monitor per slot.
    mon = HeartbeatMonitor(heartbeat_file, stall_timeout_s, grace)
    while True:
        # Fresh baseline per spawn: a stale file from the previous child
        # can't trigger (or mask) a stall verdict for this one; only a
        # *newer* mtime proves the child itself beat, so the cold-start
        # grace (compile >> step time) governs until then.
        mon.reset()
        # Per-child stream window: only lines appended from here on are
        # linted for the exit-0 verdict below AND folded into the segment
        # report the self-pinning gate judges.
        offsets = _stream_offsets(run_dir) if run_dir else {}
        spawns += 1
        spawn_argv = list(argv)
        if faults.maybe_fail("spawn_fail", spawn=spawns):
            # Scripted spawn failure: the child slot is filled by a process
            # that dies instantly — the shape of a bad binary path, an
            # exec refused by the OS, a container OOM-killed at start.
            spawn_argv = [sys.executable, "-c", "raise SystemExit(13)"]
        proc = subprocess.Popen(spawn_argv, start_new_session=True)
        log(json.dumps({"supervisor": "spawn", "pid": proc.pid,
                        "attempt": restarts + 1}))
        record("spawn", pid=proc.pid, attempt=restarts + 1)
        stalled = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            time.sleep(poll_s)
            if mon.poll() == "stall":
                stalled = True
                log(json.dumps({
                    "supervisor": "stall", "pid": proc.pid,
                    "heartbeat_age_s": round(mon.age_s, 1),
                }))
                record("stall", pid=proc.pid,
                       heartbeat_age_s=round(mon.age_s, 1))
                _kill_tree(proc)
                rc = proc.returncode
                break
        # The final beat may have landed inside the last poll window
        # (poll sleeps, then the loop breaks on proc.poll() without
        # re-sampling) — re-check before classifying this exit as a
        # startup failure, or a crash seconds after real progress gets
        # the permanent-failure treatment.
        first_beat_seen = mon.recheck()
        telemetry_bad = False
        if not stalled and rc == 0 and run_dir and validate_telemetry:
            # Exit 0 is a *claim*; the event lines this child appended are
            # the evidence. Torn/garbage telemetry means the child's final
            # moments are untrustworthy (a wedged runtime can exit 0 from
            # an atexit path) — treat it as a crash, on the budget.
            try:
                findings = _telemetry_findings(run_dir, offsets)
            except Exception as e:  # the lint itself must never kill us
                findings = []
                log(json.dumps({"supervisor": "validate_error",
                                "error": repr(e)}))
            if findings:
                telemetry_bad = True
                log(json.dumps({
                    "supervisor": "telemetry_corrupt",
                    "findings": len(findings),
                    "first": findings[0].get("msg"),
                }))
                record("telemetry_corrupt", findings=len(findings),
                       first=findings[0].get("msg"))
        if not stalled and rc == 0 and not telemetry_bad:
            # A clean final segment is judged (or pins the baseline) like
            # any other — a run whose LAST segment drifted must not slip
            # out un-gated just because it was last.
            if run_dir:
                _gate_segment(run_dir, offsets, record, log)
            log(json.dumps({"supervisor": "done", "restarts": restarts,
                            "stalls": stalls, "planned": planned}))
            record("done", restarts=restarts, stalls=stalls, planned=planned)
            if sink is not None:
                sink.close()
            return SuperviseResult(0, restarts, stalls, planned)
        if not stalled and rc == RESTART_EXIT_CODE and first_beat_seen:
            # Planned restart: the child checkpointed and asked for a fresh
            # process (restart_every_steps). Free, by design — it must not
            # consume the failure budget, or long runs would trade away
            # their real crash protection. A completed segment is real
            # progress, so it also clears the consecutive-startup-failure
            # counter (two *non-consecutive* transients must not read as a
            # deterministic startup failure).
            planned += 1
            early_fails = 0
            consec_failures = 0  # real progress ends any crash streak
            # Self-pinning gate: the first clean segment's report becomes
            # the baseline; every later clean segment is judged against it
            # (gate_regression event on drift — alert, never a restart).
            if run_dir:
                _gate_segment(run_dir, offsets, record, log)
            log(json.dumps({"supervisor": "planned_restart",
                            "count": planned}))
            record("planned_restart", count=planned)
            continue
        if not stalled and not first_beat_seen and not telemetry_bad:
            early_fails += 1
            if early_fails >= 2:
                log(json.dumps({
                    "supervisor": "giving_up",
                    "reason": f"exit_{rc} before first heartbeat, twice — "
                              "deterministic startup failure",
                    "restarts": restarts, "stalls": stalls,
                }))
                record("giving_up", reason=f"exit_{rc} before first "
                       "heartbeat, twice", restarts=restarts, stalls=stalls)
                if sink is not None:
                    sink.close()
                return SuperviseResult(rc if rc else 1, restarts, stalls,
                                       planned)
        else:
            early_fails = 0
        stalls += int(stalled)
        restarts += 1
        if restarts > max_restarts:
            log(json.dumps({"supervisor": "giving_up", "restarts": restarts - 1,
                            "stalls": stalls, "last_exit": rc}))
            record("giving_up", restarts=restarts - 1, stalls=stalls,
                   last_exit=rc)
            if sink is not None:
                sink.close()
            return SuperviseResult(rc if rc else 1, restarts - 1, stalls,
                                   planned)
        reason = ("stall" if stalled
                  else "telemetry_corrupt" if telemetry_bad
                  else f"exit_{rc}")
        # Crash-loop backoff: exponential in the UNPLANNED-restart streak,
        # jittered so a fleet of supervisors sharing a recovering
        # dependency doesn't respawn in lockstep, capped (~backoff_cap_s)
        # so a multi-day run's sporadic crashes never wait minutes.
        consec_failures += 1
        delay = min(backoff_cap_s,
                    backoff_base_s * (2 ** (consec_failures - 1)))
        delay *= 0.5 + 0.5 * rng.random()
        if delay > 0:
            log(json.dumps({"supervisor": "backoff",
                            "delay_s": round(delay, 3),
                            "consecutive_failures": consec_failures}))
            record("backoff", delay_s=round(delay, 3),
                   consecutive_failures=consec_failures)
            time.sleep(delay)
        log(json.dumps({"supervisor": "restart", "attempt": restarts + 1,
                        "reason": reason}))
        record("restart", attempt=restarts + 1, reason=reason)


def child_argv_from_cli(argv: Sequence[str], heartbeat_file: str) -> list[str]:
    """Rewrite this process's CLI argv into the supervised child's argv:
    strip supervision flags, inject the heartbeat path."""
    out = [sys.executable, "-m", "featurenet_tpu.cli"]
    skip_next = False
    for a in argv:
        if skip_next:
            skip_next = False
            continue
        if a == "--supervise":
            continue
        if a in ("--stall-timeout", "--max-restarts", "--heartbeat-file"):
            skip_next = True
            continue
        if a.startswith(
            ("--stall-timeout=", "--max-restarts=", "--heartbeat-file=")
        ):
            continue
        out.append(a)
    out += ["--heartbeat-file", heartbeat_file]
    # Mark the child as supervised: the CLI refuses a bare --restart-every
    # (nothing would respawn the exit-75 child), but *this* child's respawner
    # is us — the marker lets the re-passed --restart-every through.
    out.append("--supervised-child")
    return out
