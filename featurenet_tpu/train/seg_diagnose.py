"""Attribute a segmentation checkpoint's residual IoU gap, voxel by voxel.

The order-ambiguity ceiling is measured model-free by ``data.seg_oracle``;
after the canonical-label fix removed that ambiguity, the remaining gap (IoU
~0.81 vs ~1.0) needs attribution: is it *class identity* confusion inside
geometric families (a rectangular through step is a voxel-subset of a
two-sided through step — deciding which class a side-carve belongs to takes
global reasoning about the opposite face), or *detection* failure (feature
voxels called stock / wrong shapes)?

This tool runs one exact held-out pass with the trained checkpoint and
reports:

- the voxel-level confusion matrix over the 25 labels (stock + 24 classes);
- the top confused class pairs, and the *families* they induce (connected
  components of the pair graph above a confusion threshold);
- mean IoU as trained, and mean IoU with each family collapsed to one
  label, for prediction AND truth. The delta is the measured cost of class
  identity inside families; the collapsed number is what a
  family-level recognizer already achieves.

Run:  python -m featurenet_tpu.train.seg_diagnose
          --checkpoint-dir CK --data-cache CACHE [--threshold 0.1]
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def _families(conf: np.ndarray, threshold: float) -> list[list[int]]:
    """Connected components of the symmetrized row-normalized confusion
    graph over feature classes (label 0 = stock excluded): classes i,j are
    linked when either direction's confusion rate exceeds ``threshold``."""
    n = conf.shape[0]
    row = conf.sum(axis=1, keepdims=True)
    rate = conf / np.maximum(row, 1)
    adj = np.zeros((n, n), bool)
    for i in range(1, n):
        for j in range(1, n):
            if i != j and (rate[i, j] > threshold or rate[j, i] > threshold):
                adj[i, j] = adj[j, i] = True
    seen, out = set(), []
    for i in range(1, n):
        if i in seen:
            continue
        comp, stack = [], [i]
        seen.add(i)
        while stack:
            u = stack.pop()
            comp.append(u)
            for v in np.nonzero(adj[u])[0]:
                if v not in seen:
                    seen.add(int(v))
                    stack.append(int(v))
        if len(comp) > 1:
            out.append(sorted(comp))
    return out


def _mean_iou_from_confusion(conf: np.ndarray) -> tuple[float, np.ndarray]:
    """Exact per-class IoU from a voxel confusion matrix: inter = diagonal,
    union = row + col - diagonal. Same aggregation as train.steps."""
    inter = np.diag(conf).astype(np.float64)
    union = conf.sum(1) + conf.sum(0) - inter
    present = union > 0
    iou = np.where(present, inter / np.maximum(union, 1), 0.0)
    return float(iou.sum() / max(int(present.sum()), 1)), iou


def _collapse(conf: np.ndarray, families: list[list[int]]) -> np.ndarray:
    """Merge each family's rows+cols into one label.

    Mapping-based, not positional deletion: every label maps to its
    family's representative up front, then the matrix is aggregated in one
    pass — no index shifting between families (a positional scheme merged
    the *wrong* classes for the second family onward; caught in review,
    covered by the two-family unit test).
    """
    n = conf.shape[0]
    mapping = np.arange(n)
    for fam in families:
        mapping[fam] = fam[0]
    _, inv = np.unique(mapping, return_inverse=True)
    m = int(inv.max()) + 1
    flat = inv[:, None] * m + inv[None, :]
    return (
        np.bincount(flat.ravel(), weights=conf.ravel(), minlength=m * m)
        .reshape(m, m)
        .astype(conf.dtype)
    )


def diagnose(
    checkpoint_dir: str,
    data_cache: str,
    threshold: float = 0.1,
    batch: int = 32,
) -> dict:
    import jax
    import jax.numpy as jnp

    from featurenet_tpu.data.offline import SegCacheDataset
    from featurenet_tpu.data.synthetic import CLASS_NAMES
    from featurenet_tpu.train.checkpoint import (
        CheckpointManager,
        load_run_config,
    )
    from featurenet_tpu.train.loop import build_model
    from featurenet_tpu.train.state import create_state
    from featurenet_tpu.train.steps import make_optimizer, unpack_voxels

    cfg = load_run_config(checkpoint_dir)
    if cfg is None or cfg.task != "segment":
        raise SystemExit(
            "seg_diagnose needs a segment checkpoint with a persisted "
            f"config (got {getattr(cfg, 'task', None)!r})"
        )
    model = build_model(cfg)  # exactly the trained module tree
    R = cfg.resolution
    dummy = jnp.zeros((batch, R, R, R, 1), jnp.float32)
    state = create_state(model, make_optimizer(cfg), dummy, jax.random.key(0))
    state = CheckpointManager(checkpoint_dir, config=cfg).restore(state)

    @jax.jit
    def predict(params, batch_stats, packed):
        x = unpack_voxels(packed)  # [B,R,R,R,1] float32
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=False
        )
        return jnp.argmax(logits, axis=-1).astype(jnp.int8)

    ds = SegCacheDataset(
        data_cache, global_batch=batch, split="test",
        test_fraction=cfg.test_fraction,
    )
    # Class count from the model's own output head (build_model doesn't
    # thread arch.num_classes into the segmenter, so the config value can
    # diverge from what the checkpoint actually predicts).
    n_cls = model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        dummy[:1], train=False,
    ).shape[-1]
    conf = np.zeros((n_cls, n_cls), np.int64)
    for b in ds.epoch_batches(batch):
        pred = np.asarray(
            predict(state.params, state.batch_stats, jnp.asarray(b["voxels"]))
        )
        valid = b["mask"] > 0
        t = b["seg"][valid].ravel().astype(np.int64)
        p = pred[valid].ravel().astype(np.int64)
        # bincount over t*n+p, not np.add.at: ~10^8 scatter updates per
        # pass through ufunc.at is minutes; bincount is seconds.
        conf += np.bincount(
            t * n_cls + p, minlength=n_cls * n_cls
        ).reshape(n_cls, n_cls)

    raw_miou, raw_iou = _mean_iou_from_confusion(conf)
    fams = _families(conf, threshold)
    collapsed_miou, _ = _mean_iou_from_confusion(_collapse(conf, fams))

    def name(i):  # label 0 is stock/air
        return "stock" if i == 0 else CLASS_NAMES[i - 1]

    row = conf.sum(1)
    top_pairs = sorted(
        (
            (float(conf[i, j] / max(row[i], 1)), name(i), name(j))
            for i in range(1, n_cls)
            for j in range(n_cls)
            if i != j and conf[i, j] > 0
        ),
        reverse=True,
    )[:8]
    return {
        "checkpoint": checkpoint_dir,
        "mean_iou": round(raw_miou, 4),
        "mean_iou_family_collapsed": round(collapsed_miou, 4),
        "family_identity_cost": round(collapsed_miou - raw_miou, 4),
        "families": [[name(c) for c in fam] for fam in fams],
        "confusion_threshold": threshold,
        "top_confused_pairs": [
            {"rate": round(r, 3), "true": t, "pred": p}
            for r, t, p in top_pairs
        ],
        "per_class_iou": {
            name(i): round(float(v), 4) for i, v in enumerate(raw_iou)
        },
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--data-cache", required=True)
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="row-normalized confusion rate above which two "
                         "classes are joined into a family")
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()
    print(json.dumps(diagnose(
        args.checkpoint_dir, args.data_cache, args.threshold, args.batch
    )))


if __name__ == "__main__":
    main()
