"""Train state: params + BN stats + optimizer state, one donatable pytree.

The torch analog is three separate objects (`model.state_dict()`, the DDP
wrapper, `optimizer.state_dict()`); here it's one immutable pytree so the
whole update is `state -> state` inside jit with donated buffers (zero-copy
in-place update in HBM).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import core, struct


class TrainState(struct.PyTreeNode):
    """``params`` are always the fp32 MASTERS — under the reduced
    training precision policies (``train/precision.py``) the jitted step
    casts a bf16/fp16 working copy for forward/backward and applies the
    (fp32-upcast) gradients back to these masters. ``precision`` is the
    policy name, carried as static metadata so one step function serves
    every mode and a checkpoint (which persists the masters, never the
    working copy) restores bitwise into any other.

    ``loss_scale`` / ``good_steps`` are the dynamic loss-scaling state of
    the ``fp16_scaled`` policy (current scale; consecutive finite-grad
    steps since the last scale change). They are ordinary pytree LEAVES
    under every policy — inert scalars (1.0 / 0) outside fp16_scaled —
    so the state treedef is precision-independent: checkpoints carry the
    scale state, a resumed fp16 run keeps its adapted scale, and a
    cross-precision restore (fp16_scaled → fp32 and back) round-trips it
    untouched."""

    step: jax.Array
    params: core.FrozenDict[str, Any]
    batch_stats: core.FrozenDict[str, Any]
    opt_state: optax.OptState
    loss_scale: jax.Array
    good_steps: jax.Array
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    precision: str = struct.field(pytree_node=False, default="fp32")

    @property
    def policy(self):
        """The ``PrecisionPolicy`` this state trains under."""
        from featurenet_tpu.train.precision import get_policy

        return get_policy(self.precision)

    def apply_gradients(self, *, grads, batch_stats):
        """Apply ``grads`` (already at master dtype — the step upcasts
        via ``policy.master_grads`` before calling here) to the masters."""
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=batch_stats,
            opt_state=new_opt_state,
        )


def create_state(
    model,
    tx: optax.GradientTransformation,
    sample_input,
    rng: jax.Array,
    precision: str = "fp32",
) -> TrainState:
    """Initialize model variables and optimizer state (host-side, un-jitted).

    Callers that want sharded init should wrap this in ``jax.jit`` with
    output shardings (see ``Trainer``) so XLA materializes params directly
    into their mesh placement. ``precision`` names the training precision
    policy (``train/precision.py``); the initialized params are fp32
    masters under every policy.
    """
    from featurenet_tpu.train.precision import get_policy, initial_loss_scale

    get_policy(precision)  # refuse a typo'd policy before any device work
    variables = model.init({"params": rng}, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", core.freeze({}))
    return TrainState(
        step=jax.numpy.zeros((), dtype=jax.numpy.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        # Present under every policy (inert outside fp16_scaled) so the
        # state treedef — and cross-precision checkpoint restore — never
        # depends on the precision mode.
        loss_scale=jax.numpy.asarray(
            initial_loss_scale(precision), jax.numpy.float32
        ),
        good_steps=jax.numpy.zeros((), dtype=jax.numpy.int32),
        tx=tx,
        precision=precision,
    )


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
