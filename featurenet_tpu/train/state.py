"""Train state: params + BN stats + optimizer state, one donatable pytree.

The torch analog is three separate objects (`model.state_dict()`, the DDP
wrapper, `optimizer.state_dict()`); here it's one immutable pytree so the
whole update is `state -> state` inside jit with donated buffers (zero-copy
in-place update in HBM).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import core, struct


class TrainState(struct.PyTreeNode):
    """``params`` are always the fp32 MASTERS — under the ``bf16_master``
    training precision policy (``train/precision.py``) the jitted step
    casts a bf16 working copy for forward/backward and applies the
    (fp32-upcast) gradients back to these masters. ``precision`` is the
    policy name, carried as static metadata so one step function serves
    both modes and a checkpoint (which persists the masters, never the
    working copy) restores bitwise into either."""

    step: jax.Array
    params: core.FrozenDict[str, Any]
    batch_stats: core.FrozenDict[str, Any]
    opt_state: optax.OptState
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    precision: str = struct.field(pytree_node=False, default="fp32")

    @property
    def policy(self):
        """The ``PrecisionPolicy`` this state trains under."""
        from featurenet_tpu.train.precision import get_policy

        return get_policy(self.precision)

    def apply_gradients(self, *, grads, batch_stats):
        """Apply ``grads`` (already at master dtype — the step upcasts
        via ``policy.master_grads`` before calling here) to the masters."""
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            batch_stats=batch_stats,
            opt_state=new_opt_state,
        )


def create_state(
    model,
    tx: optax.GradientTransformation,
    sample_input,
    rng: jax.Array,
    precision: str = "fp32",
) -> TrainState:
    """Initialize model variables and optimizer state (host-side, un-jitted).

    Callers that want sharded init should wrap this in ``jax.jit`` with
    output shardings (see ``Trainer``) so XLA materializes params directly
    into their mesh placement. ``precision`` names the training precision
    policy (``train/precision.py``); the initialized params are fp32
    masters under every policy.
    """
    from featurenet_tpu.train.precision import get_policy

    get_policy(precision)  # refuse a typo'd policy before any device work
    variables = model.init({"params": rng}, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", core.freeze({}))
    return TrainState(
        step=jax.numpy.zeros((), dtype=jax.numpy.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
        tx=tx,
        precision=precision,
    )


def param_count(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))
