"""Jitted train / eval steps and the loss+metric functions they share.

Everything here is pure and shape-monomorphic: one train step is one XLA
executable containing forward, backward, the optimizer update, the BN stat
update, and — when the batch is sharded over a mesh — every collective the
partitioner decides it needs. The host loop never sees a gradient.

Reference parity (SURVEY.md §3.1 hot loop): forward → cross_entropy →
backward → allreduce → step. Here the "allreduce" has no call site: reducing
a mean over a ``data``-sharded batch axis *is* the gradient sync.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax

from featurenet_tpu.train.state import TrainState


def unpack_voxels(packed: jax.Array) -> jax.Array:
    """Device-side inverse of ``data.synthetic.pack_voxels``.

    ``[B, R, R, R/8] uint8`` → ``[B, R, R, R, 1] float32``. Bit-packed wire
    batches are 32x smaller than float32 over the host→device link; the
    unpack (shift+mask+reshape) fuses into the first conv's input read.
    """
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)  # packbits is MSB-first
    bits = (packed[..., None] >> shifts) & jnp.uint8(1)
    b, d, h, w8 = packed.shape
    # lint: allow-precision(wire contract: the model input edge is fp32)
    return bits.reshape(b, d, h, w8 * 8, 1).astype(jnp.float32)


def _batch_voxels(batch: dict, packed: bool) -> jax.Array:
    return unpack_voxels(batch["voxels"]) if packed else batch["voxels"]


def classification_loss(
    logits: jax.Array,  # [B, C] fp32
    labels: jax.Array,  # [B] int32
    label_smoothing: float = 0.0,
) -> tuple[jax.Array, dict[str, jax.Array]]:
    num_classes = logits.shape[-1]
    if label_smoothing > 0.0:
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, num_classes), label_smoothing
        )
        loss = optax.softmax_cross_entropy(logits, onehot).mean()
    else:
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
    acc = (jnp.argmax(logits, axis=-1) == labels).mean()
    return loss, {"loss": loss, "accuracy": acc}


def _soft_dice(logits: jax.Array, seg: jax.Array) -> jax.Array:
    """Mean soft-Dice loss over the classes present in the batch.

    Dice optimizes the eval metric (IoU) directly where cross-entropy
    optimizes per-voxel calibration: CE's gradient on a thin feature shell
    is dominated by the easy background interior, while Dice normalizes per
    class, so small features keep full-strength gradients. Background is
    included as a class (its Dice term penalizes false feature voxels).
    """
    n_cls = logits.shape[-1]
    probs = jax.nn.softmax(logits, axis=-1)
    true_1h = jax.nn.one_hot(seg, n_cls, dtype=probs.dtype)
    axes = tuple(range(probs.ndim - 1))
    inter = (probs * true_1h).sum(axes)
    denom = probs.sum(axes) + true_1h.sum(axes)
    present = true_1h.sum(axes) > 0
    dice = 1.0 - (2.0 * inter + 1.0) / (denom + 1.0)
    return (dice * present).sum() / jnp.maximum(present.sum(), 1)


def segmentation_loss(
    logits: jax.Array,  # [B, D, H, W, C+1] fp32
    seg: jax.Array,  # [B, D, H, W] int32, 0 = background
    variant: str = "balanced_ce",
) -> tuple[jax.Array, dict[str, jax.Array]]:
    """Per-voxel loss; ``variant`` picks the class-imbalance treatment.

    - ``balanced_ce``: cross-entropy with background down-weighting —
      background dominates (a carved part is mostly stock/air), so feature
      voxels are up-weighted until fg and bg contribute ~equally.
    - ``ce_dice``: balanced CE + soft Dice (``_soft_dice``) — the round-2
      push past the 0.779-IoU plateau; Dice optimizes the IoU metric
      directly per class.
    - ``dice``: soft Dice alone (ablation arm).
    """
    per_voxel = optax.softmax_cross_entropy_with_integer_labels(logits, seg)
    # lint: allow-precision(loss-land class weighting stays fp32)
    is_fg = (seg > 0).astype(jnp.float32)
    # Foreground voxels weighted so fg and bg contribute ~equally.
    fg_frac = is_fg.mean()
    w = jnp.where(seg > 0, 0.5 / jnp.maximum(fg_frac, 1e-4),
                  0.5 / jnp.maximum(1.0 - fg_frac, 1e-4))
    ce = (per_voxel * w).mean()
    if variant == "balanced_ce":
        loss = ce
    elif variant == "ce_dice":
        loss = ce + _soft_dice(logits, seg)
    elif variant == "dice":
        loss = _soft_dice(logits, seg)
    else:
        raise ValueError(f"unknown segmentation loss variant {variant!r}")
    pred = jnp.argmax(logits, axis=-1)
    acc = (pred == seg).mean()
    fg_acc = jnp.where(
        is_fg.sum() > 0, ((pred == seg) * is_fg).sum() / is_fg.sum(), 0.0
    )
    return loss, {"loss": loss, "accuracy": acc, "fg_accuracy": fg_acc}


def _scaled_update(loss_fn, policy, state: TrainState, voxels, target,
                   dropout_rng):
    """The fp16_scaled step body: dynamic loss scaling around the
    backward (``train/precision.py`` constants).

    The loss is multiplied by ``state.loss_scale`` before the backward
    (so float16 cotangents neither underflow nor overflow at healthy
    scales), the gradients are upcast to fp32 and UNSCALED, and the
    finiteness of the whole unscaled gradient tree decides the step:

    - finite: the update applies to the masters exactly like the other
      policies; ``LOSS_SCALE_GROWTH_INTERVAL`` consecutive finite steps
      double the scale (capped at ``LOSS_SCALE_MAX``).
    - non-finite (overflowed backward): the update is skipped BITWISE —
      masters, optimizer slots, and BN stats keep their exact bits, only
      the step counter advances — and the scale halves (floored at
      ``LOSS_SCALE_MIN``), so the next step retries at a survivable
      scale. The skip/scale verdict is branchless (``jnp.where`` over
      the state leaves): one executable serves both outcomes.

    Metrics gain ``loss_scale`` (post-verdict) and ``grads_finite`` so a
    skipped step is visible in the log stream, not inferred.
    """
    from featurenet_tpu.train.precision import (
        LOSS_SCALE_GROWTH_INTERVAL,
        LOSS_SCALE_MAX,
        LOSS_SCALE_MIN,
    )

    scale = state.loss_scale

    def scaled_loss(params, batch_stats, vox, tgt, rng):
        loss, aux = loss_fn(params, batch_stats, vox, tgt, rng)
        return loss * scale.astype(loss.dtype), aux

    grads, (new_stats, metrics) = jax.grad(scaled_loss, has_aux=True)(
        policy.working_params(state.params), state.batch_stats,
        voxels, target, dropout_rng
    )
    # Upcast FIRST, then unscale: dividing in float16 would re-overflow
    # the very gradients the scale protected.
    grads = policy.master_grads(grads)
    inv = 1.0 / scale
    grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
    finite = jnp.array(True)
    for g in jax.tree_util.tree_leaves(grads):
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    applied = state.apply_gradients(grads=grads, batch_stats=new_stats)
    # The skip twin: identical bits everywhere, step advanced (the run's
    # schedule/rng stream must not stall on a skipped update).
    skipped = state.replace(step=state.step + 1)
    merged = jax.tree_util.tree_map(
        lambda a, b: jnp.where(finite, a, b), applied, skipped
    )
    good = jnp.where(finite, state.good_steps + 1,
                     jnp.zeros_like(state.good_steps))
    grow = good >= LOSS_SCALE_GROWTH_INTERVAL
    new_scale = jnp.where(
        finite,
        jnp.where(grow, jnp.minimum(scale * 2.0, LOSS_SCALE_MAX), scale),
        jnp.maximum(scale * 0.5, LOSS_SCALE_MIN),
    )
    good = jnp.where(grow, jnp.zeros_like(good), good)
    state = merged.replace(loss_scale=new_scale, good_steps=good)
    metrics = dict(metrics)
    metrics["grad_norm"] = optax.global_norm(grads)
    metrics["loss_scale"] = new_scale
    # lint: allow-precision(host-facing metric scalar, not step dataflow)
    metrics["grads_finite"] = finite.astype(jnp.float32)
    return state, metrics


def make_train_step(
    model,
    task: str = "classify",
    label_smoothing: float = 0.0,
    augment_groups: int = 0,
    packed: bool = False,
    seg_loss: str = "balanced_ce",
    augment_noise: float = 0.0,
    augment_affine: bool = False,
    affine_opts: dict | None = None,
) -> Callable:
    """Build the pure train-step function (jit it with shardings at call site).

    ``augment_groups > 0`` applies device-side cube-group pose augmentation
    (ops/augment.py) inside the compiled step: classification rotates the
    voxels (the label is pose-invariant); segmentation rotates voxels and
    the per-voxel target jointly with shared group elements
    (``random_rotate_batch_paired``). ``augment_affine`` replaces the cube
    group with the continuous affine warp (``random_affine_batch_paired``;
    per-voxel targets resample nearest-neighbor with shared transforms);
    ``affine_opts`` carries its knobs — ``scale_range``, ``rotate``,
    ``translate_vox``, ``prob``, and ``ramp_steps`` (prob ramps linearly
    from 0 over this many steps, keyed off ``state.step``).
    ``packed=True`` expects bit-packed wire voxels and unpacks them on
    device.
    """

    target_key = "label" if task == "classify" else "seg"
    aff = dict(scale_range=(0.7, 1.05), rotate=True, translate_vox=0.0,
               prob=1.0, ramp_steps=0)
    aff.update(affine_opts or {})

    def loss_fn(params, batch_stats, voxels, target, dropout_rng):
        out, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            voxels,
            train=True,
            rngs={"dropout": dropout_rng},
            mutable=["batch_stats"],
        )
        if task == "classify":
            loss, metrics = classification_loss(out, target, label_smoothing)
        else:
            loss, metrics = segmentation_loss(
                out, target.astype(jnp.int32), variant=seg_loss
            )
        return loss, (mutated["batch_stats"], metrics)

    def train_step(state: TrainState, batch, rng):
        # Fold the step index in so dropout differs per step from one base key.
        step_rng = jax.random.fold_in(rng, state.step)
        dropout_rng, aug_rng, noise_rng = jax.random.split(step_rng, 3)
        voxels = _batch_voxels(batch, packed)
        target = batch[target_key]
        if augment_affine and augment_groups:
            from featurenet_tpu.ops.augment import (
                random_affine_batch_paired,
            )

            prob = aff["prob"]
            if aff["ramp_steps"] > 0:
                # Linear warm-in: clean batches early (fast clean
                # convergence), full augmentation pressure by ramp_steps.
                prob = prob * jnp.clip(
                    state.step / aff["ramp_steps"], 0.0, 1.0
                )
            voxels, aff_target = random_affine_batch_paired(
                voxels, target if task == "segment" else None,
                aug_rng, augment_groups,
                scale_range=tuple(aff["scale_range"]),
                rotate=aff["rotate"],
                translate_vox=aff["translate_vox"],
                prob=prob,
            )
            if task == "segment":
                target = aff_target
        elif augment_groups:
            from featurenet_tpu.ops.augment import (
                random_rotate_batch_paired,
            )

            voxels, rot_target = random_rotate_batch_paired(
                voxels, target if task == "segment" else None,
                aug_rng, augment_groups,
            )
            if task == "segment":
                target = rot_target
        if augment_noise > 0.0:
            # Occupancy bit-flips (the OOD harness's noise model), applied
            # AFTER any pose/affine augmentation so the trained noise
            # matches the harness's crisp bit-flips on the final grid
            # (flips warped through the affine resample would attenuate
            # into fractional blobs). XOR on the 0/1 grid — VPU-cheap.
            flip = jax.random.bernoulli(
                noise_rng, augment_noise, voxels.shape
            )
            voxels = jnp.abs(voxels - flip.astype(voxels.dtype))
        # Precision policy (train/precision.py): differentiate with
        # respect to the WORKING copy — under bf16_master/fp16_scaled
        # that is a reduced-precision cast of the fp32 masters compiled
        # inside this step (the donated-buffer dataflow; the cast's
        # output is a fresh buffer, never the donated masters), so the
        # backward stores reduced gradients. They come back to fp32 at
        # the step boundary and the update applies to the masters. Under
        # fp32 both calls are the identity and this step compiles
        # exactly as it always did. state.precision is STATIC pytree
        # metadata, so the loss-scaling branch below is a trace-time
        # choice — fp32/bf16_master executables contain none of it.
        policy = state.policy
        if policy.loss_scaling:
            return _scaled_update(
                loss_fn, policy, state, voxels, target, dropout_rng
            )
        grads, (new_stats, metrics) = jax.grad(loss_fn, has_aux=True)(
            policy.working_params(state.params), state.batch_stats,
            voxels, target, dropout_rng
        )
        grads = policy.master_grads(grads)
        state = state.apply_gradients(grads=grads, batch_stats=new_stats)
        metrics["grad_norm"] = optax.global_norm(grads)
        return state, metrics

    return train_step


def make_multi_train_step(
    model,
    task: str = "classify",
    label_smoothing: float = 0.0,
    augment_groups: int = 0,
    packed: bool = False,
    seg_loss: str = "balanced_ce",
    num_steps: int = 2,
    augment_noise: float = 0.0,
    augment_affine: bool = False,
    affine_opts: dict | None = None,
) -> Callable:
    """``num_steps`` train steps fused into ONE XLA executable.

    Takes ``(state, batches, rng)`` where ``batches`` is a tuple of
    ``num_steps`` wire batches; runs the single-step function over them
    sequentially inside one compiled program and returns the final state
    plus the last step's metrics. One dispatch then costs one host→device
    round trip for ``num_steps`` optimizer updates — the standard TPU idiom
    for amortizing per-step dispatch latency on a slow host or link (the
    warp64 profile's largest non-compute line was 11.2 ms of per-call
    dispatch through this environment's tunnel, BASELINE.md round 3).

    Numerics match ``num_steps`` sequential dispatches of
    ``make_train_step`` to one-ulp: the body *is* that function, and its
    per-step rng fold keys off ``state.step``, which advances per inner
    step — the only divergence is XLA reassociating fused matmuls across
    step boundaries (measured ≤1.5e-8 on Dense kernels; pinned by
    tests/test_train.py::test_steps_per_dispatch_matches_single_step).
    """
    if num_steps < 1:
        raise ValueError(f"num_steps must be >= 1, got {num_steps}")
    step = make_train_step(
        model, task, label_smoothing,
        augment_groups=augment_groups, packed=packed, seg_loss=seg_loss,
        augment_noise=augment_noise, augment_affine=augment_affine,
        affine_opts=affine_opts,
    )

    def multi_step(state: TrainState, batches, rng):
        metrics = None
        for b in batches:
            state, metrics = step(state, b, rng)
        return state, metrics

    return multi_step


def make_hbm_multi_train_step(
    model,
    mesh,
    global_batch: int,
    task: str = "classify",
    label_smoothing: float = 0.0,
    augment_groups: int = 0,
    num_steps: int = 1,
    seg_loss: str = "balanced_ce",
    augment_noise: float = 0.0,
    augment_affine: bool = False,
    affine_opts: dict | None = None,
) -> Callable:
    """Train steps that SAMPLE THEIR BATCHES FROM HBM — zero per-step host
    traffic.

    The 24×1000 64³ benchmark bit-packed is ~750 MB: it fits in a v5e
    chip's 16 GB HBM outright (the seg cache ~0.5 GB), so the TPU-native
    input pipeline for this dataset scale is *device residency* — upload
    the packed train split once, then every train step draws its batch on
    device. Takes ``(state, data, targets, rng)`` where ``data`` is uint8
    ``[N, R, R, R/8]`` and ``targets`` is int32 labels ``[N]`` (classify)
    or int8 seg ``[N, R, R, R]`` (segment), both sharded
    ``P('data')`` along dim 0 over the mesh. Each data-axis shard draws
    its ``global_batch / data_axis`` rows uniformly from its own block via
    ``shard_map`` (decorrelated per shard by ``axis_index``), so sampling
    needs no cross-shard collective; materialize the array from a
    seed-shuffled global order so blocks are random subsets (the draw is
    then block-stratified uniform — statistically equivalent to the host
    sampler for training purposes, not bit-identical to it).

    ``num_steps`` inner steps run inside the one executable (same fusion
    as ``make_multi_train_step``); with the dataset resident, one dispatch
    carries ``num_steps`` updates and ~zero bytes of input, which is what
    lets end-to-end wall-clock match the device rate even through a slow
    host link (measured in BASELINE.md round 4).
    """
    if num_steps < 1:
        raise ValueError(f"num_steps must be >= 1, got {num_steps}")
    from jax.sharding import PartitionSpec as P

    target_key = "label" if task == "classify" else "seg"
    step = make_train_step(
        model, task, label_smoothing,
        augment_groups=augment_groups, packed=True, seg_loss=seg_loss,
        augment_noise=augment_noise, augment_affine=augment_affine,
        affine_opts=affine_opts,
    )
    data_axis = mesh.shape["data"]
    if global_batch % data_axis:
        raise ValueError(
            f"global_batch {global_batch} must divide over data axis "
            f"{data_axis}"
        )
    local_batch = global_batch // data_axis

    def draw(key, data_local, targets_local):
        # Per-shard decorrelation: each data-axis block draws with its own
        # fold of the step key from its own [n_local] row range.
        ax = jax.lax.axis_index("data")
        idx = jax.random.randint(
            jax.random.fold_in(key, ax),
            (local_batch,), 0, data_local.shape[0],
        )
        return (
            jnp.take(data_local, idx, axis=0),
            jnp.take(targets_local, idx, axis=0),
        )

    # Version span: the function lives at jax.shard_map on new releases and
    # jax.experimental.shard_map on old ones, and the replication-check
    # knob was renamed check_rep -> check_vma partway through — with both
    # spellings co-existing under jax.shard_map for some versions. Probe by
    # calling (TypeError = wrong spelling for this version), not by
    # attribute presence, so mid-era releases resolve correctly too.
    if hasattr(jax, "shard_map"):
        shard_map_fn = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as shard_map_fn
    shard_kw = dict(
        mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P("data"), P("data")),
    )
    try:
        shard_draw = shard_map_fn(draw, check_vma=False, **shard_kw)
    except TypeError:
        shard_draw = shard_map_fn(draw, check_rep=False, **shard_kw)

    def multi_step(state: TrainState, data, targets, rng):
        metrics = None
        for _ in range(num_steps):
            # state.step advances per inner step, so each draw key and each
            # inner step's dropout/augment fold are distinct; the extra
            # fold decorrelates the draw from the step's own rng uses.
            dkey = jax.random.fold_in(
                jax.random.fold_in(rng, state.step), 0x5A11
            )
            voxels, tgt = shard_draw(dkey, data, targets)
            state, metrics = step(
                state, {"voxels": voxels, target_key: tgt}, rng
            )
        return state, metrics

    return multi_step


def make_eval_step(
    model, task: str = "classify", packed: bool = False,
    serve_precision: str = "fp32",
) -> Callable:
    """Eval step returning *sums* (not means) so batches aggregate exactly.

    For segmentation it also returns per-class intersection/union counts so
    the host can compute mean IoU over the whole eval set (SURVEY.md §7.5).

    ``serve_precision`` (``Config.serve_precision``) applies the
    inference-side working-copy transform to the params INSIDE the
    compiled step — ``bf16`` casts the fp32 masters at the boundary,
    ``int8`` round-trips the per-channel quantizer — so held-out eval
    measures the forward serving will actually run, not an fp32 ideal of
    it. ``fp32`` compiles the identical step it always did.
    """

    def eval_step(params, batch_stats, batch):
        if serve_precision != "fp32":
            from featurenet_tpu.train.precision import serve_params_cast

            params = serve_params_cast(params, serve_precision)
        voxels = _batch_voxels(batch, packed)
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats},
            voxels,
            train=False,
        )
        # Per-sample validity mask: padding rows (from exact epoch passes
        # whose split doesn't divide the batch) contribute zero everywhere,
        # keeping the executable shape-monomorphic while the sums stay exact.
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(voxels.shape[0], jnp.float32)
        if task == "classify":
            pred = jnp.argmax(logits, axis=-1)
            # lint: allow-precision(eval exact sums accumulate fp32)
            hit = (pred == batch["label"]).astype(jnp.float32)
            correct = (hit * mask).sum()
            loss = (
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["label"]
                )
                * mask
            ).sum()
            n_cls = logits.shape[-1]
            # Confusion counts [true, pred] — reference C7's per-class
            # accuracy / confusion matrix (SURVEY.md §2), summed exactly.
            confusion = (
                jax.nn.one_hot(batch["label"], n_cls, dtype=jnp.float32)[
                    :, :, None
                ]
                * jax.nn.one_hot(pred, n_cls, dtype=jnp.float32)[:, None, :]
                * mask[:, None, None]
            ).sum(0)
            return {
                "correct": correct,
                "loss_sum": loss,
                "count": mask.sum(),
                "confusion": confusion,
            }
        seg = batch["seg"].astype(jnp.int32)
        pred = jnp.argmax(logits, axis=-1)
        n_cls = logits.shape[-1]
        vmask = mask[:, None, None, None]
        pred_1h = jax.nn.one_hot(pred, n_cls, dtype=jnp.float32) * vmask[..., None]
        true_1h = jax.nn.one_hot(seg, n_cls, dtype=jnp.float32) * vmask[..., None]
        axes = tuple(range(pred_1h.ndim - 1))
        inter = (pred_1h * true_1h).sum(axes)  # [C+1]
        union = pred_1h.sum(axes) + true_1h.sum(axes) - inter
        loss = (
            optax.softmax_cross_entropy_with_integer_labels(logits, seg)
            * vmask
        ).sum()
        voxels_per_sample = seg.shape[1] * seg.shape[2] * seg.shape[3]
        return {
            # lint: allow-precision(eval exact sums accumulate fp32)
            "correct": ((pred == seg).astype(jnp.float32) * vmask).sum(),
            "loss_sum": loss,
            "count": mask.sum() * voxels_per_sample,
            "intersection": inter,
            "union": union,
        }

    return eval_step


def aggregate_eval(metric_list: list[dict]) -> dict[str, float]:
    """Host-side exact aggregation of per-batch eval sums."""
    import numpy as np

    total = {}
    for m in metric_list:
        for k, v in m.items():
            # lint: allow-host-sync(eval epilogue: exact host aggregation)
            total[k] = total.get(k, 0) + np.asarray(v)
    out = {
        "accuracy": float(total["correct"] / total["count"]),
        "loss": float(total["loss_sum"] / total["count"]),
    }
    if "confusion" in total:
        # lint: allow-host-sync(already host-resident after the sum above)
        conf = np.asarray(total["confusion"])
        row = conf.sum(axis=1)
        per_class = np.where(row > 0, np.diag(conf) / np.maximum(row, 1), 0.0)
        seen = row > 0
        out["per_class_accuracy"] = per_class.round(4).tolist()
        out["mean_class_accuracy"] = float(
            per_class[seen].mean() if seen.any() else 0.0
        )
        out["confusion"] = conf.astype(int).tolist()
    if "intersection" in total:
        union = total["union"]
        present = union > 0  # ignore classes absent from both pred & truth
        iou = np.where(present, total["intersection"] / np.maximum(union, 1), 0.0)
        out["mean_iou"] = float(iou.sum() / np.maximum(present.sum(), 1))
        # Per-class IoU (index 0 = background) so the summary shows *which*
        # feature classes drag the mean, not just that something does.
        out["per_class_iou"] = [
            round(float(v), 4) if p else None
            for v, p in zip(iou, present)
        ]
    return out


def make_lr_schedule(
    peak_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=max(total_steps, warmup_steps + 1),
        end_value=peak_lr * 0.01,
    )


def make_optimizer(cfg) -> optax.GradientTransformation:
    sched = make_lr_schedule(cfg.peak_lr, cfg.warmup_steps, cfg.total_steps)
    if cfg.optimizer == "adamw":
        tx = optax.adamw(sched, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "adam":
        tx = optax.adam(sched)
    elif cfg.optimizer == "sgd":
        tx = optax.sgd(sched, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    clip = getattr(cfg, "grad_clip", 0.0) or 0.0
    if clip > 0.0:
        tx = optax.chain(optax.clip_by_global_norm(clip), tx)
    return tx
