"""Checkpoint/resume via Orbax (reference analog: rank-0 ``torch.save``).

Saves ``{step, params, batch_stats, opt_state}`` — the full resumable state —
asynchronously from host 0 while the device keeps training (SURVEY.md §5).
Restore rebuilds arrays onto their original shardings from the live state
template, so a resumed multi-chip run comes back already distributed.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time
from typing import Optional

import jax
import orbax.checkpoint as ocp

from featurenet_tpu import faults, obs
from featurenet_tpu.train.state import TrainState

# Run-config sidecar written into the checkpoint directory: the checkpoint's
# identity (task/resolution/arch) travels with the weights, so eval/infer
# self-configure instead of re-guessing flags (round-1 footgun class).
CONFIG_FILENAME = "config.json"


def load_run_config(directory: str):
    """The ``Config`` persisted with a run, or ``None`` for legacy dirs."""
    path = os.path.join(os.path.abspath(directory), CONFIG_FILENAME)
    if not os.path.exists(path):
        return None
    from featurenet_tpu.config import config_from_dict

    with open(path) as fh:
        return config_from_dict(json.load(fh))


class InjectedFaultMisfire(RuntimeError):
    """An injection site fired but could not apply its effect — a bug in
    the chaos layer itself, never swallowed."""


class ChecksumMismatch(RuntimeError):
    """A checkpoint's bytes no longer match the checksum sidecar written
    at save time — silent corruption (bit rot, a torn copy, a partial
    overwrite that kept the file sizes). Raised BEFORE Orbax touches the
    step, so the existing walk-back fallback treats it exactly like a
    truncated step: resume falls back to the previous retained step; an
    explicitly requested step propagates the error."""


# --- checkpoint content verification -----------------------------------------
# A checksum sidecar (`checksum.<step>.json` next to the step dirs) is
# written once the async save finalizes and verified on restore before
# Orbax reads a byte. Orbax's own failure mode is structural (missing /
# truncated files); the sidecar catches the silent kind — same-size
# corruption restores into structurally-valid garbage weights.

def _checksum_path(root: str, step: int) -> str:
    return os.path.join(root, f"checksum.{step}.json")


def _dir_checksums(step_dir: str) -> dict[str, str]:
    """relative path -> sha256 for every file under a finalized step dir."""
    import hashlib

    out: dict[str, str] = {}
    for dirpath, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(dirpath, name)
            h = hashlib.sha256()
            with open(path, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(path, step_dir)] = h.hexdigest()
    return out


def _step_dir(root: str, step: int) -> Optional[str]:
    """The on-disk directory Orbax keeps ``step`` in (naming varies with
    step_prefix/padding options across Orbax versions, so probe)."""
    cand = os.path.join(root, str(step))
    if os.path.isdir(cand):
        return cand
    for name in os.listdir(root):
        digits = "".join(ch for ch in name if ch.isdigit())
        full = os.path.join(root, name)
        if digits and int(digits) == step and os.path.isdir(full):
            return full
    return None


def _corrupt_step_dir(root: str, step: int) -> None:
    """Injected-fault effect: truncate every file of a finalized step dir
    (the on-disk shape of a crash mid-write / torn filesystem flush)."""
    target = _step_dir(root, step)
    if target is None:
        raise InjectedFaultMisfire(
            f"checkpoint_corrupt fired but no step dir for {step} in {root}"
        )
    for dirpath, _, files in os.walk(target):
        for f in files:
            path = os.path.join(dirpath, f)
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(size // 2)


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, config=None):
        self._dir = os.path.abspath(directory)
        self._config = config
        self._saves = 0
        self._restores = 0
        # Steps whose async save has been enqueued but whose checksum
        # sidecar is not yet written (it can only be computed once the
        # background write finalizes — see _flush_checksums).
        self._pending_sums: list[int] = []
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    def _flush_checksums(self) -> None:
        """Write the checksum sidecar for every finalized pending step and
        GC sidecars of steps Orbax has retired. Called after any
        wait_until_finished — never on the save critical path."""
        for step in self._pending_sums:
            target = _step_dir(self._dir, step)
            if target is None:
                continue  # already GC'd by retention
            try:
                with open(_checksum_path(self._dir, step), "w") as fh:
                    json.dump(_dir_checksums(target), fh)
            except OSError:
                pass  # sidecar is belt-and-suspenders, never load-bearing
        self._pending_sums = []
        try:
            kept = {int(s) for s in self._mgr.all_steps()}
            for name in os.listdir(self._dir):
                if name.startswith("checksum.") and name.endswith(".json"):
                    digits = name[len("checksum."):-len(".json")]
                    if digits.isdigit() and int(digits) not in kept:
                        os.unlink(os.path.join(self._dir, name))
        except OSError:
            pass

    def _verify_checksums(self, step: int) -> None:
        """Raise ``ChecksumMismatch`` when the step's bytes disagree with
        its sidecar; silently pass for legacy dirs without one."""
        path = _checksum_path(self._dir, step)
        if not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                expected = json.load(fh)
        except (OSError, ValueError):
            return  # unreadable sidecar: fall through to Orbax's own checks
        target = _step_dir(self._dir, step)
        if target is None:
            return
        actual = _dir_checksums(target)
        if actual != expected:
            bad = sorted(
                set(expected) ^ set(actual)
                | {k for k in expected
                   if actual.get(k) not in (None, expected[k])}
            )
            raise ChecksumMismatch(
                f"checkpoint step {step} fails content verification "
                f"({len(bad)} file(s) differ from the save-time sidecar, "
                f"e.g. {bad[:3]})"
            )

    def _write_config(self) -> None:
        if self._config is None or jax.process_index() != 0:
            return
        from featurenet_tpu.config import config_to_dict

        path = os.path.join(self._dir, CONFIG_FILENAME)
        tmp = path + ".tmp"  # atomic: a killed run must not leave half a file
        with open(tmp, "w") as fh:
            json.dump(config_to_dict(self._config), fh, indent=1, default=str)
        os.replace(tmp, path)
        self._config = None  # write once per manager

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else step
        if self._pending_sums:
            # The previous async save must finalize before its sidecar can
            # be computed (Orbax serializes consecutive saves anyway, so
            # this wait is not new latency on the step path).
            self._mgr.wait_until_finished()
            self._flush_checksums()
        self._write_config()
        payload = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        # Snapshot BEFORE the async write: train_step DONATES the state,
        # and on the CPU backend Orbax's background writer serializes
        # zero-copy numpy *views* of these very buffers — the next
        # dispatch then rewrites them under the writer and the committed
        # (and checksummed!) checkpoint holds another array's bytes.
        # Surfaced by the elastic shrink e2e, the first consumer to
        # restore a mid-run checkpoint written at full speed (every
        # earlier recovery path restored a drain/final save, after which
        # nothing donates). A device-side copy stays inside jax's
        # dataflow, so the donation is ordered after it; the copy's own
        # buffers are never donated, so the writer's views stay valid.
        import jax.numpy as jnp

        payload = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            payload,
        )
        # Async save: this span is the host-blocking enqueue only; the
        # background write's completion is bounded by checkpoint_wait.
        self._saves += 1
        with obs.span("checkpoint_save", step=step):
            if faults.maybe_fail("save_slow", save=self._saves):
                # Latency injection: a dragging filesystem/serialization
                # stretching the host-blocking half of the save — the span
                # wraps it, so the slowness lands attributed in the report
                # instead of as unexplained "other" time.
                time.sleep(faults.SLOW_SLEEP_S)
            self._mgr.save(step, args=ocp.args.StandardSave(payload))
        self._pending_sums.append(step)
        if faults.maybe_fail("checkpoint_corrupt", save=self._saves):
            # Wait for the async write to finalize, then truncate the step
            # dir — the on-disk shape of a crash landing mid-checkpoint.
            # The sidecar deliberately has NOT been written yet (pending
            # flush): a crash mid-write leaves no checksum either.
            self._mgr.wait_until_finished()
            _corrupt_step_dir(self._dir, step)
            self._pending_sums.remove(step)

    def restore(self, state: TrainState, step: Optional[int] = None,
                cleanup: bool = False) -> TrainState:
        """Restore into the shardings/dtypes of the live ``state`` template.

        Verify-on-restore with fallback: when ``step`` is None (resume from
        latest) and the latest retained step is truncated/corrupt — a crash
        landed mid-write, or the filesystem tore it — the restore walks
        back through the older retained steps instead of killing the run
        permanently, and emits a ``checkpoint_fallback`` event carrying
        both step numbers. An *explicitly requested* step never falls back:
        the caller named that step, silently handing back a different one
        would be worse than the error.

        ``cleanup``: also DELETE the newer steps that failed (the resumed
        trainer will re-save those step numbers and Orbax refuses an
        existing step). Only the resume-to-train caller
        (``Trainer.resume_if_available``) passes True — a read-only
        restore (eval, infer, ``restore_init`` warm start from a possibly
        shared/foreign directory) must never destroy another run's
        checkpoints on what might be a transient read error.
        """
        latest = step if step is not None else self._mgr.latest_step()
        if latest is None:
            raise FileNotFoundError("no checkpoint to restore")
        if step is not None:
            candidates = [int(step)]
        else:
            candidates = sorted(
                (int(s) for s in self._mgr.all_steps()), reverse=True
            ) or [int(latest)]
        template = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
        first_error: Optional[BaseException] = None
        for s in candidates:
            self._restores += 1
            try:
                if faults.maybe_fail("checkpoint_restore_error",
                                     restore=self._restores):
                    raise faults.InjectedFault(
                        f"checkpoint_restore_error at step {s}"
                    )
                # Content verification BEFORE Orbax reads a byte: silent
                # same-size corruption would otherwise restore into
                # structurally-valid garbage weights. A mismatch joins the
                # existing truncation fallback below.
                self._verify_checksums(s)
                with obs.span("checkpoint_restore", step=s):
                    restored = self._mgr.restore(
                        s, args=ocp.args.StandardRestore(abstract)
                    )
            except Exception as e:  # orbax raises various system errors
                if step is not None:
                    raise
                first_error = first_error or e
                print(json.dumps({
                    "checkpoint_warning": f"restore of step {s} failed "
                    f"({type(e).__name__}: {e}); trying the previous "
                    "retained step",
                }), file=sys.stderr)
                continue
            if s != candidates[0]:
                # Recovered on an older step. For the resume-to-train
                # caller, drop the corrupt newer steps (left in place
                # they'd collide when the resumed run saves those step
                # numbers again — Orbax refuses an existing step); either
                # way make the data loss visible — the event is what the
                # e2e chaos tests (and operators) key on, and the stderr
                # line survives even sink-less runs.
                if cleanup:
                    for bad in candidates[:candidates.index(s)]:
                        try:
                            self._mgr.delete(bad)
                        except Exception:
                            d = _step_dir(self._dir, bad)
                            if d:
                                shutil.rmtree(d, ignore_errors=True)
                        try:
                            os.unlink(_checksum_path(self._dir, bad))
                        except OSError:
                            pass
                obs.emit("checkpoint_fallback", from_step=candidates[0],
                         to_step=s, error=repr(first_error)[:300])
                print(json.dumps({
                    "checkpoint_fallback": {"from_step": candidates[0],
                                            "to_step": s},
                }), file=sys.stderr)
            return state.replace(**restored)
        raise RuntimeError(
            f"every retained checkpoint failed to restore "
            f"(steps {candidates})"
        ) from first_error

    def restore_init(
        self, state: TrainState, step: Optional[int] = None
    ) -> TrainState:
        """Warm-start restore: take params + batch_stats from the
        checkpoint but keep the live state's step (0) and fresh optimizer
        slots — fine-tune semantics (the robust64 recipe's warm-start arm,
        BASELINE.md round 5). Requires the live optimizer's state tree to
        match the saved run's (same optimizer family)."""
        restored = self.restore(state, step)
        return state.replace(
            params=restored.params, batch_stats=restored.batch_stats
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        with obs.span("checkpoint_wait"):
            self._mgr.wait_until_finished()
        self._flush_checksums()

    def close(self) -> None:
        # A save() + close() caller (no wait()) must not leave its last
        # step checksum-less: finalize the in-flight async save and flush
        # sidecars while the manager can still answer all_steps().
        if self._pending_sums:
            self._mgr.wait_until_finished()
            self._flush_checksums()
        self._mgr.close()
