"""Checkpoint/resume via Orbax (reference analog: rank-0 ``torch.save``).

Saves ``{step, params, batch_stats, opt_state}`` — the full resumable state —
asynchronously from host 0 while the device keeps training (SURVEY.md §5).
Restore rebuilds arrays onto their original shardings from the live state
template, so a resumed multi-chip run comes back already distributed.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from featurenet_tpu.train.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else step
        payload = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        self._mgr.save(step, args=ocp.args.StandardSave(payload))

    def restore(self, state: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore into the shardings/dtypes of the live ``state`` template."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        template = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        return state.replace(**restored)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
