"""Checkpoint/resume via Orbax (reference analog: rank-0 ``torch.save``).

Saves ``{step, params, batch_stats, opt_state}`` — the full resumable state —
asynchronously from host 0 while the device keeps training (SURVEY.md §5).
Restore rebuilds arrays onto their original shardings from the live state
template, so a resumed multi-chip run comes back already distributed.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from featurenet_tpu import obs
from featurenet_tpu.train.state import TrainState

# Run-config sidecar written into the checkpoint directory: the checkpoint's
# identity (task/resolution/arch) travels with the weights, so eval/infer
# self-configure instead of re-guessing flags (round-1 footgun class).
CONFIG_FILENAME = "config.json"


def load_run_config(directory: str):
    """The ``Config`` persisted with a run, or ``None`` for legacy dirs."""
    path = os.path.join(os.path.abspath(directory), CONFIG_FILENAME)
    if not os.path.exists(path):
        return None
    from featurenet_tpu.config import config_from_dict

    with open(path) as fh:
        return config_from_dict(json.load(fh))


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 3, config=None):
        self._dir = os.path.abspath(directory)
        self._config = config
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )

    def _write_config(self) -> None:
        if self._config is None or jax.process_index() != 0:
            return
        from featurenet_tpu.config import config_to_dict

        path = os.path.join(self._dir, CONFIG_FILENAME)
        tmp = path + ".tmp"  # atomic: a killed run must not leave half a file
        with open(tmp, "w") as fh:
            json.dump(config_to_dict(self._config), fh, indent=1, default=str)
        os.replace(tmp, path)
        self._config = None  # write once per manager

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        step = int(state.step) if step is None else step
        self._write_config()
        payload = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        # Async save: this span is the host-blocking enqueue only; the
        # background write's completion is bounded by checkpoint_wait.
        with obs.span("checkpoint_save", step=step):
            self._mgr.save(step, args=ocp.args.StandardSave(payload))

    def restore(self, state: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore into the shardings/dtypes of the live ``state`` template."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        template = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
        }
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
        with obs.span("checkpoint_restore", step=int(step)):
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        return state.replace(**restored)

    def restore_init(
        self, state: TrainState, step: Optional[int] = None
    ) -> TrainState:
        """Warm-start restore: take params + batch_stats from the
        checkpoint but keep the live state's step (0) and fresh optimizer
        slots — fine-tune semantics (the robust64 recipe's warm-start arm,
        BASELINE.md round 5). Requires the live optimizer's state tree to
        match the saved run's (same optimizer family)."""
        restored = self.restore(state, step)
        return state.replace(
            params=restored.params, batch_stats=restored.batch_stats
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def wait(self) -> None:
        with obs.span("checkpoint_wait"):
            self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
