"""Checkpoint/resume via Orbax (reference analog: rank-0 ``torch.save``).

Saves ``{step, params, batch_stats, opt_state}`` — the full resumable state —
asynchronously from host 0 while the device keeps training (SURVEY.md §5).
Restore rebuilds arrays onto their original shardings from the live state
template, so a resumed multi-chip run comes back already distributed.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import sys
import threading
import time
from typing import Optional

import jax
import orbax.checkpoint as ocp

from featurenet_tpu import faults, obs
from featurenet_tpu.train.state import TrainState

# Run-config sidecar written into the checkpoint directory: the checkpoint's
# identity (task/resolution/arch) travels with the weights, so eval/infer
# self-configure instead of re-guessing flags (round-1 footgun class).
CONFIG_FILENAME = "config.json"


def load_run_config(directory: str):
    """The ``Config`` persisted with a run, or ``None`` for legacy dirs."""
    path = os.path.join(os.path.abspath(directory), CONFIG_FILENAME)
    if not os.path.exists(path):
        return None
    from featurenet_tpu.config import config_from_dict

    with open(path) as fh:
        return config_from_dict(json.load(fh))


class InjectedFaultMisfire(RuntimeError):
    """An injection site fired but could not apply its effect — a bug in
    the chaos layer itself, never swallowed."""


class ChecksumMismatch(RuntimeError):
    """A checkpoint's bytes no longer match the checksum sidecar written
    at save time — silent corruption (bit rot, a torn copy, a partial
    overwrite that kept the file sizes). Raised BEFORE Orbax touches the
    step, so the existing walk-back fallback treats it exactly like a
    truncated step: resume falls back to the previous retained step; an
    explicitly requested step propagates the error."""


# --- checkpoint content verification -----------------------------------------
# A checksum sidecar (`checksum.<step>.json` next to the step dirs) is
# written once the async save finalizes and verified on restore before
# Orbax reads a byte. Orbax's own failure mode is structural (missing /
# truncated files); the sidecar catches the silent kind — same-size
# corruption restores into structurally-valid garbage weights.

def _checksum_path(root: str, step: int) -> str:
    return os.path.join(root, f"checksum.{step}.json")


def _dir_checksums(step_dir: str) -> dict[str, str]:
    """relative path -> sha256 for every file under a finalized step dir."""
    import hashlib

    out: dict[str, str] = {}
    for dirpath, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(dirpath, name)
            h = hashlib.sha256()
            with open(path, "rb") as fh:
                for chunk in iter(lambda: fh.read(1 << 20), b""):
                    h.update(chunk)
            out[os.path.relpath(path, step_dir)] = h.hexdigest()
    return out


def _step_dir(root: str, step: int) -> Optional[str]:
    """The on-disk directory Orbax keeps ``step`` in (naming varies with
    step_prefix/padding options across Orbax versions, so probe)."""
    cand = os.path.join(root, str(step))
    if os.path.isdir(cand):
        return cand
    for name in os.listdir(root):
        digits = "".join(ch for ch in name if ch.isdigit())
        full = os.path.join(root, name)
        if digits and int(digits) == step and os.path.isdir(full):
            return full
    return None


def _corrupt_step_dir(root: str, step: int) -> None:
    """Injected-fault effect: truncate every file of a finalized step dir
    (the on-disk shape of a crash mid-write / torn filesystem flush)."""
    target = _step_dir(root, step)
    if target is None:
        raise InjectedFaultMisfire(
            f"checkpoint_corrupt fired but no step dir for {step} in {root}"
        )
    for dirpath, _, files in os.walk(target):
        for f in files:
            path = os.path.join(dirpath, f)
            size = os.path.getsize(path)
            with open(path, "r+b") as fh:
                fh.truncate(size // 2)


class CheckpointManager:
    """Double-buffered async saves: ``save()`` is the host-blocking
    ENQUEUE only — a device-side snapshot of the state dropped into one
    of two slots — and a background writer thread drains the slots
    through Orbax (waiting out each async write, then landing that
    step's checksum sidecar). The enqueue therefore never waits on the
    previous async write; it blocks only when BOTH slots are full, which
    bounds snapshot HBM at two state copies in steady state (a blocked
    third save has already taken its own snapshot before the put
    backpressures, so the transient worst case is three). Proven against
    the
    ``save_slow@save`` fault site: the injected filesystem latency lands
    in the writer's ``checkpoint_write`` span while the ``checkpoint_
    save`` enqueue span stays bounded (tests/test_slo.py).

    **Multi-process worlds keep the previous lockstep enqueue.** Orbax's
    ``save()`` coordinates across hosts (a sync-global-devices barrier —
    a real collective over the mesh), and a collective launched from a
    side thread runs CONCURRENTLY with the training step's collectives
    on the main thread: two in-flight collectives with no cross-host
    ordering wedge the mesh. Observed, not theorized — the elastic
    shrink e2e's generation 0 hung to its stall verdict exactly this
    way. So with ``jax.process_count() > 1`` the save stays on the
    caller thread (Orbax's own async machinery still overlaps the write
    with training; only the wait-out-the-previous-write latency stays on
    the path, as before this change)."""

    # Total snapshots in flight: 2 = the one the writer is writing plus
    # one queued behind it — the next save's snapshot can be taken while
    # the previous write is still in flight, and a third save blocks
    # (backpressure) instead of pinning unbounded HBM. The queue's
    # capacity is SLOTS - 1 because the writer HOLDS its slot for the
    # whole write (it pops the item before writing; a maxsize of SLOTS
    # would quietly admit a third live snapshot).
    SNAPSHOT_SLOTS = 2

    def __init__(self, directory: str, keep: int = 3, config=None):
        self._dir = os.path.abspath(directory)
        self._config = config
        self._saves = 0
        self._restores = 0
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True
            ),
        )
        # The double-buffer: started lazily on the first save() so
        # restore-only managers (eval, infer, warm starts) never spawn a
        # thread. The writer owns every _mgr.save/wait_until_finished
        # after that point; the foreground only touches the manager again
        # once the queue is drained (wait/close join the queue first).
        self._queue: queue.Queue = queue.Queue(
            maxsize=self.SNAPSHOT_SLOTS - 1
        )
        self._writer: Optional[threading.Thread] = None
        self._writer_error: Optional[BaseException] = None
        # Lockstep-mode bookkeeping (multi-process worlds, see the class
        # docstring): steps whose async save is enqueued but whose
        # checksum sidecar awaits the write's finalization.
        self._pending_sync: list[int] = []

    def _ensure_writer(self) -> None:
        if self._writer is None:
            self._writer = threading.Thread(
                target=self._write_loop, name="ckpt-writer", daemon=True
            )
            self._writer.start()

    def _check_writer(self) -> None:
        """Surface a background write failure at the next foreground
        touch point (save/wait/close) — a failed write must never be
        silent, and never later than the next save decision."""
        err, self._writer_error = self._writer_error, None
        if err is not None:
            raise RuntimeError(
                "background checkpoint write failed"
            ) from err

    def _write_loop(self) -> None:
        """The background writer: one queued snapshot at a time through
        Orbax — enqueue the async write, wait it out, then land the
        step's checksum sidecar (or, for an injected checkpoint_corrupt,
        truncate the finalized step and leave NO sidecar, the on-disk
        shape of a crash mid-write)."""
        while True:
            item = self._queue.get()
            if item is None:
                self._queue.task_done()
                return
            step, payload, save_n, corrupt = item
            try:
                with obs.span("checkpoint_write", step=step):
                    if faults.maybe_fail("save_slow", save=save_n):
                        # Latency injection: a dragging filesystem /
                        # serialization in the BACKGROUND write — off the
                        # step path by construction now; the span proves
                        # where the slowness went.
                        time.sleep(faults.SLOW_SLEEP_S)
                    self._mgr.save(step, args=ocp.args.StandardSave(payload))
                    self._mgr.wait_until_finished()
                    if corrupt:
                        _corrupt_step_dir(self._dir, step)
                    else:
                        self._write_checksum(step)
                        self._gc_checksums()
            except BaseException as e:  # surfaced by _check_writer
                # Keep the FIRST failure: a later write failing with a
                # secondary error (the disk already full) must not bury
                # the root cause the operator needs.
                if self._writer_error is None:
                    self._writer_error = e
            finally:
                self._queue.task_done()

    def _drain(self) -> None:
        """Foreground barrier: every queued snapshot written and
        finalized (and, in lockstep mode, every pending sidecar landed).
        After this the Orbax manager is idle, so the caller may touch it
        directly."""
        if self._writer is not None:
            self._queue.join()
        self._mgr.wait_until_finished()  # no-op once the writer drained
        self._flush_sync()

    def _flush_sync(self) -> None:
        """Lockstep mode: sidecars for every pending finalized step."""
        for step in self._pending_sync:
            self._write_checksum(step)
        if self._pending_sync:
            self._gc_checksums()
        self._pending_sync = []

    def _write_checksum(self, step: int) -> None:
        """Checksum sidecar for one FINALIZED step (writer-side; never on
        the save critical path)."""
        target = _step_dir(self._dir, step)
        if target is None:
            return  # already GC'd by retention
        try:
            with open(_checksum_path(self._dir, step), "w") as fh:
                json.dump(_dir_checksums(target), fh)
        except OSError:
            pass  # sidecar is belt-and-suspenders, never load-bearing

    def _gc_checksums(self) -> None:
        """Prune sidecars of steps Orbax's retention has retired."""
        try:
            kept = {int(s) for s in self._mgr.all_steps()}
            for name in os.listdir(self._dir):
                if name.startswith("checksum.") and name.endswith(".json"):
                    digits = name[len("checksum."):-len(".json")]
                    if digits.isdigit() and int(digits) not in kept:
                        os.unlink(os.path.join(self._dir, name))
        except OSError:
            pass

    def _verify_checksums(self, step: int) -> None:
        """Raise ``ChecksumMismatch`` when the step's bytes disagree with
        its sidecar; silently pass for legacy dirs without one."""
        path = _checksum_path(self._dir, step)
        if not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                expected = json.load(fh)
        except (OSError, ValueError):
            return  # unreadable sidecar: fall through to Orbax's own checks
        target = _step_dir(self._dir, step)
        if target is None:
            return
        actual = _dir_checksums(target)
        if actual != expected:
            bad = sorted(
                set(expected) ^ set(actual)
                | {k for k in expected
                   if actual.get(k) not in (None, expected[k])}
            )
            raise ChecksumMismatch(
                f"checkpoint step {step} fails content verification "
                f"({len(bad)} file(s) differ from the save-time sidecar, "
                f"e.g. {bad[:3]})"
            )

    def _write_config(self) -> None:
        if self._config is None or jax.process_index() != 0:
            return
        from featurenet_tpu.config import config_to_dict

        path = os.path.join(self._dir, CONFIG_FILENAME)
        tmp = path + ".tmp"  # atomic: a killed run must not leave half a file
        with open(tmp, "w") as fh:
            json.dump(config_to_dict(self._config), fh, indent=1, default=str)
        os.replace(tmp, path)
        self._config = None  # write once per manager

    def save(self, state: TrainState, step: Optional[int] = None) -> None:
        """Enqueue an async save of ``state`` (the fp32 masters — what
        every precision mode persists). Host-blocking work: the config
        sidecar (once), a device-side snapshot, and a bounded slot
        enqueue. The Orbax write — including waiting out the PREVIOUS
        write — happens on the background writer, so this never sits on
        the step path while an earlier save is still flushing."""
        self._check_writer()
        step = int(state.step) if step is None else step
        self._write_config()
        payload = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            # Dynamic loss-scaling state (fp16_scaled — inert scalars
            # under the other policies): persisted so a resumed fp16 run
            # keeps its ADAPTED scale instead of re-learning it from
            # overflow, and round-trips untouched through a
            # cross-precision restore.
            "loss_scale": state.loss_scale,
            "good_steps": state.good_steps,
        }
        # Snapshot BEFORE the async write: train_step DONATES the state,
        # and on the CPU backend Orbax's background writer serializes
        # zero-copy numpy *views* of these very buffers — the next
        # dispatch then rewrites them under the writer and the committed
        # (and checksummed!) checkpoint holds another array's bytes.
        # Surfaced by the elastic shrink e2e, the first consumer to
        # restore a mid-run checkpoint written at full speed (every
        # earlier recovery path restored a drain/final save, after which
        # nothing donates). A device-side copy stays inside jax's
        # dataflow, so the donation is ordered after it; the copy's own
        # buffers are never donated, so the writer's views stay valid.
        # The snapshot is also what makes the double-buffer sound: each
        # queued slot owns its own device buffers, independent of the
        # live state AND of the other slot.
        import jax.numpy as jnp

        payload = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            payload,
        )
        self._saves += 1
        # The corrupt decision is taken HERE (deterministic counter
        # order) but applied once the write finalizes — truncating the
        # step dir and skipping its sidecar, the on-disk shape of a
        # crash landing mid-checkpoint.
        corrupt = faults.maybe_fail("checkpoint_corrupt", save=self._saves)
        if jax.process_count() > 1:
            # Lockstep mode (see the class docstring): Orbax's save
            # coordination is a cross-host collective and must stay on
            # the thread that runs the training collectives.
            self._save_lockstep(step, payload, self._saves, corrupt)
            return
        self._ensure_writer()
        with obs.span("checkpoint_save", step=step):
            # The bounded enqueue: blocks ONLY when both snapshot slots
            # are still in flight (backpressure beats unbounded HBM).
            self._queue.put((step, payload, self._saves, corrupt))

    def _save_lockstep(self, step: int, payload, save_n: int,
                       corrupt: bool) -> None:
        """The multi-process save path — the pre-double-buffer behavior:
        wait out the previous async write (Orbax serializes consecutive
        saves anyway), enqueue on the caller thread, sidecars flushed at
        the next finalization point."""
        if self._pending_sync:
            self._mgr.wait_until_finished()
            self._flush_sync()
        with obs.span("checkpoint_save", step=step):
            if faults.maybe_fail("save_slow", save=save_n):
                # In lockstep mode the latency injection lands where the
                # latency itself does: on the save path, attributed.
                time.sleep(faults.SLOW_SLEEP_S)
            self._mgr.save(step, args=ocp.args.StandardSave(payload))
        if corrupt:
            self._mgr.wait_until_finished()
            _corrupt_step_dir(self._dir, step)
        else:
            self._pending_sync.append(step)

    def restore(self, state: TrainState, step: Optional[int] = None,
                cleanup: bool = False) -> TrainState:
        """Restore into the shardings/dtypes of the live ``state`` template.

        Verify-on-restore with fallback: when ``step`` is None (resume from
        latest) and the latest retained step is truncated/corrupt — a crash
        landed mid-write, or the filesystem tore it — the restore walks
        back through the older retained steps instead of killing the run
        permanently, and emits a ``checkpoint_fallback`` event carrying
        both step numbers. An *explicitly requested* step never falls back:
        the caller named that step, silently handing back a different one
        would be worse than the error.

        ``cleanup``: also DELETE the newer steps that failed (the resumed
        trainer will re-save those step numbers and Orbax refuses an
        existing step). Only the resume-to-train caller
        (``Trainer.resume_if_available``) passes True — a read-only
        restore (eval, infer, ``restore_init`` warm start from a possibly
        shared/foreign directory) must never destroy another run's
        checkpoints on what might be a transient read error.
        """
        # A restore through a manager with writes still in flight must
        # see them finalized (no-op for the usual restore-only manager).
        self._drain()
        self._check_writer()
        latest = step if step is not None else self._mgr.latest_step()
        if latest is None:
            raise FileNotFoundError("no checkpoint to restore")
        if step is not None:
            candidates = [int(step)]
        else:
            candidates = sorted(
                (int(s) for s in self._mgr.all_steps()), reverse=True
            ) or [int(latest)]
        template = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": state.opt_state,
            "loss_scale": state.loss_scale,
            "good_steps": state.good_steps,
        }
        abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
        # Checkpoints written before the loss-scale state existed carry
        # only the first four keys; restore them against the narrower
        # template (the live state's inert scale leaves stand in). The
        # legacy shape is detected, not probed-by-failure, so a corrupt
        # new-shape dir still walks back instead of half-restoring.
        legacy_abstract = {
            k: abstract[k]
            for k in ("step", "params", "batch_stats", "opt_state")
        }
        first_error: Optional[BaseException] = None
        for s in candidates:
            self._restores += 1
            try:
                if faults.maybe_fail("checkpoint_restore_error",
                                     restore=self._restores):
                    raise faults.InjectedFault(
                        f"checkpoint_restore_error at step {s}"
                    )
                # Content verification BEFORE Orbax reads a byte: silent
                # same-size corruption would otherwise restore into
                # structurally-valid garbage weights. A mismatch joins the
                # existing truncation fallback below.
                self._verify_checksums(s)
                target = abstract
                try:
                    md = self._mgr.item_metadata(s)
                    if (hasattr(md, "keys")
                            and "loss_scale" not in md.keys()):
                        target = legacy_abstract
                except Exception:
                    pass  # undecidable metadata: restore the full shape
                with obs.span("checkpoint_restore", step=s):
                    restored = self._mgr.restore(
                        s, args=ocp.args.StandardRestore(target)
                    )
            except Exception as e:  # orbax raises various system errors
                if step is not None:
                    raise
                first_error = first_error or e
                print(json.dumps({
                    "checkpoint_warning": f"restore of step {s} failed "
                    f"({type(e).__name__}: {e}); trying the previous "
                    "retained step",
                }), file=sys.stderr)
                continue
            if s != candidates[0]:
                # Recovered on an older step. For the resume-to-train
                # caller, drop the corrupt newer steps (left in place
                # they'd collide when the resumed run saves those step
                # numbers again — Orbax refuses an existing step); either
                # way make the data loss visible — the event is what the
                # e2e chaos tests (and operators) key on, and the stderr
                # line survives even sink-less runs.
                if cleanup:
                    for bad in candidates[:candidates.index(s)]:
                        try:
                            self._mgr.delete(bad)
                        except Exception:
                            d = _step_dir(self._dir, bad)
                            if d:
                                shutil.rmtree(d, ignore_errors=True)
                        try:
                            os.unlink(_checksum_path(self._dir, bad))
                        except OSError:
                            pass
                obs.emit("checkpoint_fallback", from_step=candidates[0],
                         to_step=s, error=repr(first_error)[:300])
                print(json.dumps({
                    "checkpoint_fallback": {"from_step": candidates[0],
                                            "to_step": s},
                }), file=sys.stderr)
            return state.replace(**restored)
        raise RuntimeError(
            f"every retained checkpoint failed to restore "
            f"(steps {candidates})"
        ) from first_error

    def restore_init(
        self, state: TrainState, step: Optional[int] = None
    ) -> TrainState:
        """Warm-start restore: take params + batch_stats from the
        checkpoint but keep the live state's step (0) and fresh optimizer
        slots — fine-tune semantics (the robust64 recipe's warm-start arm,
        BASELINE.md round 5). Requires the live optimizer's state tree to
        match the saved run's (same optimizer family)."""
        restored = self.restore(state, step)
        return state.replace(
            params=restored.params, batch_stats=restored.batch_stats
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def verify(self, step: Optional[int] = None) -> Optional[int]:
        """Content-verify one step (default: latest) against its
        save-time checksum sidecar WITHOUT restoring it — the rollout
        hot-swap's admission check: a candidate that fails here is
        refused before any weights move. Raises ``ChecksumMismatch`` on
        disagreement; returns the verified step (None when the directory
        holds no finalized step). Legacy dirs without a sidecar pass, as
        on restore."""
        s = step if step is not None else self._mgr.latest_step()
        if s is None:
            return None
        self._verify_checksums(int(s))
        return int(s)

    def wait(self) -> None:
        with obs.span("checkpoint_wait"):
            self._drain()
        self._gc_checksums()
        self._check_writer()

    def close(self) -> None:
        # A save() + close() caller (no wait()) must not leave its last
        # step checksum-less: drain the writer (which lands sidecars per
        # finalized step) while the manager can still answer all_steps().
        self._drain()
        if self._writer is not None:
            self._queue.put(None)  # writer exits after the sentinel
            self._writer.join(timeout=30.0)
            self._writer = None
        self._gc_checksums()
        self._mgr.close()
        self._check_writer()
