"""Training: state, jitted steps, checkpointing, and the run loop.

Reference parity: `train.py`'s epoch loop — forward, cross-entropy, backward,
step, periodic eval and ``torch.save`` (SURVEY.md §3.1). Rebuilt as: one
jit-compiled SPMD train step (loss, grads, optimizer update, BN stat update,
and every collective fused into a single XLA executable), a host loop that
only feeds batches and reads metrics, and Orbax for checkpoint/resume.
"""

from featurenet_tpu.train.state import TrainState, create_state
from featurenet_tpu.train.steps import make_eval_step, make_train_step
from featurenet_tpu.train.loop import Trainer

__all__ = [
    "TrainState",
    "create_state",
    "make_train_step",
    "make_eval_step",
    "Trainer",
]
