"""The shared per-slot heartbeat/stall state machine (ROADMAP item 3's
fold-the-duplicate follow-on).

Both watchers — ``train.supervisor.supervise`` (one child) and
``elastic.ElasticCoordinator`` (one monitor per host slot) — used to
carry this logic shape-for-shape: the fresh-baseline touch before each
spawn, the deleted-file recreate, the first-beat-vs-grace split, and the
re-read-before-verdict double check. A fix landing in one copy could
silently miss the other; this module is the single implementation both
drive.

The protocol (unchanged from the supervisor's original):

- ``reset()`` touches the file and records its mtime as the BASELINE:
  only a *strictly newer* mtime proves the watched child itself beat, so
  the cold-start grace window (compile can dwarf a step) governs until
  the first beat.
- ``poll()`` returns this instant's verdict — ``"ok"``, or ``"stall"``
  when the child never beat within ``grace_s``, or beat and then went
  silent past ``stall_timeout_s``. Before a stale-age verdict the mtime
  is RE-READ: a beat can land between the sample and the verdict (slow
  poll iteration, laggy shared-filesystem mtime), and a SIGKILL on a
  live, progressing child costs a full restart for nothing.
- A deleted heartbeat file (an external /tmp cleaner on a multi-day run)
  is recreated with the baseline reset rather than raised — a dead
  watcher orphans the detached child it was guarding — and first-beat
  detection stays honest against the fresh baseline.
- ``recheck()`` is the final sweep after the child exits (and, for the
  coordinator, before generation-wide kills freeze the mtimes): the last
  beat may have landed inside the last poll window, and classifying a
  crash-seconds-after-real-progress as a startup failure would hand it
  the permanent-failure verdict.

Stdlib-only, like both of its drivers.
"""

from __future__ import annotations

import os
import time


def touch_heartbeat(path: str) -> None:
    """Create-or-touch the liveness file (both halves of the heartbeat
    protocol use this: the trainer to beat, a watcher to reset the
    baseline before each spawn)."""
    with open(path, "a"):
        os.utime(path, None)


class HeartbeatMonitor:
    """One watched heartbeat file's liveness state.

    ``beaten`` is sticky: once the child has proven liveness, a later
    quiet spell is judged against ``stall_timeout_s``, never against the
    startup grace again. ``age_s`` holds the heartbeat age observed at
    the most recent ``poll()`` — the number a stall verdict logs.
    """

    def __init__(self, path: str, stall_timeout_s: float, grace_s: float):
        self.path = path
        self.stall_timeout_s = stall_timeout_s
        self.grace_s = grace_s
        self.beaten = False
        self.age_s = 0.0
        self._base = 0.0
        self._started = 0.0

    def reset(self) -> None:
        """Fresh baseline for a new spawn: a stale file from the previous
        child must neither trigger nor mask a stall verdict for this
        one."""
        touch_heartbeat(self.path)
        self._base = os.path.getmtime(self.path)
        self._started = time.monotonic()
        self.beaten = False
        self.age_s = 0.0

    def _mtime(self) -> float:
        try:
            return os.path.getmtime(self.path)
        except OSError:
            # Deleted externally: recreate rather than crash (a dead
            # watcher leaves the detached child running unsupervised).
            # Resetting the baseline keeps first-beat detection honest;
            # the stall clock restarts from the fresh touch.
            touch_heartbeat(self.path)
            self._base = os.path.getmtime(self.path)
            return self._base

    def poll(self) -> str:
        """One watcher-poll verdict: ``"ok"`` or ``"stall"``."""
        mtime = self._mtime()
        if mtime > self._base:
            self.beaten = True
        # lint: allow-wall-clock(file mtimes are epoch-based)
        age = time.time() - mtime
        if not self.beaten:
            if time.monotonic() - self._started > self.grace_s:
                self.age_s = age
                return "stall"  # never came up at all
        elif age > self.stall_timeout_s:
            # Re-read immediately before the verdict (see module doc).
            try:
                # lint: allow-wall-clock(file mtimes are epoch-based)
                age = time.time() - os.path.getmtime(self.path)
            except OSError:
                pass
            if age > self.stall_timeout_s:
                self.age_s = age
                return "stall"
        self.age_s = age
        return "ok"

    def recheck(self) -> bool:
        """Final beat sweep after the child exited: returns (and records)
        whether the child ever beat — the startup-vs-run-failure
        discriminator."""
        try:
            if os.path.getmtime(self.path) > self._base:
                self.beaten = True
        except OSError:
            pass
        return self.beaten
