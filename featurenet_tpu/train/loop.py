"""The Trainer: config → mesh → sharded state → compiled step → run loop.

Reference parity: ``train.py`` ``main()`` (SURVEY.md §3.1), redesigned:

- One ``jit`` with explicit in/out shardings replaces DDP + NCCL; the host
  loop below contains no collectives, no gradient handling, no device code.
- State buffers are donated: each step updates params/opt-state in place in
  HBM — no per-step reallocation.
- Input is a seeded, threaded, host-sharded prefetcher (``data`` package);
  batches land in HBM under the step's input sharding before the step needs
  them, overlapping generation with compute.
- Init is jitted **with output shardings**, so a tensor-parallel run
  materializes each kernel shard directly on its device — no host-side full
  copy of the model ever exists.
"""

from __future__ import annotations

import collections
import os
import signal
import time
from typing import Optional

import jax
import numpy as np

from featurenet_tpu import faults, obs
from featurenet_tpu.config import Config
from featurenet_tpu.obs import perf as obs_perf
from featurenet_tpu.data.dataset import (
    SyntheticVoxelDataset,
    prefetch_to_device,
    put_batch,
)
from featurenet_tpu.parallel.mesh import feed_shards
# build_model lives in the runtime registry now (the single source shared
# by Trainer, Predictor, and every registry program); re-exported here for
# the callers that import it from the loop (seg_diagnose, older tests).
from featurenet_tpu.runtime.registry import Runtime, build_model  # noqa: F401
from featurenet_tpu.train.checkpoint import CheckpointManager
from featurenet_tpu.train.state import TrainState, param_count
from featurenet_tpu.train.steps import aggregate_eval
from featurenet_tpu.utils.logging import MetricLogger


class Trainer:
    def __init__(self, cfg: Config, mesh=None, spatial: Optional[bool] = None):
        self.cfg = cfg.validate()
        # Run-scoped event log (featurenet_tpu.obs): installed first so
        # every later warning/span of this construction is captured. Every
        # host initializes its own stream (host 0 keeps events.jsonl and
        # owns run.json; host i writes events.<i>.jsonl) — the report
        # layer merges them, so a multi-process run's data-wait is visible
        # per host instead of host 0's view standing in for the mesh.
        if self.cfg.run_dir:
            from featurenet_tpu.config import config_to_dict

            obs.init_run(self.cfg.run_dir,
                         config=config_to_dict(self.cfg),
                         process_index=jax.process_index())
            # Live SLO layer (obs.windows/alerts): rolling windows of step
            # time / data-wait / queue depth / heartbeat age / serving
            # latency with this run's alert rules; replaces init_run's
            # default-rule aggregator. Every sample is a host-side float
            # the instrumentation already had — no host-sync cost.
            from featurenet_tpu.obs import alerts, windows

            windows.install(windows.WindowAggregator(
                rules=alerts.parse_rules(self.cfg.alert_rules)
            ))
        # Chaos plan (featurenet_tpu.faults): installed before any layer
        # that hosts an injection site runs. One-shot markers go to the
        # run_dir (shared across a supervised run's respawns) so a fault
        # fires once per RUN, not once per process.
        if self.cfg.inject_faults:
            faults.install(
                self.cfg.inject_faults,
                state_dir=self.cfg.run_dir or self.cfg.checkpoint_dir,
            )
        # The runtime registry (featurenet_tpu.runtime): mesh, shardings,
        # and every compiled program this run dispatches — enumerable,
        # rebuildable, and (with Config.exec_cache_dir) served from the
        # persistent AOT executable cache so a supervisor respawn or
        # preemption resume skips recompilation.
        self.rt = Runtime(cfg, mesh=mesh, spatial=spatial)
        self.mesh = self.rt.mesh
        self.spatial = self.rt.spatial
        self.model = self.rt.model
        self.tx = self.rt.tx
        # Performance attribution (obs.perf): the device-kind peak row
        # (explicit `unknown` tier on CPU — no MFU sample is ever
        # synthesized from a missing peak) and the cost counters of the
        # last dispatched program, folded against measured step wall in
        # run()'s loop.
        self._peaks = obs_perf.local_device_peaks()
        self._last_cost: Optional[dict] = None
        # TB events from host 0 only (multi-host runs would double-write).
        self.logger = MetricLogger(
            tb_dir=cfg.tb_dir if jax.process_index() == 0 else None
        )

        n_data = self.mesh.shape["data"]
        if cfg.global_batch % (n_data or 1):
            raise ValueError(
                f"global_batch {cfg.global_batch} must be a multiple of the "
                f"data mesh axis size {n_data}"
            )

        # --- sharded init ---------------------------------------------------
        # The sample batch is created *inside* the traced init so it is shape
        # metadata only — never a host constant baked into the executable;
        # init is a registry program, so a tensor-parallel run materializes
        # each kernel shard directly on its device.
        self.state_sh = self.rt.state_sh
        self.state: TrainState = self.rt.build("init")(jax.random.key(cfg.seed))
        self.params_n = param_count(self.state.params)

        # Warm start (fine-tune semantics): params + batch_stats from an
        # existing checkpoint, step 0 and fresh optimizer slots. A resume
        # from checkpoint_dir still wins (resume_if_available overwrites),
        # so supervised fine-tune runs restart correctly.
        if cfg.init_from:
            from featurenet_tpu.train.checkpoint import (
                CheckpointManager as _CM,
                load_run_config,
            )

            saved = load_run_config(cfg.init_from)
            if saved is not None:
                from featurenet_tpu.config import check_identity

                check_identity(saved, cfg)
            src = _CM(cfg.init_from)
            self.state = src.restore_init(self.state)
            src.close()

        # --- compiled steps (runtime registry programs) ---------------------
        # Wire format: voxels travel bit-packed for both tasks (unpacked on
        # device inside the step); classify drops the per-voxel target,
        # segment ships int8 seg. Host→device bandwidth is the input
        # pipeline's scarce resource — 32x less of it than float32 batches.
        # Sharding/donation decisions live in the registry's ProgramSpecs,
        # so the bench and the Trainer can never compile different programs
        # under one name.
        self.batch_sh = self.rt.batch_sh
        rep = self.rt.rep
        # Cache-backed classification augments on device (rotations inside
        # the compiled step); the host dataset then skips its rotation pass.
        self._device_aug = cfg.device_augment
        # Train/eval programs build LAZILY on first dispatch (_program):
        # an eval-only Trainer (the `eval` CLI, recalibration) must not
        # pay a train-step compile, and a training run compiles its first
        # step exactly when the old inline jit would have. Serving is the
        # opposite tradeoff — the Predictor builds at construction, since
        # startup-before-traffic is the warmup. With Config.exec_cache_dir
        # set, either way lands on the persistent executable cache.
        self._programs: dict[tuple, object] = {}
        # Pipelined dispatch: k steps fused into one executable; the host
        # dispatches once per k optimizer updates (bitwise-identical math,
        # see make_multi_train_step). The single-step program stays for
        # segment remainders (total % k) and as the k=1 path. The
        # requested k is clamped against the analytic HBM byte model
        # (Runtime.dispatch_k / ops/membytes.py) — degrade with a warning,
        # never crash, never silently under-dispatch; an explicit CLI
        # request (clamp_dispatch_k=False) is honored with the OOM-risk
        # warning (advisor r5).
        self._k = self.rt.dispatch_k(self.params_n)
        # Computed under jit with an output sharding (not device_put): a
        # multi-process mesh's replicated sharding spans non-addressable
        # devices, which device_put refuses but GSPMD computation handles.
        self._step_rng = jax.jit(
            lambda: jax.random.key(cfg.seed + 1), out_shardings=rep
        )()

        # --- data -----------------------------------------------------------
        # Each host generates exactly the data-row group its devices touch
        # (the DistributedSampler analog); put_batch then assembles the
        # globally-sharded array from per-host blocks. feed_shards — not
        # (process_count, process_index) — because with the model axis
        # spanning processes several hosts share one row group and must
        # feed identical rows (parallel.mesh.feed_shards).
        n_hosts, host_id = feed_shards(self.mesh)
        self._feed = (n_hosts, host_id)
        if cfg.data_cache and cfg.task == "segment":
            from featurenet_tpu.data.offline import SegCacheDataset

            common = dict(
                global_batch=cfg.global_batch,
                test_fraction=cfg.test_fraction,
                num_hosts=n_hosts,
                host_id=host_id,
            )
            self.train_data = SegCacheDataset(
                cfg.data_cache, split="train", seed=cfg.seed,
                augment=cfg.augment, **common,
            )
            self.eval_data = SegCacheDataset(
                cfg.data_cache, split="test", seed=cfg.seed + 10_000, **common,
            )
        elif cfg.data_cache:
            from featurenet_tpu.data.offline import VoxelCacheDataset

            self.train_data = VoxelCacheDataset(
                cfg.data_cache,
                global_batch=cfg.global_batch,
                split="train",
                test_fraction=cfg.test_fraction,
                num_hosts=n_hosts,
                host_id=host_id,
                seed=cfg.seed,
                augment=cfg.augment and not self._device_aug,
            )
            # Held-out split, evaluated as full deterministic epoch passes.
            self.eval_data = VoxelCacheDataset(
                cfg.data_cache,
                global_batch=cfg.global_batch,
                split="test",
                test_fraction=cfg.test_fraction,
                num_hosts=n_hosts,
                host_id=host_id,
                seed=cfg.seed + 10_000,
            )
            # A label the head can't express would train/evaluate silently
            # wrong (one_hot of an out-of-range id is all-zero; integer CE
            # clamps) — refuse up front.
            max_label = int(
                max(self.train_data.labels.max(), self.eval_data.labels.max())
            )
            if max_label >= cfg.arch.num_classes:
                raise ValueError(
                    f"cache {cfg.data_cache!r} contains label id {max_label} "
                    f"but the model head has num_classes="
                    f"{cfg.arch.num_classes}; non-canonical class dirs need "
                    "a config with a larger head (see build_cache docs)"
                )
        else:
            self.train_data = SyntheticVoxelDataset(
                resolution=cfg.resolution,
                global_batch=cfg.global_batch,
                num_hosts=n_hosts,
                host_id=host_id,
                num_features=cfg.num_features,
                seed=cfg.seed,
                task=cfg.task,
            )
            self.eval_data = SyntheticVoxelDataset(
                resolution=cfg.resolution,
                global_batch=cfg.global_batch,
                num_hosts=n_hosts,
                host_id=host_id,
                num_features=cfg.num_features,
                seed=cfg.seed + 10_000,
                task=cfg.task,
            )

        # --- device-resident dataset (HBM) mode -----------------------------
        # Upload the packed train split once, sharded P('data') along rows;
        # train steps then draw batches on device (zero per-step input
        # traffic — see make_hbm_multi_train_step). The host stream above
        # still exists for eval's exact epoch passes.
        self._hbm = bool(cfg.hbm_cache)
        if self._hbm:
            from jax.sharding import NamedSharding, PartitionSpec as P

            blk_vox, blk_tgt, n_keep = self.train_data.materialize_split(
                multiple_of=self.mesh.shape["data"],
                num_shards=n_hosts,
                shard_id=host_id,
            )
            if cfg.task != "segment":
                blk_tgt = blk_tgt.astype(np.int32)
            d_sh = NamedSharding(self.mesh, P("data"))
            if jax.process_count() == 1:
                self._hbm_data = jax.device_put(blk_vox, d_sh)
                self._hbm_labels = jax.device_put(blk_tgt, d_sh)
            else:
                self._hbm_data = jax.make_array_from_process_local_data(
                    d_sh, blk_vox
                )
                self._hbm_labels = jax.make_array_from_process_local_data(
                    d_sh, blk_tgt
                )

            # Augmentation in HBM mode is necessarily in-step (there is no
            # host pass): classify rotates voxels, segment rotates
            # voxels+seg jointly. cfg.device_augment is the single source
            # of truth and covers the hbm_cache case. The resident arrays
            # carry the program's shapes, so the registry build takes them
            # explicitly (an index estimate could round differently).
            self._hbm_step_k = self.rt.build(
                "hbm_train_step", num_steps=self._k,
                data=self._hbm_data, targets=self._hbm_labels,
            )
            # Remainder dispatches (total % k, segment cuts) run one step.
            self._hbm_step_1 = (
                self.rt.build(
                    "hbm_train_step", num_steps=1,
                    data=self._hbm_data, targets=self._hbm_labels,
                ) if self._k > 1 else self._hbm_step_k
            )
            self.logger.log(0, {
                "hbm_resident_rows": float(n_keep),
                "hbm_resident_mb": round(
                    (blk_vox.nbytes * n_hosts) / 1e6, 1
                ),
            }, prefix="setup")

        self.ckpt: Optional[CheckpointManager] = None
        if cfg.checkpoint_dir:
            self.ckpt = CheckpointManager(
                cfg.checkpoint_dir, cfg.keep_checkpoints, config=cfg
            )

    def _heartbeat(self) -> None:
        """Record confirmed progress for an external supervisor.

        Called only after evidence the *device* is advancing (a completed
        readback / eval / checkpoint) — never on mere dispatch, which
        succeeds even when the backend is hung. Deliberately NOT at loop
        entry either: the first beat arms the supervisor's stall clock,
        and before the first train-step compile only the (longer) grace
        window may govern. The elastic coordinator tells a pre-first-beat
        host loss from a startup failure by whether it had to kill live
        peers, not by beats.
        """
        # perf_counter, not time.time(): the inter-beat age is a process-
        # local interval and must not jump when NTP steps the wall clock
        # (the hygiene lint flags wall-clock subtraction for this reason).
        now = time.perf_counter()
        last = getattr(self, "_last_beat", None)
        obs.emit("heartbeat",
                 age_s=round(now - last, 3) if last is not None else None)
        if last is not None:
            # SLO window: inter-beat age trend — the live precursor of
            # the supervisor's stall verdict.
            obs.observe("heartbeat_age_s", round(now - last, 3))
        self._last_beat = now
        if self.cfg.poll_device_memory:
            # Opt-in device-memory watermark (obs.perf): sampled here —
            # the heartbeat cadence — because every beat already sits off
            # the dispatch hot path (a completed readback/eval/
            # checkpoint). Degrades silently to no events on backends
            # without memory_stats (CPU).
            obs_perf.sample_device_memory()
        if self.cfg.heartbeat_file:
            from featurenet_tpu.train.supervisor import touch_heartbeat

            touch_heartbeat(self.cfg.heartbeat_file)

    def _program(self, name: str, **kw):
        """The Trainer's lazily-built registry programs, one build per
        (name, kwargs) (Runtime.build → lower → compile or
        executable-cache load) — a later call with different kwargs (e.g.
        another fusion width) builds its own executable instead of
        silently reusing the first one's."""
        key = (name, tuple(sorted(kw.items())))
        if key not in self._programs:
            self._programs[key] = self.rt.build(name, **kw)
        return self._programs[key]

    # ------------------------------------------------------------------
    def dispatch_group(self, stream, take: int):
        """Dispatch ``take`` train steps as one compiled call and return the
        (device-resident) metrics of the last step.

        The single source of dispatch truth: the run loop and the e2e
        benchmark (``benchmark.measure_e2e``) both go through here, so what
        the benchmark times is by construction what training executes.
        ``stream`` is the prefetched batch iterator (unused — may be None —
        in HBM-resident mode); ``take`` must be ``self._k`` or 1 (the
        remainder path).
        """
        if self._hbm:
            fn = self._hbm_step_k if take == self._k else self._hbm_step_1
            with obs.span("dispatch", take=take, mode="hbm"):
                self.state, metrics = fn(
                    self.state, self._hbm_data, self._hbm_labels,
                    self._step_rng,
                )
        elif take > 1:
            # data_wait is the host blocking on the prefetcher (starved
            # input pipeline); dispatch is the enqueue of the fused
            # executable — actual device time surfaces at the readback.
            with obs.span("data_wait", take=take):
                batches = tuple(next(stream) for _ in range(take))
            fn = self._program("multi_train_step", num_steps=self._k)
            with obs.span("dispatch", take=take):
                self.state, metrics = fn(self.state, batches, self._step_rng)
        else:
            with obs.span("data_wait", take=1):
                batch = next(stream)
            fn = self._program("train_step")
            with obs.span("dispatch", take=1):
                self.state, metrics = fn(self.state, batch, self._step_rng)
        # The dispatched program's compiled counters (obs.perf): the fused
        # program's flops already cover its whole dispatch group, so the
        # MFU fold in run() divides by the group wall, not per step.
        self._last_cost = getattr(fn, "cost", None)
        return metrics

    def recalibrate_bn(self, batches: int = 64) -> None:
        """Re-estimate BatchNorm running statistics over CLEAN training
        batches (train-mode forwards, no optimizer — only batch_stats
        move).

        Mixed-distribution training (clean/affine batch mixing,
        ``augment_affine_prob < 1``) leaves the BN running stats blended
        over the mix; eval/serving on the clean modality then pays an
        eval-only accuracy tax — the same mechanism the round-4 recipe
        study identified during high-lr phases (BASELINE.md). The host
        stream used here is guaranteed UN-augmented: when this Trainer's
        host data path applies augmentation in its workers (streamed
        segment, host-augmented classify), a clean shallow clone of the
        dataset feeds this pass instead — so API callers get the same
        clean-stream guarantee the CLI ``recalibrate`` command enforces
        by rebuilding the config (advisor r5). Device augmentation lives
        inside the train step, which this never calls.
        """
        from featurenet_tpu.parallel.mesh import replicated as _rep
        from featurenet_tpu.train.steps import _batch_voxels

        data = self.train_data
        if getattr(data, "augment", False):
            # Cache datasets read self.augment per gather; a shallow copy
            # shares the mmapped shards and costs nothing.
            import copy

            data = copy.copy(data)
            data.augment = False

        def fwd(params, stats, batch, rng):
            _, mutated = self.model.apply(
                {"params": params, "batch_stats": stats},
                _batch_voxels(batch, True),
                train=True,
                rngs={"dropout": rng},
                mutable=["batch_stats"],
            )
            return mutated["batch_stats"]

        jfwd = jax.jit(
            fwd,
            in_shardings=(
                self.state_sh.params, self.state_sh.batch_stats,
                self.batch_sh, _rep(self.mesh),
            ),
            out_shardings=self.state_sh.batch_stats,
        )
        # Fresh dropout mask per batch (mirroring the train step's per-step
        # fold): stats must average over the dropout marginal, not condition
        # on one fixed realization. Jitted like _step_rng itself — eager key
        # ops on a replicated multi-process array would fail.
        fold = jax.jit(jax.random.fold_in)
        it = data.worker_iter(0, 1)
        stats = self.state.batch_stats
        for i in range(batches):
            batch = put_batch(next(it), self.batch_sh)
            stats = jfwd(
                self.state.params, stats, batch, fold(self._step_rng, i)
            )
        self.state = self.state.replace(
            # lint: allow-host-sync(recalibration epilogue, off the step loop)
            batch_stats=jax.block_until_ready(stats)
        )

    def resume_if_available(self) -> int:
        if self.ckpt and self.ckpt.latest_step() is not None:
            # cleanup=True: this caller OWNS the directory and will re-save
            # the step numbers a corrupt-latest fallback walked past.
            self.state = self.ckpt.restore(self.state, cleanup=True)
            return int(self.state.step)
        return 0

    def evaluate(self) -> dict[str, float]:
        with obs.span("eval"):
            return self._evaluate()

    def _evaluate(self) -> dict[str, float]:
        if hasattr(self.eval_data, "epoch_batches"):
            # Cache-backed: one exact pass over the held-out split, sharded
            # across hosts — host i feeds the i-th decimation of the split
            # into its slice of the global batch, so the globally-reduced
            # masked sums count every sample exactly once and eval wall
            # time scales 1/process_count (round 1 walked the full epoch on
            # every host, process_count-times redundant).
            # Decimate by *feed group*, not process: hosts sharing a data-
            # row group (model axis spanning processes) must walk identical
            # batches or put_batch would assemble mismatched rows.
            batches = self.eval_data.epoch_batches(
                self.eval_data.local_batch,
                num_shards=self._feed[0],
                shard_id=self._feed[1],
            )
        else:
            it = iter(self.eval_data)
            batches = (next(it) for _ in range(self.cfg.eval_batches))
        sums = []
        eval_step = self._program("eval_step")
        for host_batch in batches:
            batch = put_batch(host_batch, self.batch_sh)
            s = eval_step(
                self.state.params, self.state.batch_stats, batch
            )
            sums.append(s)
            if self.cfg.heartbeat_file:
                # A full held-out pass can exceed the supervisor's stall
                # timeout; without per-batch beats it kills a healthy run
                # mid-eval, resumes, hits the same eval, and burns every
                # restart. Each beat follows a device→host readback —
                # dispatch alone proves nothing on a hung backend (and on
                # this tunnel block_until_ready can return early).
                # lint: allow-host-sync(readback IS the progress proof)
                np.asarray(jax.tree_util.tree_leaves(s)[0])
                self._heartbeat()
        # lint: allow-host-sync(eval epilogue: exact host-side aggregation)
        return aggregate_eval(jax.block_until_ready(sums))

    def run(self, num_steps: Optional[int] = None) -> dict:
        cfg = self.cfg
        total = num_steps if num_steps is not None else cfg.total_steps
        start = self.resume_if_available()
        # Planned-restart segmenting (supervised runs): stop early, save,
        # and exit RESTART_EXIT_CODE so the supervisor respawns a fresh
        # process (this environment's tunnel client leaks host RSS with
        # steps; a new process restores full throughput — see Config).
        stop = total
        if cfg.restart_every_steps and self.ckpt is not None:
            stop = min(total, start + cfg.restart_every_steps)
        self.logger.log(start, {"params": self.params_n,
                                "devices": len(self.mesh.devices.flat)},
                        prefix="setup")
        stream = None if self._hbm else prefetch_to_device(
            self.train_data,
            sharding=self.batch_sh,
            num_workers=cfg.data_workers,
        )
        self.logger.start_window()
        # Preemption handling: SIGTERM (the cloud scheduler's "you have a
        # grace period" signal) flips a flag the loop checks at each step
        # boundary; the run then checkpoints exactly-here and exits with
        # RESTART_EXIT_CODE, so a supervised preemption is a *planned*
        # restart (free — no failure budget burned) and an unsupervised
        # one leaves a resumable checkpoint instead of losing the segment.
        # Installed only in the main thread (signal.signal refuses
        # elsewhere; a benchmark running Trainers off-thread keeps the
        # default disposition).
        self._preempted = False
        prev_sigterm = None
        try:
            prev_sigterm = signal.signal(
                signal.SIGTERM,
                lambda signum, frame: setattr(self, "_preempted", True),
            )
        except ValueError:
            pass
        preempted = False
        # Loop window markers: the report attributes span time to the
        # step-time breakdown only between these two events. The mesh
        # summary + global batch ride along so an elastic run's report
        # can show, per generation, the world shape each segment ran at
        # — and that the global batch was preserved across re-forms.
        from featurenet_tpu.parallel.mesh import mesh_summary

        obs.emit("loop_start", step=start, stop=stop, total=total,
                 mesh=mesh_summary(self.mesh), global_batch=cfg.global_batch)
        loop_t0 = time.perf_counter()
        last = {}
        # Resume-safe profiling window: anchored at the first step this run
        # actually executes, and always closed before the loop exits.
        trace_start = max(cfg.profile_start, start) if cfg.profile_dir else -1
        trace_active = False
        trace_done = False
        # Dispatch-depth bound: hold the metrics of the last K steps; reading
        # one scalar from step N-K before dispatching step N+1 guarantees at
        # most K steps (and their pinned host batches) are ever in flight.
        pending: collections.deque = collections.deque()
        try:
            step = start
            while step < stop:
                if (trace_start >= 0 and step >= trace_start
                        and not trace_active and not trace_done):
                    jax.profiler.start_trace(cfg.profile_dir)
                    trace_active = True
                # Dispatch k fused steps while a full group fits in the
                # segment; the remainder (total % k, segment cuts) runs
                # single steps — cadences keep exact step semantics.
                take = self._k if self._k > 1 and step + self._k <= stop else 1
                t_iter = time.perf_counter()
                metrics = self.dispatch_group(stream, take)
                new_step = step + take
                pending.append(metrics["loss"])
                paced = len(pending) > max(cfg.max_inflight_steps // take, 1)
                if paced:
                    with obs.span("readback", step=new_step):
                        float(pending.popleft())  # readback = progress proof
                    self._heartbeat()
                # SLO window: per-step time of the dispatch+paced-readback
                # core (eval/checkpoint cadence work deliberately excluded
                # — those are their own spans, and folding them in would
                # make the p99-vs-median tail alert fire on every healthy
                # eval boundary).
                group_wall = time.perf_counter() - t_iter
                obs.observe("step_ms", round(group_wall / take * 1e3, 3))
                if paced:
                    # Perf attribution: compiled flops/bytes over the
                    # group wall feed the rolling mfu / achieved-bw
                    # windows — but ONLY on iterations whose wall was
                    # bounded by a real readback. While the dispatch
                    # pipeline is still filling, the wall is enqueue time
                    # alone (sub-ms against tens of ms of device work)
                    # and would fabricate impossible MFU samples >> 1.
                    obs_perf.observe_dispatch(
                        self._last_cost, group_wall, peaks=self._peaks
                    )
                if trace_active and (
                    new_step >= trace_start + cfg.profile_steps
                    or new_step == total
                ):
                    # lint: allow-host-sync(wall the traced steps pre-stop)
                    jax.block_until_ready(metrics)
                    jax.profiler.stop_trace()
                    trace_active = False
                    trace_done = True

                def crossed(every: int) -> bool:
                    return (new_step // every) > (step // every)

                self.logger.count_samples(cfg.global_batch * take)
                if crossed(cfg.log_every) or new_step == total:
                    last = self.logger.log(new_step, metrics)
                if crossed(cfg.eval_every) or new_step == total:
                    ev = self.evaluate()
                    # The 24×24 confusion matrix stays out of the log stream.
                    self.logger.log(
                        new_step,
                        {k: v for k, v in ev.items() if k != "confusion"},
                        prefix="eval",
                    )
                    last = {**last, **{f"eval_{k}": v for k, v in ev.items()}}
                    # Don't charge eval wall time to the next train window.
                    self.logger.start_window()
                    self._heartbeat()
                if self.ckpt and (crossed(cfg.checkpoint_every)
                                  or new_step == total):
                    with obs.span("checkpoint", step=new_step):
                        self.ckpt.save(self.state)
                    self._heartbeat()
                step = new_step
                if faults.maybe_fail("sigterm", step=step):
                    # Scripted preemption: a REAL signal through the real
                    # handler, at the first step boundary >= N (fused
                    # dispatch may stride past the exact step). The
                    # run-dir marker keeps the resumed process — whose
                    # steps also sit past N — from re-firing.
                    os.kill(os.getpid(), signal.SIGTERM)
                if (faults.active()
                        and jax.process_index() == jax.process_count() - 1
                        and faults.maybe_fail("host_loss", step=step)):
                    # Scripted host loss: SIGKILL self — no drain, no exit
                    # code, mid-everything; the rest of the mesh wedges in
                    # its next collective, which is exactly what the
                    # elastic coordinator must detect and shrink around.
                    # Only the LAST host checks (a single deterministic
                    # casualty; host 0's stream and run.json survive), so
                    # the shared run-dir marker is never raced.
                    os.kill(os.getpid(), signal.SIGKILL)
                if self._preempted and step < total:
                    preempted = True
                    obs.emit("preempt", step=int(step))
                    break
        finally:
            if prev_sigterm is not None:
                signal.signal(signal.SIGTERM, prev_sigterm)
            obs.emit("loop_end", step=int(step),
                     wall_s=time.perf_counter() - loop_t0)
            # Final SLO cycle: a run shorter than the emit period still
            # lands its window summaries (and their alert evaluation)
            # before anything reads the stream.
            obs.flush_windows()
            if stream is not None:
                # Stop the producer threads and release their lookahead of
                # device_put batches — a returned run must not keep pinning
                # HBM or host cycles (benchmarks run several Trainers in
                # one process).
                stream.close()
            if trace_active:
                # An exception mid-window must not lose the trace of the
                # failing steps (the ones worth inspecting).
                jax.profiler.stop_trace()
            # Flush buffered TB events even when the run dies mid-loop —
            # the crashed run is the one worth inspecting. Flush, not close:
            # the same Trainer may run()/evaluate() again and must keep
            # mirroring to TB.
            self.logger.flush()
        if self.ckpt:
            self.ckpt.wait()
        if preempted and self.ckpt is None:
            # Drained, but nothing was persisted: exit 75 would tell a
            # supervising caller "checkpointed, respawn me free", and the
            # respawned run would restart from step 0 — repeated
            # preemptions would then loop forever without burning the
            # failure budget or preserving any progress. Die by the
            # signal instead (the pre-handler disposition), which a
            # supervisor correctly counts as a crash.
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
        if preempted or stop < total:
            # Two ways out mid-run, one exit protocol: a finished segment
            # (planned restart) or a SIGTERM preemption. Both persist
            # exactly-here state (the periodic save may not align with the
            # cut) and exit RESTART_EXIT_CODE, so the supervisor respawns
            # the run as *planned* — a preemption must not burn the
            # failure budget.
            from featurenet_tpu.train.supervisor import RESTART_EXIT_CODE

            if self.ckpt is not None:
                if self.ckpt.latest_step() != int(self.state.step):
                    self.ckpt.save(self.state)
                    self.ckpt.wait()
                # A completed save is confirmed progress: without this
                # beat, a short segment (< max_inflight/eval/checkpoint
                # cadence) would exit 75 having never beaten, and the
                # supervisor would misclassify the planned restart as a
                # startup failure.
                self._heartbeat()
            self.logger.log(
                int(self.state.step),
                {"preempt_exit" if preempted else "planned_restart_exit":
                 1.0},
                prefix="setup",
            )
            raise SystemExit(RESTART_EXIT_CODE)
        # Full step budget reached: mark the run terminal so a live tail
        # (`cli report --follow`) knows to stop re-polling. Segment exits
        # above deliberately don't — the run continues in a fresh process.
        obs.emit("run_end", step=int(step), total=total)
        return last


def train(cfg: Config, **kw) -> dict:
    """One-call entry: build a Trainer and run to cfg.total_steps."""
    return Trainer(cfg, **kw).run()
