"""Training precision policy: fp32 master weights, optional bf16 working step.

The model has always COMPUTED bf16 (flax modules with ``dtype=bfloat16``
cast their fp32 params per layer inside the forward), but the training
state itself ran fp32 end to end: fp32 params into the step, per-layer
bf16 casts as temporaries, fp32 gradient storage out of the backward,
fp32 optimizer math. The ``bf16_master`` policy moves the cast to the
step boundary instead:

- the optimizer (and every checkpoint) holds **fp32 master params** —
  the masters are what's persisted, so checkpoints restore bitwise
  across precision modes;
- the jitted train step casts ONE **bf16 working copy** of the params
  and differentiates with respect to it — the forward runs the same
  bf16 math it always did (minus the per-layer casts), and the backward
  now stores the gradient tree in bf16 (half the gradient HBM);
- the gradients are upcast to fp32 at the step boundary and the update
  applies to the masters — optimizer accumulation never runs in bf16.

``fp32`` is the identity policy: the masters ARE the working copy and
no cast exists anywhere (the compiled step is unchanged). The policy
name rides ``TrainState`` as static metadata (``state.precision``), so
one ``make_train_step`` serves both modes and the runtime registry
fingerprints the two executables apart (``runtime.registry``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# The accepted Config.train_precision values — Config.validate() and the
# CLI's --train-precision choices both mirror this pair (the config-cli
# lint rule cross-checks the surfaces).
TRAIN_PRECISIONS = ("fp32", "bf16_master")


def _cast_floating(tree, dtype):
    """Cast the floating-point leaves of ``tree`` to ``dtype``; integer
    leaves (and leaves already at ``dtype``) pass through untouched."""
    import jax
    import jax.numpy as jnp

    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dtype:
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One training precision mode: how master params become the working
    copy the forward/backward sees, and how the resulting gradients come
    back to master dtype for the optimizer."""

    name: str
    # Working-copy dtype name, or None = the masters are the working copy
    # (no cast compiled anywhere — the fp32 identity policy).
    working_dtype: Optional[str] = None

    def working_params(self, params):
        """The param tree the forward/backward differentiates: a bf16
        cast of the fp32 masters under ``bf16_master``, the masters
        verbatim under ``fp32``."""
        if self.working_dtype is None:
            return params
        import jax.numpy as jnp

        return _cast_floating(params, jnp.dtype(self.working_dtype))

    def master_grads(self, grads):
        """Gradients at master dtype: the bf16 gradient tree upcast to
        fp32 at the step boundary (optimizer accumulation must never run
        in bf16), or the grads verbatim under ``fp32``."""
        if self.working_dtype is None:
            return grads
        import jax.numpy as jnp

        return _cast_floating(grads, jnp.float32)


POLICIES = {
    "fp32": PrecisionPolicy("fp32", None),
    "bf16_master": PrecisionPolicy("bf16_master", "bfloat16"),
}


def get_policy(name: str) -> PrecisionPolicy:
    """The policy object for a ``Config.train_precision`` value; a typo
    is refused here (and at config-validate time) rather than silently
    training at the wrong precision."""
    if name not in POLICIES:
        raise ValueError(
            f"unknown train precision {name!r}; one of "
            f"{', '.join(TRAIN_PRECISIONS)}"
        )
    return POLICIES[name]
