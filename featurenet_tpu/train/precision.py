"""Precision policies: fp32 masters with reduced-precision working steps,
for training AND inference.

Training side. The model has always COMPUTED bf16 (flax modules with
``dtype=bfloat16`` cast their fp32 params per layer inside the forward),
but the training state itself ran fp32 end to end: fp32 params into the
step, per-layer bf16 casts as temporaries, fp32 gradient storage out of
the backward, fp32 optimizer math. The reduced-precision policies move
the cast to the step boundary instead:

- the optimizer (and every checkpoint) holds **fp32 master params** —
  the masters are what's persisted, so checkpoints restore bitwise
  across precision modes;
- the jitted train step casts ONE working copy of the params
  (``bf16_master``: bfloat16; ``fp16_scaled``: float16) and
  differentiates with respect to it — the backward then stores the
  gradient tree at the working dtype (half the gradient HBM);
- the gradients are upcast to fp32 at the step boundary and the update
  applies to the masters — optimizer accumulation never runs reduced.

``fp16_scaled`` additionally runs **dynamic loss scaling**: float16's
narrow exponent (max ~65504, min normal ~6e-5) means small backward
cotangents flush to zero and large ones overflow where bfloat16's
fp32-range exponent shrugs — so the loss is multiplied by a running
scale before the backward, the gradients are unscaled in fp32 after it,
and the scale adapts: ``LOSS_SCALE_GROWTH_INTERVAL`` consecutive
finite-gradient steps double it (up to ``LOSS_SCALE_MAX``); a non-finite
gradient tree halves it (down to ``LOSS_SCALE_MIN``) and the update is
SKIPPED bitwise — masters, optimizer slots, and BN stats unchanged, only
the step counter and the scale state advance. The scale state
(``TrainState.loss_scale`` / ``good_steps``) is part of the train-state
pytree, so checkpoints persist and restore it like the masters — a
resumed fp16 run does not re-learn its scale from overflow. bf16_master
needs none of this (bf16 shares fp32's exponent range), which is exactly
why fp16 is the rung that matters on backends where fp16 is the fast
path and bf16 is not.

``fp32`` is the identity policy: the masters ARE the working copy and
no cast exists anywhere (the compiled step is unchanged). The policy
name rides ``TrainState`` as static metadata (``state.precision``), so
one ``make_train_step`` serves every mode and the runtime registry
fingerprints the executables apart (``runtime.registry``).

Inference side. ``serve_params_cast`` is the same working-copy idea
extended to serving (``Config.serve_precision``): ``bf16`` casts the
fp32 masters to bfloat16, ``int8`` round-trips through the per-channel
quantizer (``runtime.quantize``) — the accuracy-faithful stand-in the
precision-agnostic agreement gate compares against — and ``fp32`` is
the identity. Where the cast runs differs by purpose: the SERVING
programs (``serve_bf16``/``serve_packed_bf16``) take the cast tree as a
program argument, produced ONCE at Predictor construction, so 2-byte
weights are what serving HBM reads per dispatch (the int8
quantize-at-construction pattern); ``eval_step`` compiles the cast
inside instead, because its job is accuracy-faithful eval of the rung,
not bandwidth. Masters stay fp32 in checkpoints under every serving
precision.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# The accepted Config.train_precision values — Config.validate() and the
# CLI's --train-precision choices both mirror this triple (the config-cli
# lint rule cross-checks the surfaces).
TRAIN_PRECISIONS = ("fp32", "bf16_master", "fp16_scaled")

# The accepted Config.serve_precision values (and Predictor precisions) —
# mirrored by Config.validate() and the --serve-precision / --precision
# choices the same way.
SERVE_PRECISIONS = ("fp32", "bf16", "int8")

# Dynamic loss scaling (the fp16_scaled policy). INIT = 2^15: the
# standard warm start — large enough that ~1e-3-magnitude gradients land
# mid-range in float16, small enough that the first steps of a fresh run
# do not overflow (and if they do, the halving converges within a few
# skipped steps). MAX caps growth below float16 overflow for any gradient
# the clip/schedule regime produces; MIN floors the halving so a
# pathological run degrades to unscaled fp16 instead of a zero scale.
LOSS_SCALE_INIT = 2.0 ** 15
LOSS_SCALE_GROWTH_INTERVAL = 200
LOSS_SCALE_MAX = 2.0 ** 24
LOSS_SCALE_MIN = 1.0


def _cast_floating(tree, dtype):
    """Cast the floating-point leaves of ``tree`` to ``dtype``; integer
    leaves (and leaves already at ``dtype``) pass through untouched."""
    import jax
    import jax.numpy as jnp

    def cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dtype:
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One training precision mode: how master params become the working
    copy the forward/backward sees, how the resulting gradients come
    back to master dtype for the optimizer, and whether the step runs
    dynamic loss scaling around the backward."""

    name: str
    # Working-copy dtype name, or None = the masters are the working copy
    # (no cast compiled anywhere — the fp32 identity policy).
    working_dtype: Optional[str] = None
    # Dynamic loss scaling (fp16 only): scale the loss before the
    # backward, unscale the fp32 gradients after, skip-and-halve on
    # non-finite gradients (see the module docstring).
    loss_scaling: bool = False

    def working_params(self, params):
        """The param tree the forward/backward differentiates: a reduced-
        precision cast of the fp32 masters under bf16_master/fp16_scaled,
        the masters verbatim under ``fp32``."""
        if self.working_dtype is None:
            return params
        import jax.numpy as jnp

        return _cast_floating(params, jnp.dtype(self.working_dtype))

    def master_grads(self, grads):
        """Gradients at master dtype: the reduced gradient tree upcast to
        fp32 at the step boundary (optimizer accumulation must never run
        reduced), or the grads verbatim under ``fp32``."""
        if self.working_dtype is None:
            return grads
        import jax.numpy as jnp

        return _cast_floating(grads, jnp.float32)


POLICIES = {
    "fp32": PrecisionPolicy("fp32", None),
    "bf16_master": PrecisionPolicy("bf16_master", "bfloat16"),
    "fp16_scaled": PrecisionPolicy("fp16_scaled", "float16",
                                   loss_scaling=True),
}


def get_policy(name: str) -> PrecisionPolicy:
    """The policy object for a ``Config.train_precision`` value; a typo
    is refused here (and at config-validate time) rather than silently
    training at the wrong precision."""
    if name not in POLICIES:
        raise ValueError(
            f"unknown train precision {name!r}; one of "
            f"{', '.join(TRAIN_PRECISIONS)}"
        )
    return POLICIES[name]


def initial_loss_scale(precision: str) -> float:
    """The loss-scale value a fresh ``TrainState`` starts from: the
    dynamic-scaling warm start under a loss-scaling policy, the inert 1.0
    everywhere else (the leaves exist under EVERY policy so the state
    treedef — and therefore cross-precision checkpoint restore — is
    precision-independent)."""
    return LOSS_SCALE_INIT if get_policy(precision).loss_scaling else 1.0


def serve_params_cast(params, precision: str):
    """The inference-side working-copy transform (``Config.
    serve_precision``):

    - ``fp32``: identity — the masters are what the forward reads.
    - ``bf16``: one boundary cast of every floating leaf to bfloat16
      (BN statistics live in ``batch_stats``, not here, and stay fp32);
      the model's per-layer bf16 casts then become no-ops. The
      Predictor/bench run this ONCE at construction and feed the 2-byte
      tree to the serve programs as an argument; ``eval_step`` traces it
      inside its compiled step (see the module docstring for why each).
    - ``int8``: quantize → dequantize through the per-channel symmetric
      quantizer (``runtime.quantize``) — numerically the int8 serving
      program's weights, which is what makes the precision-agnostic
      agreement gate honest for both rungs.

    Masters are never mutated; the cast output is a fresh tree at the
    reduced width.
    """
    if precision == "fp32":
        return params
    if precision == "bf16":
        import jax.numpy as jnp

        return _cast_floating(params, jnp.bfloat16)
    if precision == "int8":
        from featurenet_tpu.runtime.quantize import (
            dequantize_tree,
            quantize_tree,
        )

        return dequantize_tree(*quantize_tree(params))
    raise ValueError(
        f"unknown serve precision {precision!r}; one of "
        f"{', '.join(SERVE_PRECISIONS)}"
    )
