"""Command-line entry point (reference parity: ``python train.py …`` flags,
SURVEY.md §2 C8 — argparse over resolution/batch/lr/epochs/data/world-size).

Usage:
    python -m featurenet_tpu.cli train --config pod64 [--overrides…]
    python -m featurenet_tpu.cli eval  --config pod64 --checkpoint-dir D
    python -m featurenet_tpu.cli infer --checkpoint-dir D part.stl [more.stl…]
    python -m featurenet_tpu.cli bench
    python -m featurenet_tpu.cli export-data --out D [--per-class N]
    python -m featurenet_tpu.cli build-cache --stl-root S --out D

Multi-host: pass ``--distributed`` to call ``jax.distributed.initialize()``
before any device query (the TPU-native replacement for torchrun + NCCL
rendezvous; coordinator/rank discovery comes from the TPU environment).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import time


def _add_override_flags(p: argparse.ArgumentParser) -> None:
    # default=None so "user explicitly asked for this preset" is
    # distinguishable from "use the default": with a persisted checkpoint
    # config, an explicit contradicting --config is a hard error while the
    # bare default silently defers to the checkpoint.
    p.add_argument("--config", default=None)
    p.add_argument("--resolution", type=int)
    p.add_argument("--global-batch", type=int)
    p.add_argument("--peak-lr", type=float)
    p.add_argument("--total-steps", type=int)
    p.add_argument("--seed", type=int)
    p.add_argument("--grad-clip", type=float, dest="grad_clip",
                   help="global-norm gradient clipping (0 = off)")
    p.add_argument("--train-precision",
                   choices=["fp32", "bf16_master", "fp16_scaled"],
                   dest="train_precision",
                   help="training precision policy "
                        "(featurenet_tpu.train.precision): bf16_master "
                        "keeps fp32 master weights in the optimizer while "
                        "the compiled step runs a bf16 working copy "
                        "(bf16 gradient storage, fp32 update); "
                        "fp16_scaled is the same split at float16 plus "
                        "dynamic loss scaling (non-finite grads skip the "
                        "update bitwise and halve the scale; the scale "
                        "state rides the checkpoint); masters are what "
                        "checkpoints persist, so modes restore into each "
                        "other (default fp32)")
    p.add_argument("--serve-precision", choices=["fp32", "bf16", "int8"],
                   dest="serve_precision",
                   help="serving/eval precision policy "
                        "(featurenet_tpu.train.precision): bf16 serves "
                        "a bfloat16 working copy of the fp32 masters — "
                        "cast once at startup, so every serve/"
                        "serve_packed dispatch reads 2-byte weights; "
                        "eval_step compiles the same cast inside for "
                        "accuracy-faithful eval; int8 selects the "
                        "per-channel quantized programs; each rung is "
                        "gated by the precision-agnostic agreement "
                        "check at the paper's 96.7%% bar (default fp32)")
    p.add_argument("--checkpoint-dir")
    p.add_argument("--mesh-model", type=int)
    p.add_argument("--data-workers", type=int)
    p.add_argument("--data-cache", help="offline npz cache dir (see export-data)")
    p.add_argument("--profile-dir", help="capture an XProf trace here")
    p.add_argument("--tb-dir", help="mirror scalar metrics to TensorBoard "
                                    "event files here")
    p.add_argument("--run-dir", dest="run_dir",
                   help="run-scoped observability directory: writes "
                        "run.json + events.jsonl (spans, gauges, metrics, "
                        "warnings, heartbeats, supervisor restarts); "
                        "analyze with `cli report <run_dir>`")
    p.add_argument("--exec-cache-dir", dest="exec_cache_dir",
                   help="persistent AOT executable cache directory "
                        "(featurenet_tpu.runtime): compiled programs are "
                        "serialized here and respawns/resumes/cold starts "
                        "deserialize instead of recompiling; loads are "
                        "probe-guarded and degrade to a fresh compile "
                        "with a cache_reject event on any failure")
    p.add_argument("--no-augment", action="store_true",
                   help="disable train-time pose augmentation (cache-backed)")
    p.add_argument("--augment-affine", action="store_true",
                   dest="augment_affine",
                   help="arbitrary-angle SO(3)+scale augmentation on "
                        "device (OOD-robust training; replaces cube-group "
                        "rotation; segment warps targets jointly)")
    p.add_argument("--augment-affine-prob", type=float,
                   dest="augment_affine_prob",
                   help="per-group probability the affine warp applies "
                        "(clean/affine batch mixing; default 1.0)")
    p.add_argument("--augment-ramp-steps", type=int,
                   dest="augment_ramp_steps",
                   help="ramp the affine probability linearly 0->prob over "
                        "this many steps (default 0 = no ramp)")
    p.add_argument("--no-augment-affine-rotate", action="store_true",
                   dest="no_augment_affine_rotate",
                   help="affine without rotation: scale+translate only "
                        "(parameter-extrapolation augmentation)")
    p.add_argument("--augment-scale-range", type=float, nargs=2,
                   dest="augment_scale_range", metavar=("LO", "HI"),
                   help="uniform scale window for the affine warp "
                        "(default 0.7 1.05)")
    p.add_argument("--augment-translate-vox", type=float,
                   dest="augment_translate_vox",
                   help="uniform per-axis translation draw in voxels for "
                        "the affine warp (default 0)")
    p.add_argument("--init-from", dest="init_from",
                   help="warm-start params+batch_stats from this checkpoint "
                        "dir (step and optimizer state start fresh)")
    p.add_argument("--augment-noise", type=float, dest="augment_noise",
                   help="train-time occupancy bit-flip rate (robustness "
                        "augmentation, applied on device; 0 = off)")
    p.add_argument("--no-stem-s2d", action="store_true",
                   help="use the direct strided conv instead of the "
                        "space-to-depth stem (matches checkpoints trained "
                        "with stem_s2d=False)")
    p.add_argument("--conv-backend",
                   choices=["xla", "pallas", "hybrid_dw", "fused33"],
                   help="backend for stride-1 conv blocks (default xla); "
                        "fused33 is the layout-specialized tap-unrolled "
                        "path for the 3^3 blocks (ops/conv33.py)")
    p.add_argument("--seg-loss", choices=["balanced_ce", "ce_dice", "dice"],
                   help="segmentation loss variant (default balanced_ce)")
    p.add_argument("--seg-input-context",
                   choices=["none", "proj", "proj_coords"],
                   help="segmenter input context channels (axis projections"
                        " / + coords) for global through/blind reasoning")
    p.add_argument("--seg-decoder-blocks", type=int,
                   help="refine convs per decoder stage (default 1)")
    p.add_argument("--seg-bottleneck-blocks", type=int,
                   help="bottleneck convs (default 1)")
    p.add_argument("--no-spatial", action="store_true", dest="no_spatial",
                   help="disable spatial (depth-over-'model') sharding "
                        "(e.g. single-chip runs of presets that ship "
                        "pod-scale spatial meshes, or --hbm-cache)")
    p.add_argument("--hbm-cache", action="store_true", dest="hbm_cache",
                   help="upload the packed train split into device HBM "
                        "once and sample batches on device (classify + "
                        "--data-cache only; zero per-step input traffic)")
    p.add_argument("--steps-per-dispatch", type=int,
                   dest="steps_per_dispatch",
                   help="fuse k train steps into one compiled dispatch "
                        "(amortizes host/link latency; numerically "
                        "equivalent to k single steps)")
    p.add_argument("--restart-every", type=int, dest="restart_every_steps",
                   help="supervised runs: checkpoint + respawn a fresh "
                        "process every N steps (clears the tunnel client's "
                        "host-RSS leak; does not consume the restart "
                        "budget)")
    p.add_argument("--debug-nans", action="store_true",
                   help="jax_debug_nans: fail fast on the op producing a NaN")
    p.add_argument("--inject-faults", dest="inject_faults",
                   help="chaos spec 'site[@counter=N[:every=M]],...' "
                        "(featurenet_tpu.faults): deterministically inject "
                        "failures — checkpoint_corrupt@save=2, "
                        "sigterm@step=120, producer_crash@batch=40, "
                        "sink_enospc@emit=10, producer_slow@batch=8 … — to "
                        "exercise the recovery paths; each fault fires once "
                        "per run (markers in --run-dir), or once per "
                        "every=M counter stride for soak testing "
                        "(per-firing markers)")
    p.add_argument("--alert-rules", dest="alert_rules",
                   help="live SLO alert rules "
                        "'metric(>|<)threshold[:severity],...' "
                        "(featurenet_tpu.obs.alerts), evaluated over the "
                        "run's rolling windows with --run-dir — e.g. "
                        "'data_wait_fraction>0.6:critical,"
                        "serving_p99_ms>20'; default: the built-in rule "
                        "set (data-wait fraction, step p99/median ratio, "
                        "heartbeat age, cross-host data-wait spread)")
    p.add_argument("--trace-sample", type=float, dest="trace_sample",
                   help="request-tracing sample rate in [0,1] "
                        "(featurenet_tpu.obs.tracing): the fraction of "
                        "healthy serving requests whose admit→dispatch→"
                        "done timeline lands in the run log (decided by "
                        "a hash of the trace id, so hosts agree for "
                        "free); rejections, errors, and SLO breaches "
                        "are always sampled regardless (default 1.0)")
    p.add_argument("--poll-device-memory", action="store_true",
                   dest="poll_device_memory",
                   help="sample per-device memory_stats() at each "
                        "heartbeat (off the hot path) into device_memory "
                        "events — the report's HBM watermark and a "
                        "Chrome-trace counter track (featurenet_tpu.obs."
                        "perf); needs --run-dir, degrades silently on "
                        "backends without stats")


def _add_supervise_flags(p: argparse.ArgumentParser) -> None:
    # Train-only (a supervised eval would parse but silently not supervise).
    p.add_argument("--heartbeat-file",
                   help="touch this file at each confirmed point of device "
                        "progress (used by --supervise; standalone use lets "
                        "external monitoring watch run liveness)")
    p.add_argument("--supervise", action="store_true",
                   help="run training under a stall supervisor: restart from "
                        "the latest checkpoint when the heartbeat goes stale "
                        "(hung device/tunnel) or the process crashes; "
                        "requires --checkpoint-dir")
    p.add_argument("--stall-timeout", type=float, default=600.0,
                   help="seconds of heartbeat staleness that count as a hang "
                        "(default 600)")
    p.add_argument("--max-restarts", type=int, default=5,
                   help="restarts allowed before the supervisor gives up")
    # Internal: set by supervisor.child_argv_from_cli on the respawned child
    # so the --restart-every-requires-a-supervisor guard lets the re-passed
    # flag through (the child's respawner is the supervisor itself).
    p.add_argument("--supervised-child", action="store_true",
                   help=argparse.SUPPRESS)
    p.add_argument("--elastic", action="store_true",
                   help="run under the elastic coordinator "
                        "(featurenet_tpu.elastic): spawn --world-size "
                        "training processes, re-form the mesh at the "
                        "surviving count on host loss (resume from the "
                        "latest checkpoint, global batch preserved), and "
                        "re-admit recovered hosts at the next generation "
                        "boundary; requires --checkpoint-dir and "
                        "--run-dir (membership file + heartbeats)")
    p.add_argument("--world-size", type=int, default=1,
                   help="(--elastic) host slots at full strength; each "
                        "slot is one training process of the "
                        "jax.distributed world (default 1)")
    p.add_argument("--min-world-size", type=int, dest="min_world_size",
                   help="(--elastic) smallest admissible world: fewer "
                        "surviving hosts forces a full-strength restart "
                        "instead of a shrink (default 1)")
    p.add_argument("--local-devices", type=int, default=1,
                   help="(--elastic) accelerator devices per host — the "
                        "planner's feasibility input: every admitted "
                        "world's device count must divide global_batch "
                        "(default 1)")
    p.add_argument("--readmit", choices=["auto", "agent"], default="auto",
                   help="(--elastic) boundary re-admission policy: "
                        "'auto' (default) re-offers every lost slot at "
                        "the next generation boundary; 'agent' "
                        "re-admits only slots whose external host "
                        "agent signaled recovery by writing its slot "
                        "into membership.json "
                        "(elastic.membership.signal_ready) — a "
                        "still-dead host is never blindly offered a "
                        "rank it cannot fill")
    # Internal: injected by the elastic coordinator on each child so the
    # child joins the generation's jax.distributed world.
    p.add_argument("--elastic-rank", type=int, help=argparse.SUPPRESS)
    p.add_argument("--elastic-world", type=int, help=argparse.SUPPRESS)
    p.add_argument("--elastic-port", type=int, help=argparse.SUPPRESS)
    p.add_argument("--elastic-generation", type=int, help=argparse.SUPPRESS)


def _overrides(args) -> dict:
    keys = [
        "resolution", "global_batch", "peak_lr", "total_steps", "seed",
        "checkpoint_dir", "mesh_model", "data_workers", "data_cache",
        "profile_dir", "tb_dir", "run_dir", "heartbeat_file", "seg_loss",
        "restart_every_steps", "steps_per_dispatch", "grad_clip",
        "train_precision", "serve_precision",
        "augment_noise", "augment_affine_prob", "augment_ramp_steps",
        "augment_translate_vox", "init_from", "inject_faults",
        "alert_rules", "exec_cache_dir", "min_world_size", "trace_sample",
        "seg_input_context", "seg_decoder_blocks", "seg_bottleneck_blocks",
    ]
    out = {
        k: getattr(args, k, None)
        for k in keys
        if getattr(args, k, None) is not None
    }
    if getattr(args, "steps_per_dispatch", None) is not None:
        # An explicitly requested k is honored as-is: the operator opted
        # out of the first-order membytes clamp (the Trainer still warns
        # when the request exceeds the model — advisor r5).
        out["clamp_dispatch_k"] = False
    if getattr(args, "augment_scale_range", None) is not None:
        out["augment_scale_range"] = tuple(args.augment_scale_range)
    if getattr(args, "no_augment_affine_rotate", False):
        out["augment_affine_rotate"] = False
    if getattr(args, "no_augment", False):
        out["augment"] = False
    if getattr(args, "hbm_cache", False):
        out["hbm_cache"] = True
    if getattr(args, "poll_device_memory", False):
        out["poll_device_memory"] = True
    if getattr(args, "elastic", False):
        out["elastic"] = True
    if getattr(args, "augment_affine", False):
        out["augment_affine"] = True
    if getattr(args, "no_spatial", False):
        out["spatial"] = False
    return out


def _apply_arch_overrides(cfg, args):
    arch_kw = {}
    if getattr(args, "no_stem_s2d", False):
        arch_kw["stem_s2d"] = False
    if getattr(args, "conv_backend", None):
        arch_kw["conv_backend"] = args.conv_backend
    if arch_kw:
        cfg = dataclasses.replace(
            cfg, arch=dataclasses.replace(cfg.arch, **arch_kw)
        ).validate()
    return cfg


def _cfg_from_checkpoint(saved, args):
    """Persisted checkpoint config + run-policy overrides from ``args``.

    Identity-defining flags (--config/--resolution/arch flags) must agree
    with what the checkpoint was trained with — a silent mismatch restores
    structurally-valid weights into the wrong model (the round-1 disease the
    sidecar exists to kill), so contradiction is a hard error, not a merge.
    """
    from featurenet_tpu.config import check_identity

    if getattr(args, "config", None) and args.config != saved.name:
        raise SystemExit(
            f"flags contradict the config persisted with this checkpoint: "
            f"--config {args.config} (checkpoint: {saved.name}) — drop the "
            "flag (the checkpoint self-configures), or point at a run "
            "trained with these settings"
        )
    # Build the identity the flags request and let the one canonical check
    # (config.check_identity, driven by IDENTITY_FIELDS) rule on it — a
    # second hand-rolled field list here would drift as fields are added.
    requested = saved
    if getattr(args, "resolution", None):
        requested = dataclasses.replace(
            requested, resolution=args.resolution
        )
    requested = _apply_arch_overrides(requested, args)
    try:
        check_identity(saved, requested)
    except ValueError as e:
        raise SystemExit(str(e))
    over = _overrides(args)
    over.pop("resolution", None)  # identity — already verified equal
    # Ephemeral run-environment fields must not leak across runs: a stale
    # heartbeat path or the training run's TB dir is never what an eval or
    # resume meant unless the flag was passed again. restart_every_steps is
    # in the list because only a *supervised* run should segment (the
    # supervisor's child argv re-passes --restart-every every spawn); an
    # unsupervised resume inheriting it from the sidecar would die with
    # exit 75 mid-run and nothing would respawn it.
    for k in ("heartbeat_file", "profile_dir", "tb_dir", "run_dir",
              "restart_every_steps", "inject_faults", "exec_cache_dir"):
        over.setdefault(k, None)
    # Same ephemerality, bool-typed: the memory poller belongs to the run
    # that asked for it, not to every later eval/resume of its checkpoint.
    over.setdefault("poll_device_memory", False)
    # Arch flags must reach the returned config too — check_identity above
    # already rejected real contradictions, so what flows through here is
    # exactly the deliberately-allowed lowering choice (conv_backend A/B
    # on one trained run).
    return _apply_arch_overrides(
        dataclasses.replace(saved, **over).validate(), args
    )


def _score_capture_ring(pred, capture_dir: str, recs=None):
    """Re-score one flight-recorder ring through ``pred``'s AOT serving
    path — the shared canary core of ``cli replay`` and ``cli fleet
    rollout`` (one implementation, so the agreement arithmetic and the
    zero-compile evidence can never drift between the CI gate and the
    live-rollout gate). Returns ``(recs, labels, probs, score_ms,
    post_warmup_compiles)``; ``recs`` is the label-carrying record list
    (``[]`` when the ring is missing/empty — only answered requests
    carry a recorded prediction to agree with)."""
    import numpy as np

    from featurenet_tpu.obs import events as _events
    from featurenet_tpu.serve.recorder import read_captures, unpack_grid

    if recs is None:
        recs = [r for r in read_captures(capture_dir)
                if r.get("label") is not None]
    if not recs:
        return [], None, None, 0.0, 0
    grids = np.stack([unpack_grid(r["voxels"]) for r in recs])
    warm = _events.kind_counts().get("program_compile", 0)
    t0 = time.perf_counter()
    labels, probs = pred.predict_voxels(grids)
    score_ms = (time.perf_counter() - t0) * 1e3
    compiles = _events.kind_counts().get("program_compile", 0) - warm
    return recs, labels, probs, score_ms, compiles


# The rollout orchestrator's event stream index: far above any replica's
# slot+1 stream so `cli fleet rollout` can append rollout_* events into
# a LIVE fleet's run dir without ever colliding with a replica stream.
_ROLLOUT_STREAM = 1000


def _fleet_router_address(run_dir: str):
    """The live fleet's router ``(host, port)``, read from the LAST
    ``fleet_start`` event in the run's stream-0 log (the router owns
    stream 0; an ephemeral ``--port 0`` is only ever printed/emitted, so
    the event stream is the one durable place to find it). ``None`` when
    the run dir has no fleet_start — not a fleet run dir."""
    import os

    from featurenet_tpu.obs.events import events_filename

    addr = None
    try:
        with open(os.path.join(run_dir, events_filename(0)),
                  encoding="utf-8") as fh:
            for line in fh:
                try:
                    e = json.loads(line)
                except ValueError:
                    continue  # torn tail line of a live log
                if e.get("ev") == "fleet_start":
                    addr = (e.get("host"), e.get("port"))
    except OSError:
        return None
    if not addr or not addr[0] or not addr[1]:
        return None
    return addr[0], int(addr[1])


def _cmd_fleet_rollout(args) -> None:
    """``cli fleet rollout <checkpoint_dir>``: zero-downtime rolling
    weight rollout across a LIVE fleet, one replica at a time —
    replay-canary against that replica's own capture ring, hot-swap via
    ``POST /admin/reload`` (the replica cordons itself and drains
    through the router's spillover path while the new generation is
    restored), verify the version tag, move on. Any canary failure,
    swap refusal, or replica death mid-rollout rolls every
    already-swapped replica back to its old checkpoint and exits 2."""
    import http.client
    import os

    from featurenet_tpu import obs
    from featurenet_tpu.config import get_config
    from featurenet_tpu.fleet.pool import ConnectionPool
    from featurenet_tpu.infer import Predictor
    from featurenet_tpu.train.checkpoint import load_run_config

    candidate = args.rollout_checkpoint_dir
    if not (0.0 <= args.min_agreement <= 1.0):
        raise SystemExit(
            f"fleet rollout: --min-agreement must be in [0, 1], got "
            f"{args.min_agreement}"
        )
    run_dir = getattr(args, "run_dir", None)
    if not run_dir:
        raise SystemExit(
            "fleet rollout: --run-dir is required — it names the LIVE "
            "fleet (router address from its event stream, capture rings "
            "under <run-dir>/capture, rollout events into the same run)"
        )
    addr = _fleet_router_address(run_dir)
    if addr is None:
        raise SystemExit(
            f"fleet rollout: no fleet_start event under {run_dir!r} — "
            "point --run-dir at the run directory of a live `cli fleet`"
        )
    host, router_port = addr
    pool = ConnectionPool(timeout_s=args.swap_timeout_s)

    def _get_json(port: int, path: str,
                  timeout_s: float = 10.0) -> tuple:
        status, raw = pool.get(host, port, path, timeout_s)
        try:
            return status, json.loads(raw.decode("utf-8"))
        except ValueError:
            return status, {}

    try:
        status, health = _get_json(router_port, "/healthz")
    except (OSError, http.client.HTTPException) as e:
        raise SystemExit(
            f"fleet rollout: router at {host}:{router_port} is "
            f"unreachable ({e}) — the fleet must be live to roll"
        )
    ports = {int(s): int(p)
             for s, p in (health.get("ports") or {}).items()}
    if status != 200 or not ports:
        raise SystemExit(
            f"fleet rollout: fleet at {host}:{router_port} is not ready "
            f"(status {status}, ports {ports}) — nothing to roll"
        )
    # The per-replica OLD identity, straight off each replica's own
    # /healthz: the version tag proves the mixed-version window later,
    # and checkpoint_dir is what a rollback re-submits.
    roster: dict = {}
    for slot, port in sorted(ports.items()):
        try:
            st, h = _get_json(port, "/healthz")
        except (OSError, http.client.HTTPException):
            continue
        if st != 200 or not h.get("ready"):
            continue
        roster[slot] = {
            "port": port,
            "old_version": h.get("model_version", "unversioned"),
            "old_checkpoint_dir": h.get("checkpoint_dir"),
        }
    if not roster:
        raise SystemExit(
            "fleet rollout: no ready replicas answered /healthz — "
            "refusing to roll a degraded fleet"
        )
    saved = load_run_config(candidate)
    cfg = saved if saved is not None else get_config(
        args.config or "pod64"
    )
    obs.init_run(run_dir, extra={"cmd": "fleet-rollout"},
                 process_index=_ROLLOUT_STREAM)
    exit_code = 0
    out: dict = {}
    try:
        # Construction is the canary's warmup: ONE scoring program
        # builds here, in THIS process — the replicas' own AOT ladders
        # are untouched, which is what "zero post-warmup compiles on
        # the swapped path" means.
        pred = Predictor.from_checkpoint(
            candidate, cfg, batch=args.batch, precision=args.precision
        )
        if pred.cfg.task != "classify":
            raise SystemExit(
                "fleet rollout: capture rings hold classify traffic — "
                f"the candidate is task={pred.cfg.task!r}"
            )
        target_version = pred.model_version
        obs.emit("rollout_start", checkpoint_dir=str(candidate),
                 replicas=sorted(roster), to_version=target_version)
        swapped: list = []
        steps: list = []
        failure = None

        def _rollback(reason: str) -> tuple:
            rolled, failed = [], []
            for slot in reversed(swapped):
                info = roster[slot]
                old = info["old_checkpoint_dir"]
                if not old:
                    failed.append(slot)
                    continue
                try:
                    st, raw, _ra = pool.post(
                        host, info["port"], "/admin/reload",
                        json.dumps({"checkpoint_dir": old}).encode(),
                        {"Content-Type": "application/json"},
                        args.swap_timeout_s,
                    )
                    (rolled if st == 200 else failed).append(slot)
                except (OSError, http.client.HTTPException):
                    failed.append(slot)
            obs.emit("rollout_rollback", reason=reason,
                     rolled_back=rolled, failed=failed)
            return rolled, failed

        for slot in sorted(roster):
            info = roster[slot]
            ring = os.path.join(run_dir, "capture", f"replica{slot}")
            recs, labels, _probs, _score_ms, compiles = \
                _score_capture_ring(pred, ring)
            agreement = None
            if recs:
                agree = sum(
                    1 for i, r in enumerate(recs)
                    if int(r["label"]) == int(labels[i])
                )
                agreement = agree / len(recs)
                obs.emit("replay_verdict",
                         agreement=round(agreement, 6), n=len(recs),
                         ok=agreement >= args.min_agreement,
                         min_agreement=args.min_agreement,
                         flips=len(recs) - agree,
                         post_warmup_compiles=compiles, replica=slot)
                if agreement < args.min_agreement:
                    obs.emit("rollout_step", replica=slot, ok=False,
                             agreement=round(agreement, 6),
                             reason="canary_failed")
                    failure = (
                        f"canary_failed(replica={slot},"
                        f"agreement={agreement:.4f})"
                    )
                    break
            try:
                st, raw, _ra = pool.post(
                    host, info["port"], "/admin/reload",
                    json.dumps({"checkpoint_dir": candidate}).encode(),
                    {"Content-Type": "application/json"},
                    args.swap_timeout_s,
                )
            except (OSError, http.client.HTTPException) as e:
                # The replica died (or vanished) mid-swap — the manager
                # will respawn it on the OLD argv; our job is to roll
                # the already-swapped peers back to match it.
                obs.emit("rollout_step", replica=slot, ok=False,
                         reason=f"replica_lost: {e}")
                failure = f"replica_lost(replica={slot})"
                break
            try:
                doc = json.loads(raw.decode("utf-8"))
            except ValueError:
                doc = {}
            if st != 200:
                kind = doc.get("kind") or st
                obs.emit("rollout_step", replica=slot, ok=False,
                         reason=f"swap_refused:{kind}")
                failure = f"swap_refused(replica={slot},kind={kind})"
                break
            swapped.append(slot)
            step = {
                "replica": slot, "canary_n": len(recs),
                "agreement": (None if agreement is None
                              else round(agreement, 6)),
                "swap_ms": doc.get("swap_ms"),
                "model_version": doc.get("model_version"),
            }
            steps.append(step)
            obs.emit("rollout_step", replica=slot, ok=True, **{
                k: v for k, v in step.items() if k != "replica"
            })
        if failure is not None:
            rolled, failed_rb = _rollback(failure)
            converged = _wait_one_version(
                pool, host, router_port, args.converge_timeout_s
            )
            obs.emit("rollout_done", ok=False, swapped=[],
                     reason=failure, rolled_back=rolled)
            out = {"ok": False, "reason": failure,
                   "rolled_back": rolled, "rollback_failed": failed_rb,
                   "converged": converged, "steps": steps}
            exit_code = 2
        else:
            converged = _wait_one_version(
                pool, host, router_port, args.converge_timeout_s,
                expect=target_version,
            )
            obs.emit("rollout_done", ok=True, swapped=swapped,
                     version=target_version, converged=converged)
            out = {"ok": True, "version": target_version,
                   "swapped": swapped, "converged": converged,
                   "steps": steps}
        print(json.dumps({"fleet_rollout": {
            "checkpoint_dir": candidate, "run_dir": run_dir,
            "min_agreement": args.min_agreement, **out,
        }}))
    finally:
        pool.close()
        obs.close_run()
    if exit_code:
        raise SystemExit(exit_code)


def _wait_one_version(pool, host: str, router_port: int,
                      timeout_s: float, expect=None) -> bool:
    """Poll the router's roster until every replica with a known version
    tag reports the SAME one (and ``expect``, when given) — the
    "re-converged to one version" verdict. Bounded; False on timeout
    (informational: the exit code rides the rollout verdict, not this)."""
    import http.client

    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            status, raw = pool.get(host, router_port, "/healthz", 10.0)
            doc = json.loads(raw.decode("utf-8"))
        except (OSError, http.client.HTTPException, ValueError):
            time.sleep(0.5)
            continue
        versions = doc.get("versions") or {}
        vals = set(versions.values())
        healthy = doc.get("healthy", 0)
        if (versions and len(vals) == 1 and healthy
                and (expect is None or vals == {expect})):
            return True
        time.sleep(0.5)
    return False


def main(argv=None) -> None:
    # allow_abbrev=False everywhere: the supervisor re-execs a rewritten argv
    # with supervision flags stripped by exact match — a prefix abbreviation
    # like --superv would leak through and spawn supervisors recursively.
    parser = argparse.ArgumentParser(prog="featurenet_tpu", allow_abbrev=False)
    parser.add_argument("--distributed", action="store_true",
                        help="multi-host: jax.distributed.initialize() first")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_train = sub.add_parser("train", allow_abbrev=False)
    _add_override_flags(p_train)
    _add_supervise_flags(p_train)
    _add_override_flags(sub.add_parser("eval", allow_abbrev=False))
    sub.add_parser("bench")
    p_exp = sub.add_parser("export-data",
                           help="materialize the synthetic set as an npz cache")
    p_exp.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: record per-class "
                            "export spans (see `cli report`)")
    p_exp.add_argument("--out", required=True)
    p_exp.add_argument("--per-class", type=int, default=1000)
    p_exp.add_argument("--resolution", type=int, default=64)
    p_exp.add_argument("--seed", type=int, default=0)
    p_exp.add_argument("--param-range", default=None,
                       help="feature-parameter quantile window: 'mid', "
                            "'tails', or 'lo,hi' (OOD-holdout caches; "
                            "default: full range)")
    p_exp.add_argument("--mesh-pose", default="none",
                       choices=["none", "remesh", "so3"],
                       help="route parts through the STL pipeline: "
                            "'remesh' = STL normalization, identity pose; "
                            "'so3' = + uniform random rotation "
                            "(OOD-robust training caches)")
    p_exp.add_argument("--margin-jitter", default=None,
                       help="'lo,hi': per-sample normalization margin "
                            "(scale augmentation; default fixed 0.05)")
    p_ood = sub.add_parser("eval-ood", allow_abbrev=False,
                           help="robustness report: fresh-draw accuracy "
                                "under rotation/noise/morph/parameter-tail "
                                "perturbation (featurenet_tpu.ood)")
    p_ood.add_argument("--checkpoint-dir", required=True)
    p_ood.add_argument("--per-class", type=int, default=50)
    p_ood.add_argument("--seg-parts", type=int, default=60,
                       help="segment checkpoints: fresh draws per row "
                            "(the task is auto-detected from the "
                            "checkpoint's persisted config)")
    p_ood.add_argument("--seed", type=int, default=777)
    p_ood.add_argument("--families", default=None,
                       help="comma list: clean,rotation,noise,morph,tails,scale")
    p_ood.add_argument("--out", default=None,
                       help="also write the report rows as a JSON file")
    p_ood.add_argument("--canonicalize", action="store_true",
                       help="classify checkpoints: undo arbitrary pose by "
                            "min-AABB canonicalization before predicting "
                            "(robust-serving mode; implies --tta, which "
                            "resolves the residual 24-pose ambiguity)")
    p_ood.add_argument("--tta", action="store_true", dest="tta_rotations",
                       help="classify checkpoints: average probabilities "
                            "over the 24 cube-group orientations (resolves "
                            "canonicalization ambiguity; 24x device work)")
    p_rec = sub.add_parser("recalibrate", allow_abbrev=False,
                           help="re-estimate a checkpoint's BatchNorm "
                                "running statistics over clean training "
                                "batches and save the result as a new "
                                "checkpoint (recovers the clean-modality "
                                "eval tax of mixed-distribution training)")
    p_rec.add_argument("--checkpoint-dir", required=True)
    p_rec.add_argument("--out-dir", required=True,
                       help="directory for the recalibrated checkpoint "
                            "(the source checkpoint is never modified)")
    p_rec.add_argument("--batches", type=int, default=64,
                       help="clean train batches to stream through "
                            "(momentum-0.9 stats converge in ~30)")
    p_rec.add_argument("--data-cache", dest="rec_data_cache", default=None,
                       help="override the persisted data_cache path")
    p_seg = sub.add_parser("export-seg-data",
                           help="materialize multi-feature parts with "
                                "per-voxel ground truth as a seg cache")
    p_seg.add_argument("--out", required=True)
    p_seg.add_argument("--num-parts", type=int, default=2400)
    p_seg.add_argument("--resolution", type=int, default=64)
    p_seg.add_argument("--num-features", type=int, default=3)
    p_seg.add_argument("--seed", type=int, default=0)
    p_seg.add_argument("--label-order", choices=["canonical", "generation"],
                       default="canonical",
                       help="overlap-voxel labeling: canonical (default) is "
                            "deterministic given the geometry; generation "
                            "reproduces the round-2 ambiguous dataset")
    p_stl = sub.add_parser("export-stl-data", allow_abbrev=False,
                           help="materialize the synthetic benchmark as an "
                                "STL class tree (the reference dataset's "
                                "on-disk shape; ingest with build-cache)")
    p_stl.add_argument("--out", required=True)
    p_stl.add_argument("--per-class", type=int, default=10)
    p_stl.add_argument("--resolution", type=int, default=64)
    p_stl.add_argument("--seed", type=int, default=0)
    p_stl.add_argument("--seg", action="store_true",
                       help="segmentation tree: multi-feature parts with "
                            "per-voxel label sidecars (<part>.seg.npy)")
    p_stl.add_argument("--num-parts", type=int, default=2400,
                       help="(--seg) total parts in the tree")
    p_stl.add_argument("--num-features", type=int, default=3,
                       help="(--seg) features carved per part")
    p_stl.add_argument("--label-order", choices=["canonical", "generation"],
                       default="canonical",
                       help="(--seg) overlap-voxel labeling: canonical is "
                            "deterministic (learnable); generation "
                            "reproduces the round-2 ambiguous dataset")
    p_bld = sub.add_parser("build-cache",
                           help="voxelize an STL class tree into a packed "
                                "voxel cache")
    p_bld.add_argument("--stl-root", required=True)
    p_bld.add_argument("--out", required=True)
    p_bld.add_argument("--resolution", type=int, default=None,
                       help="classification trees only (default 64); a "
                            "segmentation tree's resolution is fixed by its "
                            "sidecars, so a contradicting flag is refused")
    p_bld.add_argument("--workers", type=int, default=None,
                       help="process-pool width for per-file voxelization "
                            "(default: cpu count; 1 = serial)")
    p_bld.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: record per-class "
                            "ingest spans (see `cli report`)")
    p_prog = sub.add_parser("programs", allow_abbrev=False,
                            help="enumerate the runtime registry's "
                                 "compiled programs for a config "
                                 "(featurenet_tpu.runtime): name, "
                                 "precision, applicability; --warm builds "
                                 "them AOT (and populates "
                                 "--exec-cache-dir when set)")
    p_prog.add_argument("--config", default="pod64",
                        help="preset whose program catalog to list "
                             "(default pod64)")
    p_prog.add_argument("--train-precision",
                        choices=["fp32", "bf16_master", "fp16_scaled"],
                        dest="train_precision",
                        help="enumerate (and --warm build) the train "
                             "programs under this precision policy; the "
                             "executable-cache fingerprint separates the "
                             "variants (default fp32)")
    p_prog.add_argument("--serve-precision",
                        choices=["fp32", "bf16", "int8"],
                        dest="serve_precision",
                        help="enumerate (and --warm build) eval_step "
                             "under this serving precision (the serve/"
                             "serve_bf16/serve_int8 variants are listed "
                             "by name regardless; default fp32)")
    p_prog.add_argument("--warm", action="store_true",
                        help="build every applicable program (AOT warmup; "
                             "with --exec-cache-dir, populates the "
                             "persistent executable cache for later "
                             "respawns/cold starts)")
    p_prog.add_argument("--exec-cache-dir", dest="exec_cache_dir",
                        help="persistent executable cache directory the "
                             "warmup builds into / loads from")
    p_prog.add_argument("--run-dir", dest="run_dir",
                        help="observability directory: record "
                             "program_compile/cache_* events of the "
                             "warmup (see `cli report`)")
    p_lint = sub.add_parser("lint", allow_abbrev=False,
                            help="repo-native static analysis "
                                 "(featurenet_tpu.analysis): enforce the "
                                 "telemetry, fault-site, host-sync, "
                                 "timing-hygiene, config/CLI, and "
                                 "concurrency contracts over the "
                                 "package's own AST; exits 2 on findings")
    p_lint.add_argument("path", nargs="?", default=None,
                        help="directory (or single file) to lint; default: "
                             "the installed featurenet_tpu package. A path "
                             "inside the package lints the whole package "
                             "(the contracts are package-wide) and narrows "
                             "the reported findings to that subtree; a "
                             "path outside is linted as its own tree")
    p_lint.add_argument("--json", action="store_true", dest="as_json",
                        help="alias for --format json (one JSON object "
                             "per finding plus a summary record)")
    p_lint.add_argument("--format", dest="fmt", default=None,
                        choices=("text", "json", "sarif"),
                        help="output rendering: text (default), json "
                             "(one object per finding), or sarif "
                             "(SARIF 2.1.0 for CI code-scanning "
                             "annotation)")
    p_lint.add_argument("--changed", action="store_true",
                        help="report only findings in files the git "
                             "working tree changed vs HEAD (plus "
                             "untracked files); package-level findings "
                             "always survive. Falls back to the full "
                             "lint when git is absent")
    p_lint.add_argument("--rule", action="append", dest="rules",
                        metavar="NAME",
                        help="run only this rule family (repeatable): "
                             "telemetry, fault-sites, host-sync, hygiene, "
                             "config-cli, spans, raw-conn, alerts, "
                             "concurrency, suppressions")
    p_rep = sub.add_parser("report", allow_abbrev=False,
                           help="analyze a run directory's observability "
                                "log (featurenet_tpu.obs): step-time "
                                "breakdown, per-host merge + skew, "
                                "input-pipeline health, restart/stall "
                                "timeline, serving latency")
    p_rep.add_argument("run_dir", help="directory a run wrote via --run-dir")
    p_rep.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the raw report dict as JSON instead of "
                            "the human-readable rendering")
    p_rep.add_argument("--trace", default=None,
                       help="also export the timing spans as a Chrome "
                            "trace.json to this path (one track per host; "
                            "chrome://tracing, ui.perfetto.dev)")
    p_rep.add_argument("--follow", action="store_true",
                       help="live tail: re-read the event stream(s) "
                            "incrementally and re-render the report every "
                            "few seconds while the run is hot, with the "
                            "latest SLO window percentiles and active "
                            "alerts under the header; exits on Ctrl-C or "
                            "when the run ends")
    p_rep.add_argument("--interval", type=float, default=3.0,
                       help="--follow re-render period in seconds "
                            "(default 3)")
    p_rep.add_argument("--gate", default=None, metavar="BASELINE_JSON",
                       help="evaluate regression gates against this pinned "
                            "baseline (obs.gates); exits non-zero on any "
                            "regression — data-wait fraction, p99 serving "
                            "latency, step time, restart count, each with "
                            "a tolerance")
    p_rep.add_argument("--validate", action="store_true",
                       help="event-schema lint: unknown event kinds, "
                            "missing required fields, non-monotonic span "
                            "nesting; exits non-zero on findings")
    p_rep.add_argument("--request", default=None, metavar="TRACE_ID",
                       dest="request_trace",
                       help="render ONE request's admit→dispatch→done "
                            "timeline (featurenet_tpu.obs.tracing), "
                            "merged across host streams, with its batch "
                            "attribution — the id the serving response "
                            "echoed in the X-Featurenet-Trace header; "
                            "exits non-zero when the id has no sampled "
                            "events in this run dir")
    p_hist = sub.add_parser("bench-history", allow_abbrev=False,
                            help="one-table summary across BENCH_r*.json "
                                 "rounds (featurenet_tpu.obs."
                                 "bench_history): throughput/MFU/serving/"
                                 "fleet pins per round (incl. "
                                 "fleet_conn_reuse_ratio — the pooled "
                                 "data plane's trajectory); skipped "
                                 "rounds render with their structured "
                                 "reason instead of vanishing")
    p_hist.add_argument("bench_dir", nargs="?", default=".",
                        help="directory holding the BENCH_r*.json "
                             "artifacts (default: the current dir)")
    p_hist.add_argument("--json", action="store_true", dest="as_json",
                        help="one JSON object per round instead of the "
                             "table")
    p_hist.add_argument("--gate", action="store_true", dest="trend_gate",
                        help="judge the latest parseable round against "
                             "the PREVIOUS one on the pinned bench keys "
                             "(obs.gates tolerances + noisy-key slack) "
                             "and exit 2 on a regression — a trend gate "
                             "CI can run with no baseline file checked "
                             "in")
    p_dash = sub.add_parser("dash", allow_abbrev=False,
                            help="live terminal fleet dashboard over a "
                                 "run dir's time-series store "
                                 "(featurenet_tpu.obs.dash): per-replica "
                                 "qps/p99/queue sparklines, burn-rate "
                                 "gauges, roster + scrape health — "
                                 "renders from <run_dir>/tsdb alone, so "
                                 "it works on a live fleet and on a "
                                 "finished run identically")
    p_dash.add_argument("run_dir", help="run directory (the fleet "
                                        "scraper's store lives at "
                                        "<run_dir>/tsdb)")
    p_dash.add_argument("--once", action="store_true",
                        help="render ONE frame and exit (tests/CI "
                             "artifacts) instead of the live loop")
    p_dash.add_argument("--interval", type=float, default=2.0,
                        help="refresh interval in seconds (default 2)")
    p_dash.add_argument("--window-s", type=float, default=300.0,
                        dest="window_s",
                        help="sparkline look-back window in seconds "
                             "(default 300)")
    p_dash.add_argument("--slos", default=None,
                        help="burn-rate SLO spec for the gauges "
                             "(obs.alerts.parse_slos, e.g. "
                             "'serving_p99_ms<250@99%%'); default: the "
                             "built-in serving objective")
    p_incd = sub.add_parser("incident", allow_abbrev=False,
                            help="post-mortem over alert-triggered "
                                 "incident bundles "
                                 "(featurenet_tpu.obs.incidents): list a "
                                 "run dir's bundles or render one — "
                                 "everything reads "
                                 "<run_dir>/incidents/<id>/ alone, so it "
                                 "works after the service that captured "
                                 "them is long gone")
    p_incd.add_argument("action", choices=["list", "show"],
                        help="list: one line per bundle, oldest first; "
                             "show: render one incident's full "
                             "post-mortem (trigger, tsdb slice, window "
                             "snapshots, events tail, folded thread "
                             "stacks)")
    p_incd.add_argument("run_dir", help="run directory (bundles live "
                                        "under <run_dir>/incidents)")
    p_incd.add_argument("incident_id", nargs="?", default=None,
                        help="show only: incident id (default: the "
                             "latest bundle)")
    p_incd.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output: the bundle index "
                             "(list) or the loaded bundle dict (show)")
    p_inf = sub.add_parser("infer", allow_abbrev=False,
                           help="classify or segment STL files with a "
                                "trained checkpoint")
    p_inf.add_argument("stl", nargs="+", help="STL file path(s)")
    p_inf.add_argument("--checkpoint-dir", required=True)
    p_inf.add_argument("--config", default=None,
                       help="only needed for legacy checkpoints without a "
                            "persisted config.json (default: read the "
                            "checkpoint's own config)")
    p_inf.add_argument("--resolution", type=int,
                       help="legacy checkpoints only: must match the "
                            "trained resolution")
    p_inf.add_argument("--no-stem-s2d", action="store_true",
                       help="legacy checkpoints trained with "
                            "--no-stem-s2d (param tree differs)")
    p_inf.add_argument("--conv-backend",
                       choices=["xla", "pallas", "hybrid_dw", "fused33"],
                       help="legacy checkpoints trained with a non-default "
                            "conv backend")
    p_inf.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="serving weight precision (default: the "
                            "config's serve_precision, itself fp32 by "
                            "default): bf16 serves a bfloat16 working "
                            "copy cast once at startup (half the weight "
                            "HBM traffic per dispatch); int8 runs the "
                            "per-channel post-training-quantized program "
                            "(featurenet_tpu.runtime.quantize; 4x less "
                            "weight HBM traffic); both rungs are "
                            "accuracy-gated in tests against the "
                            "paper's 96.7%% target")
    p_inf.add_argument("--seg-out",
                       help="segment checkpoints: also write each part's "
                            "per-voxel label grid to this directory as "
                            "<stem>_seg.npz")
    p_inf.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: record per-batch "
                            "serving latency spans (see `cli report`)")
    p_inf.add_argument("--alert-rules", dest="alert_rules",
                       help="serving SLO rules "
                            "'metric(>|<)threshold[:severity],...' "
                            "(featurenet_tpu.obs.alerts) evaluated over "
                            "this run's serving windows — e.g. "
                            "'serving_p99_ms>20:critical'. An unresolved "
                            "serving alert when the batch finishes makes "
                            "infer EXIT 2, so CI can gate on latency "
                            "regressions; requires --run-dir")
    p_srv = sub.add_parser("serve", allow_abbrev=False,
                           help="always-on inference service "
                                "(featurenet_tpu.serve): HTTP/1.1 "
                                "keep-alive front end feeding a "
                                "continuous batcher over a ladder of "
                                "pre-built serving executables; "
                                "POST /predict with raw STL bytes, "
                                "POST /predict_voxels_stream pipelines "
                                "length-prefixed voxel frames over one "
                                "socket (one JSON line per frame), "
                                "GET /stats for counters; overload "
                                "fast-rejects with a structured 503")
    p_srv.add_argument("--checkpoint-dir", required=True)
    p_srv.add_argument("--config", default=None,
                       help="only needed for legacy checkpoints without a "
                            "persisted config.json")
    p_srv.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="serving weight precision (see `infer`; "
                            "default: the config's serve_precision)")
    p_srv.add_argument("--buckets", default="1,4,16,64",
                       help="comma list of compiled batch shapes (the "
                            "bucket ladder); every one is built AOT at "
                            "startup so no request ever pays an XLA "
                            "compile (default 1,4,16,64)")
    p_srv.add_argument("--max-wait-ms", type=float, default=5.0,
                       dest="max_wait_ms",
                       help="continuous-batching flush deadline: a batch "
                            "dispatches when the largest bucket fills OR "
                            "the oldest request has waited this long "
                            "(default 5)")
    p_srv.add_argument("--queue-limit", type=int, default=64,
                       dest="queue_limit",
                       help="admission bound: requests beyond this queue "
                            "depth are fast-rejected with a structured "
                            "overload response instead of queueing "
                            "without bound (default 64)")
    p_srv.add_argument("--batch-queue-limit", type=int, default=None,
                       dest="batch_queue_limit",
                       help="per-lane admission cap for batch-priority "
                            "requests (X-Featurenet-Priority: batch): "
                            "the batch lane rejects at this depth even "
                            "while the global queue has room, so under "
                            "pressure batch sheds FIRST (default: half "
                            "of --queue-limit)")
    p_srv.add_argument("--replica-id", default=None, dest="replica_id",
                       help="this replica's fleet identity: echoed in "
                            "overload error bodies and /healthz so the "
                            "fleet router (or a client holding a 503) "
                            "can name WHICH backend answered; set by "
                            "`cli fleet` on each child")
    p_srv.add_argument("--heartbeat-file", dest="heartbeat_file",
                       help="touch this file once a second while the "
                            "service is ready (the fleet replica "
                            "manager's liveness protocol — the shared "
                            "heartbeat/stall state machine that also "
                            "watches training children)")
    p_srv.add_argument("--inject-faults", dest="inject_faults",
                       help="chaos spec (see `train --inject-faults`); "
                            "serving sites: replica_slow@request=N "
                            "drags this replica's Nth forward by the "
                            "latency-injection sleep")
    # Internal: which per-host event stream this process owns (the fleet
    # launcher gives each replica its own stream; the router keeps 0).
    p_srv.add_argument("--process-index", type=int, default=None,
                       dest="process_index", help=argparse.SUPPRESS)
    p_srv.add_argument("--host", default="127.0.0.1")
    p_srv.add_argument("--port", type=int, default=8000,
                       help="HTTP port (0 = ephemeral; the bound port is "
                            "printed in the startup line)")
    p_srv.add_argument("--slo-p99-ms", type=float, default=250.0,
                       dest="slo_p99_ms",
                       help="end-to-end p99 latency SLO: installs "
                            "'serving_p99_ms>SLO:critical' and "
                            "'queue_wait_ms_p99>SLO' alert rules over "
                            "the serving windows (default 250; "
                            "--alert-rules replaces them entirely)")
    p_srv.add_argument("--alert-rules", dest="alert_rules",
                       help="full custom rule spec (see `infer "
                            "--alert-rules`); replaces the --slo-p99-ms "
                            "defaults")
    p_srv.add_argument("--duration-s", type=float, default=None,
                       dest="duration_s",
                       help="serve for this many seconds then drain and "
                            "exit (default: run until SIGTERM/SIGINT)")
    p_srv.add_argument("--drain", action="store_true",
                       help="gate the exit code on the SLO at drain time: "
                            "exit 2 when a serving alert is still "
                            "unresolved after the final flush (CI "
                            "latency gate); without this flag the drain "
                            "verdict is reported but the exit stays 0")
    p_srv.add_argument("--trace-sample", type=float, dest="trace_sample",
                       help="request-tracing sample rate in [0,1] (see "
                            "`train --trace-sample`); rejections, "
                            "errors, and SLO breaches are always "
                            "sampled (default: the checkpoint config's "
                            "trace_sample, itself 1.0)")
    p_srv.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: serve_batch/"
                            "overload events, per-request trace "
                            "timelines, window summaries, alert "
                            "fire/resolve pairs (see `cli report`)")
    p_srv.add_argument("--exec-cache-dir", dest="exec_cache_dir",
                       help="persistent AOT executable cache: the bucket "
                            "ladder's warmup deserializes instead of "
                            "compiling on later cold starts")
    p_srv.add_argument("--quality", action="store_true",
                       help="model-quality telemetry (classify "
                            "checkpoints): per-request top-1 confidence, "
                            "top1-top2 margin, and prediction entropy "
                            "feed rolling windows (confidence_p50 etc. "
                            "in /metrics, dash, and the report) and "
                            "install the confidence-collapse alert rule")
    p_srv.add_argument("--quality-baseline", dest="quality_baseline",
                       help="pinned prediction-mix baseline "
                            "(quality_baseline.json from `cli "
                            "pin-quality`): enables --quality and the "
                            "quality_drift_score windows + drift alert "
                            "rule — total-variation distance of the "
                            "rolling predicted-class histogram vs this "
                            "baseline")
    p_srv.add_argument("--capture", action="store_true",
                       help="flight recorder: keep a bounded, sampled "
                            "JSONL ring of served requests (bit-packed "
                            "voxels + trace id + prediction + "
                            "confidence) under <run-dir>/capture; "
                            "rejections, errors, low-confidence "
                            "predictions, and SLO breaches are always "
                            "captured — `cli replay` re-scores the ring "
                            "against a candidate")
    p_srv.add_argument("--capture-sample", type=float, default=None,
                       dest="capture_sample",
                       help="deterministic capture rate in [0,1] for "
                            "healthy traffic (trace-id hash, so a fleet "
                            "agrees without coordination; default 0.05); "
                            "forced reasons ignore it")
    p_srv.add_argument("--capture-dir", dest="capture_dir",
                       help="capture ring directory (default: "
                            "<run-dir>/capture; implies --capture)")
    p_flt = sub.add_parser("fleet", allow_abbrev=False,
                           help="elastic serving fleet "
                                "(featurenet_tpu.fleet): N supervised "
                                "`cli serve` replicas behind one router "
                                "— health-gated least-queue routing over "
                                "pooled keep-alive channels (forwards "
                                "and /healthz probes share fleet.pool), "
                                "overload spillover, re-submit-once on "
                                "replica loss, priority-lane shedding, "
                                "Retry-After backoff, advisory "
                                "fleet_scale verdicts")
    p_flt.add_argument("--checkpoint-dir", default=None,
                       help="the checkpoint every replica serves "
                            "(required to launch a fleet; the `rollout` "
                            "subcommand instead names its candidate "
                            "positionally)")
    p_flt.add_argument("--replicas", type=int, default=2,
                       help="serving replicas to run (default 2); each "
                            "is a supervised `cli serve --port 0` child "
                            "that rejoins the roster only after its "
                            "/healthz turns ready")
    p_flt.add_argument("--buckets", default="1,4,16,64",
                       help="per-replica bucket ladder (see `serve "
                            "--buckets`)")
    p_flt.add_argument("--max-wait-ms", type=float, default=5.0,
                       dest="max_wait_ms",
                       help="per-replica flush deadline (see `serve`)")
    p_flt.add_argument("--queue-limit", type=int, default=64,
                       dest="queue_limit",
                       help="per-replica admission bound (see `serve`)")
    p_flt.add_argument("--batch-shed-depth", type=int, default=8,
                       dest="batch_shed_depth",
                       help="router-level batch-lane pressure bar: a "
                            "batch request is forwarded only to a "
                            "replica whose load score sits under this; "
                            "above it on every replica, batch sheds "
                            "immediately with Retry-After (default 8)")
    p_flt.add_argument("--host", default="127.0.0.1")
    p_flt.add_argument("--port", type=int, default=8000,
                       help="router HTTP port (0 = ephemeral; printed "
                            "in the startup line)")
    p_flt.add_argument("--slo-p99-ms", type=float, default=250.0,
                       dest="slo_p99_ms",
                       help="fleet end-to-end p99 SLO: drives the "
                            "router's serving alert rules and the "
                            "advisory fleet_scale verdicts "
                            "(default 250)")
    p_flt.add_argument("--slos", default=None,
                       help="burn-rate SLO objectives, comma-separated "
                            "'metric<threshold@objective%%[:severity]' "
                            "fragments (e.g. 'serving_p99_ms<250@99%%'); "
                            "default: one p99 objective derived from "
                            "--slo-p99-ms")
    p_flt.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="replica serving precision (see `serve`)")
    p_flt.add_argument("--duration-s", type=float, default=None,
                       dest="duration_s",
                       help="serve for this long then drain and exit "
                            "(default: until SIGTERM/SIGINT)")
    p_flt.add_argument("--drain", action="store_true",
                       help="gate the exit code on the drain verdict: "
                            "exit 2 on an unresolved fleet serving "
                            "alert OR any dropped admitted request")
    p_flt.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: the router owns "
                            "stream 0 (fleet_* events, roster "
                            "membership.json, scale verdicts); each "
                            "replica writes events.<slot+1>.jsonl into "
                            "the same dir")
    p_flt.add_argument("--exec-cache-dir", dest="exec_cache_dir",
                       help="fleet-SHARED persistent executable cache: "
                            "the first replica's compiles warm every "
                            "later replica and every respawn — rejoin "
                            "is seconds, not minutes")
    p_flt.add_argument("--trace-sample", type=float, dest="trace_sample",
                       help="replica request-tracing sample rate (see "
                            "`serve --trace-sample`)")
    p_flt.add_argument("--inject-faults", dest="inject_faults",
                       help="chaos spec (featurenet_tpu.faults): "
                            "replica_loss@request=N SIGKILLs a live "
                            "replica at the router's Nth routed "
                            "request; replica_slow@request=N drags one "
                            "replica's Nth forward; spawn_fail fires "
                            "in the manager — child-side sites fire in "
                            "the replicas")
    p_flt.add_argument("--quality", action="store_true",
                       help="per-replica model-quality telemetry (see "
                            "`serve --quality`); the scraper folds the "
                            "confidence windows into the fleet tsdb")
    p_flt.add_argument("--quality-baseline", dest="quality_baseline",
                       help="pinned prediction-mix baseline passed to "
                            "every replica (see `serve "
                            "--quality-baseline`)")
    p_flt.add_argument("--capture", action="store_true",
                       help="per-replica flight recorder: each replica "
                            "keeps its own ring under "
                            "<run-dir>/capture/replica<slot> (see "
                            "`serve --capture`)")
    p_flt.add_argument("--capture-sample", type=float, default=None,
                       dest="capture_sample",
                       help="per-replica capture rate (see `serve "
                            "--capture-sample`)")
    p_flt.add_argument("--autoscale", action="store_true",
                       help="ACT on the scale verdicts instead of only "
                            "advising: a manager-owned control thread "
                            "adds a replica on a sustained add verdict "
                            "and drains+parks one on a sustained shed "
                            "verdict, with hysteresis and a post-action "
                            "cooldown so a flapping verdict never "
                            "thrashes the roster")
    p_flt.add_argument("--min-replicas", type=int, default=1,
                       dest="min_replicas",
                       help="autoscale floor: shed verdicts never take "
                            "the roster below this (default 1)")
    p_flt.add_argument("--max-replicas", type=int, default=None,
                       dest="max_replicas",
                       help="autoscale ceiling: add verdicts never take "
                            "the roster above this (default: "
                            "--replicas + 2)")
    p_flt.add_argument("--scale-hysteresis", type=int, default=3,
                       dest="scale_hysteresis",
                       help="consecutive identical actionable verdicts "
                            "required before the autoscaler moves the "
                            "roster (default 3)")
    p_flt.add_argument("--scale-cooldown-s", type=float, default=30.0,
                       dest="scale_cooldown_s",
                       help="minimum seconds since the LAST ACTION "
                            "before the autoscaler acts again — flap "
                            "damping measured from actions, not verdict "
                            "edges (default 30)")
    flt_sub = p_flt.add_subparsers(dest="fleet_cmd", metavar="{rollout}")
    p_rol = flt_sub.add_parser(
        "rollout", allow_abbrev=False,
        help="zero-downtime rolling weight rollout: swap a LIVE fleet "
             "(launched with `cli fleet --capture --run-dir D`) onto a "
             "candidate checkpoint one replica at a time — each replica "
             "is replay-canaried against its own capture ring, drained "
             "through the router's spillover path, hot-swapped via "
             "POST /admin/reload, and verified on /healthz; a canary "
             "failure, swap refusal, or replica death mid-rollout rolls "
             "every already-swapped replica back to its old checkpoint "
             "and EXITS 2")
    p_rol.add_argument("rollout_checkpoint_dir",
                       metavar="checkpoint_dir",
                       help="the CANDIDATE checkpoint directory to roll "
                            "the fleet onto")
    # SUPPRESS: the fleet-level --run-dir default (None) must survive
    # when the operator puts the flag before the subcommand token —
    # a subparser default would clobber the already-parsed value.
    p_rol.add_argument("--run-dir", dest="run_dir",
                       default=argparse.SUPPRESS,
                       help="the LIVE fleet's observability directory: "
                            "the router address is read from its event "
                            "stream, capture rings from "
                            "<run-dir>/capture/replica<slot>, and the "
                            "rollout_* events land in the same run")
    p_rol.add_argument("--min-agreement", type=float, default=0.967,
                       dest="min_agreement",
                       help="per-replica replay-canary gate: the "
                            "candidate must match at least this "
                            "fraction of the replica's captured "
                            "predictions or the rollout rolls back "
                            "(default 0.967, the paper's accuracy bar)")
    p_rol.add_argument("--batch", type=int, default=32,
                       help="canary scoring batch size (default 32)")
    p_rol.add_argument("--config", default=None,
                       help="only needed for legacy candidate "
                            "checkpoints without a persisted "
                            "config.json")
    p_rol.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="candidate scoring precision for the canary "
                            "(default: the candidate config's "
                            "serve_precision)")
    p_rol.add_argument("--swap-timeout-s", type=float, default=120.0,
                       dest="swap_timeout_s",
                       help="per-replica /admin/reload deadline "
                            "(default 120)")
    p_rol.add_argument("--converge-timeout-s", type=float, default=120.0,
                       dest="converge_timeout_s",
                       help="bounded wait for the roster's /healthz "
                            "version tags to converge after the last "
                            "swap or after a rollback (default 120)")
    p_rpq = sub.add_parser(
        "pin-quality", allow_abbrev=False,
        help="pin a predicted-class-mix baseline "
             "(quality_baseline.json) from an eval pass of a classify "
             "checkpoint over the synthetic set — the reference "
             "distribution `serve --quality-baseline` scores live "
             "traffic against (quality_drift_score = total-variation "
             "distance, alert rule quality_drift_score_p50>0.25)")
    p_rpq.add_argument("--checkpoint-dir", required=True)
    p_rpq.add_argument("--config", default=None,
                       help="only needed for legacy checkpoints without "
                            "a persisted config.json")
    p_rpq.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="score the eval pass at this serving "
                            "precision (default: the config's "
                            "serve_precision)")
    p_rpq.add_argument("--n", type=int, default=512,
                       help="eval parts to score (default 512)")
    p_rpq.add_argument("--seed", type=int, default=0,
                       help="synthetic-set seed (default 0)")
    p_rpq.add_argument("--batch", type=int, default=32,
                       help="scoring batch size (default 32)")
    p_rpq.add_argument("--out", default=None,
                       help="baseline artifact path (default: "
                            "<checkpoint-dir>/quality_baseline.json)")
    p_rpl = sub.add_parser(
        "replay", allow_abbrev=False,
        help="replay canary: re-score a flight-recorder capture ring "
             "(`serve --capture`) against a candidate — a different "
             "checkpoint, --precision, or --conv-backend — through the "
             "same AOT serving program path, and report agreement vs "
             "the recorded predictions, the per-class flip matrix, "
             "confidence deltas, and scoring latency; EXITS 2 below "
             "--min-agreement, so CI can gate a rollout on real "
             "captured traffic")
    p_rpl.add_argument("capture_dir",
                       help="capture ring directory (e.g. "
                            "<run-dir>/capture)")
    p_rpl.add_argument("--checkpoint-dir", required=True,
                       help="the CANDIDATE checkpoint to re-score with")
    p_rpl.add_argument("--config", default=None,
                       help="only needed for legacy checkpoints without "
                            "a persisted config.json")
    p_rpl.add_argument("--precision", choices=["fp32", "bf16", "int8"],
                       default=None,
                       help="candidate serving precision (see `infer "
                            "--precision`)")
    p_rpl.add_argument("--conv-backend",
                       choices=["xla", "pallas", "hybrid_dw", "fused33"],
                       help="candidate conv lowering (non-identity: the "
                            "same trained weights through a different "
                            "backend)")
    p_rpl.add_argument("--batch", type=int, default=32,
                       help="scoring batch size — one AOT program, "
                            "built at warmup; replay then runs ZERO "
                            "compiles (default 32)")
    p_rpl.add_argument("--min-agreement", type=float, default=0.967,
                       dest="min_agreement",
                       help="agreement gate: exit 2 when the candidate "
                            "matches fewer than this fraction of the "
                            "ring's recorded predictions (default "
                            "0.967, the paper's accuracy bar)")
    p_rpl.add_argument("--run-dir", dest="run_dir",
                       help="observability directory: the replay_verdict "
                            "event (agreement, n, ok) lands in this "
                            "run's stream so the report's quality "
                            "section shows the canary outcome")
    args = parser.parse_args(argv)

    if args.cmd == "programs":
        # The registry's enumeration surface: list what a config compiles
        # (no backend work), or --warm to build it all AOT — the same path
        # `infer` warms its serving program through at startup.
        from featurenet_tpu.config import get_config
        from featurenet_tpu.runtime import list_programs

        prog_over = {}
        if args.exec_cache_dir:
            prog_over["exec_cache_dir"] = args.exec_cache_dir
        if getattr(args, "train_precision", None):
            prog_over["train_precision"] = args.train_precision
        if getattr(args, "serve_precision", None):
            prog_over["serve_precision"] = args.serve_precision
        cfg = get_config(args.config, **prog_over)
        if args.run_dir:
            from featurenet_tpu import obs
            from featurenet_tpu.config import config_to_dict

            obs.init_run(args.run_dir, config=config_to_dict(cfg),
                         extra={"cmd": "programs"})
        for row in list_programs(cfg):
            print(json.dumps(row))
        if args.warm:
            from featurenet_tpu.runtime import Runtime

            built = Runtime(cfg).warmup()
            print(json.dumps({"warmup": built}))
        if args.run_dir:
            from featurenet_tpu import obs

            obs.close_run()
        return

    if args.cmd == "bench-history":
        # Cross-round bench trajectory: stdlib-only, like report — the
        # table must render where no backend exists.
        from featurenet_tpu.obs.bench_history import (
            format_history,
            format_trend_gate,
            load_rounds,
            trend_gate,
        )

        rows = load_rounds(args.bench_dir)
        if args.as_json:
            for row in rows:
                # Underscore keys are internal (the trend gate's full
                # value set); the JSON schema stays the table's.
                print(json.dumps({k: v for k, v in row.items()
                                  if not k.startswith("_")}))
        else:
            print(format_history(rows, bench_dir=args.bench_dir))
        if args.trend_gate:
            result = trend_gate(rows)
            if args.as_json:
                print(json.dumps({"trend_gate": result}))
            else:
                print(format_trend_gate(result))
            if not result["ok"]:
                raise SystemExit(2)
        return

    if args.cmd == "dash":
        # The fleet dashboard: stdlib-only reads over <run_dir>/tsdb —
        # works identically against a live fleet (the scraper is still
        # appending) and a finished run dir.
        from featurenet_tpu.obs.dash import render_frame

        def frame() -> str:
            return render_frame(args.run_dir, window_s=args.window_s,
                                slos=args.slos)

        try:
            if args.once:
                print(frame(), end="")
                return
            while True:
                # ANSI clear + home, then the frame: dumb enough to
                # pipe, no curses dependency.
                print("\x1b[2J\x1b[H" + frame(), end="", flush=True)
                time.sleep(args.interval)
        except KeyboardInterrupt:
            print()
        except ValueError as e:
            raise SystemExit(f"dash: {e}")
        return

    if args.cmd == "incident":
        # Incident post-mortems: stdlib-only reads over the bundle
        # directory — degraded bundles (torn manifest, pruned pieces)
        # render with an explicit "missing" section, never a traceback.
        from featurenet_tpu.obs import incidents as _incidents

        if args.action == "list":
            entries = _incidents.list_incidents(args.run_dir)
            if args.as_json:
                print(json.dumps(entries, indent=1, default=str))
                return
            if not entries:
                print("no incident bundles under "
                      f"{_incidents.incidents_dir(args.run_dir)}")
                return
            for e in entries:
                dur = (f"  duration={e['duration_s']:.3f}s"
                       if isinstance(e.get("duration_s"), (int, float))
                       else "")
                print(f"{e['id']}  rule={e.get('rule', '?')}  "
                      f"severity={e.get('severity', '?')}  "
                      f"state={e.get('state', '?')}{dur}")
            return
        incident_id = args.incident_id
        if incident_id is None:
            entries = _incidents.list_incidents(args.run_dir)
            if not entries:
                raise SystemExit(
                    "incident show: no bundles under "
                    f"{_incidents.incidents_dir(args.run_dir)}")
            incident_id = entries[-1]["id"]
        import os as _os

        bundle = _incidents.load_bundle(args.run_dir, incident_id)
        if not _os.path.isdir(bundle["dir"]):
            raise SystemExit(
                f"incident show: no bundle {incident_id!r} under "
                f"{_incidents.incidents_dir(args.run_dir)}")
        if args.as_json:
            print(json.dumps(bundle, indent=1, default=str))
        else:
            print(_incidents.format_incident(bundle), end="")
        return

    if args.cmd == "lint":
        # Static analysis of the package itself: stdlib + ast only, no
        # backend — must run in CI preambles and on bare laptops.
        from featurenet_tpu.analysis import (format_findings, format_sarif,
                                             run_lint)

        fmt = args.fmt or ("json" if args.as_json else "text")
        try:
            findings = run_lint(args.path, rules=args.rules or None,
                                changed_only=args.changed)
        except (ValueError, OSError, SyntaxError) as e:
            raise SystemExit(f"lint: {e}")
        if fmt == "sarif":
            print(format_sarif(findings))
        else:
            print(format_findings(findings, as_json=(fmt == "json")))
        if findings:
            raise SystemExit(2)
        return

    if args.cmd == "report":
        # Post-hoc analysis of a finished (or crashed) run: stdlib-only —
        # must work where the backend that produced the run is long gone.
        import os

        from featurenet_tpu.obs.report import (
            build_report,
            discover_event_files,
            follow_report,
            format_report,
            load_events,
            load_manifest,
            validate_events,
        )

        files = discover_event_files(args.run_dir)
        if not files:
            # Say what IS here, not just what isn't: an empty dir, a
            # per-host-only layout typo, or a wrong path each read
            # differently to the operator.
            if not os.path.isdir(args.run_dir):
                raise SystemExit(
                    f"report: {args.run_dir!r} is not a directory — was "
                    "the run started with --run-dir pointing here?"
                )
            names = sorted(os.listdir(args.run_dir))
            raise SystemExit(
                "report: no event stream (events.jsonl or "
                f"events.<i>.jsonl) in {args.run_dir!r} — "
                + (f"found: {', '.join(names)}" if names
                   else "the directory is empty")
                + "; was the run started with --run-dir pointing here?"
            )
        if args.follow:
            try:
                follow_report(args.run_dir, interval=args.interval)
            except KeyboardInterrupt:
                print()  # clean ^C: no traceback over the live view
            return
        events, bad = load_events(args.run_dir)
        if args.request_trace:
            from featurenet_tpu.obs.report import (
                format_request_timeline,
                request_timeline,
            )

            tl = request_timeline(events, args.request_trace)
            if args.as_json:
                print(json.dumps(tl, indent=1, default=str))
            else:
                print(format_request_timeline(tl))
            if not tl["found"]:
                raise SystemExit(2)
            return
        if args.validate:
            findings = validate_events(events, bad_lines=bad)
            for f in findings:
                print(json.dumps(f, default=str))
            if findings:
                raise SystemExit(
                    f"validate: {len(findings)} finding(s) across "
                    f"{len(events)} event(s) in {len(files)} stream(s)"
                )
            print(json.dumps({
                "validate": "ok", "events": len(events),
                "streams": len(files),
            }))
            return
        rep = build_report(events, load_manifest(args.run_dir),
                           bad_lines=bad)
        # Fleet runs leave a <run_dir>/tsdb behind (the scraper's store);
        # fold its per-replica timeline in — absent for non-fleet runs.
        from featurenet_tpu.obs.report import fleet_timeline_section

        timeline = fleet_timeline_section(args.run_dir)
        if timeline is not None:
            rep["fleet_timeline"] = timeline
        if args.as_json:
            print(json.dumps(rep, indent=1, default=str))
        else:
            print(format_report(rep))
        if args.trace:
            from featurenet_tpu.obs.spans import chrome_trace

            with open(args.trace, "w") as fh:
                json.dump(chrome_trace(events), fh)
            print(json.dumps({"trace": args.trace}))
        if args.gate:
            from featurenet_tpu.obs.gates import (
                evaluate_gates,
                format_gates,
                load_baseline,
                report_gate_values,
            )

            result = evaluate_gates(
                report_gate_values(rep), load_baseline(args.gate)
            )
            print(format_gates(result, args.gate))
            if not result["ok"]:
                raise SystemExit(2)
        return

    if (
        args.cmd == "train"
        and getattr(args, "restart_every_steps", None)
        and not getattr(args, "supervise", False)
        and not getattr(args, "supervised_child", False)
        and not getattr(args, "elastic", False)
    ):
        # Without a supervisor, the child checkpoints and exits 75 at the
        # first segment boundary and nothing respawns it — the run silently
        # stops mid-training. Refuse at parse time (ADVICE r2; the sidecar
        # path already strips restart_every_steps on unsupervised resume).
        raise SystemExit(
            "--restart-every requires --supervise: a segmented run exits "
            "(code 75) at every segment boundary and only the supervisor "
            "respawns it — without one, training silently stops at step N"
        )

    if (
        args.cmd == "train"
        and getattr(args, "elastic", False)
        and not getattr(args, "supervised_child", False)
    ):
        import sys

        from featurenet_tpu.config import get_config
        from featurenet_tpu.elastic import ElasticCoordinator, heartbeat_path
        from featurenet_tpu.train.supervisor import child_argv_from_cli

        if getattr(args, "supervise", False):
            raise SystemExit(
                "--elastic already supervises its world (it is the "
                "N-host generalization of --supervise) — drop --supervise"
            )
        if not args.checkpoint_dir:
            raise SystemExit(
                "--elastic requires --checkpoint-dir: a re-formed mesh "
                "resumes from the latest checkpoint, not from scratch"
            )
        if not getattr(args, "run_dir", None):
            raise SystemExit(
                "--elastic requires --run-dir: the membership file, "
                "per-slot heartbeats, and the coordinator's event stream "
                "live there"
            )
        # The planner's feasibility input: refuse an undividable global
        # batch here, not in N spawned children — plan_world would
        # otherwise *silently* form generation 0 below the requested
        # strength (it picks the largest feasible world) and the
        # operator would pay for provisioned hosts that never join.
        cfg = get_config(args.config or "pod64", **_overrides(args))
        if cfg.global_batch % (args.world_size * args.local_devices):
            raise SystemExit(
                f"--elastic: global batch {cfg.global_batch} is not "
                f"divisible by world-size {args.world_size} x "
                f"local-devices {args.local_devices} = "
                f"{args.world_size * args.local_devices} device(s) — the "
                "coordinator preserves the global batch across re-forms, "
                "so the full-strength world could never form; adjust "
                "--global-batch or the world shape"
            )
        raw = argv if argv is not None else sys.argv[1:]
        run_dir = args.run_dir

        def spawn(members, rank, generation, port):
            child = child_argv_from_cli(
                raw, heartbeat_path(run_dir, members[rank])
            )
            return child + [
                "--elastic-rank", str(rank),
                "--elastic-world", str(len(members)),
                "--elastic-port", str(port),
                "--elastic-generation", str(generation),
            ]

        if getattr(args, "inject_faults", None):
            # Same split as --supervise: the coordinator process installs
            # only its own site; the child-side sites must fire in the
            # training processes.
            from featurenet_tpu import faults

            try:
                faults.install(args.inject_faults, state_dir=run_dir,
                               only={"spawn_fail"})
            except ValueError as e:
                raise SystemExit(f"--inject-faults: {e}")
        result = ElasticCoordinator(
            args.world_size,
            spawn,
            run_dir,
            min_world_size=args.min_world_size or 1,
            global_batch=cfg.global_batch,
            local_devices=args.local_devices,
            stall_timeout_s=args.stall_timeout,
            max_reforms=args.max_restarts,
            readmit=args.readmit,
        ).run()
        print(json.dumps({"elastic": {
            "exit_code": result.exit_code,
            "generations": result.generations,
            "reforms": result.reforms,
            "losses": result.losses,
            "rejoins": result.rejoins,
            "planned": result.planned,
        }}))
        raise SystemExit(result.exit_code)

    if args.cmd == "train" and getattr(args, "supervise", False):
        import os
        import sys
        import tempfile

        from featurenet_tpu.train.supervisor import (
            child_argv_from_cli,
            supervise,
        )

        if not args.checkpoint_dir:
            raise SystemExit(
                "--supervise requires --checkpoint-dir: a restarted run "
                "must resume, not silently retrain from scratch"
            )
        # Honor a user-supplied heartbeat path (external monitoring may be
        # watching it); otherwise use a private temp file, removed on exit.
        hb, hb_is_temp = args.heartbeat_file, False
        if not hb:
            fd, hb = tempfile.mkstemp(prefix="fn_heartbeat_")
            os.close(fd)
            hb_is_temp = True
        if getattr(args, "inject_faults", None):
            # The spec reaches every child unmodified (--inject-faults is
            # an override flag; child_argv_from_cli strips only the
            # supervision flags), and one-shot markers in run_dir keep a
            # fault from re-firing across respawns. The supervisor process
            # itself installs ONLY its own site (spawn_fail): child-side
            # sites firing here — e.g. sink_enospc on the supervisor's
            # EventSink, which also counts emits — would consume the
            # one-shot marker without ever exercising the recovery path
            # under test.
            from featurenet_tpu import faults

            try:
                faults.install(args.inject_faults,
                               state_dir=getattr(args, "run_dir", None),
                               only={"spawn_fail"})
            except ValueError as e:
                raise SystemExit(f"--inject-faults: {e}")
        raw = argv if argv is not None else sys.argv[1:]
        try:
            result = supervise(
                child_argv_from_cli(raw, hb),
                stall_timeout_s=args.stall_timeout,
                max_restarts=args.max_restarts,
                heartbeat_file=hb,
                # The child's --run-dir flows through child_argv_from_cli;
                # the supervisor appends its own restart/stall events to
                # the same run log.
                run_dir=getattr(args, "run_dir", None),
            )
        finally:
            if hb_is_temp:
                try:
                    os.unlink(hb)
                except OSError:
                    pass
        raise SystemExit(result.exit_code)

    if args.distributed:
        import jax

        jax.distributed.initialize()
    elif getattr(args, "elastic_rank", None) is not None \
            and (getattr(args, "elastic_world", None) or 0) > 1:
        # Elastic child: join this generation's explicit world (the
        # coordinator allocated the port; TPU-env discovery would hand
        # back the FULL pod shape, not the surviving one).
        import jax

        try:
            # CPU worlds (CI, laptop demos) need gloo for cross-process
            # collectives on this jax line; safe here because the
            # distributed client below always exists, and a TPU world's
            # collectives ride ICI/DCN regardless. Newer jax dropped the
            # knob (cross-process CPU works natively) — hence the guard.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except AttributeError:
            pass
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{args.elastic_port}",
            num_processes=args.elastic_world,
            process_id=args.elastic_rank,
        )

    if args.cmd == "bench":
        import bench

        bench.main()
        return
    if args.cmd == "export-data":
        from featurenet_tpu.data.offline import export_synthetic_cache

        if args.run_dir:
            from featurenet_tpu import obs

            obs.init_run(args.run_dir, extra={"cmd": "export-data"},
                         process_index=0)
        pr = args.param_range
        if pr and "," in pr:
            pr = tuple(float(v) for v in pr.split(","))
        mj = args.margin_jitter
        if mj:
            mj = tuple(float(v) for v in mj.split(","))
        index = export_synthetic_cache(
            args.out, per_class=args.per_class,
            resolution=args.resolution, seed=args.seed, param_range=pr,
            mesh_pose=args.mesh_pose, margin_jitter=mj,
        )
        print(json.dumps({"exported": index["counts"],
                          "param_range": index.get("param_range"),
                          "mesh_pose": index.get("mesh_pose"),
                          "margin_jitter": index.get("margin_jitter")}))
        return
    if args.cmd == "eval-ood":
        from featurenet_tpu.ood import evaluate_ood, evaluate_ood_seg
        from featurenet_tpu.train.checkpoint import load_run_config

        saved = load_run_config(args.checkpoint_dir)
        if saved is not None and saved.task == "segment":
            if args.canonicalize or args.tta_rotations:
                raise SystemExit(
                    "eval-ood: --canonicalize/--tta are classify-only "
                    "(per-voxel labels would need the inverse warp)"
                )
            rows = evaluate_ood_seg(
                args.checkpoint_dir, parts=args.seg_parts, seed=args.seed,
                families=args.families.split(",") if args.families else None,
            )
        else:
            rows = evaluate_ood(
                args.checkpoint_dir, per_class=args.per_class,
                seed=args.seed,
                families=args.families.split(",") if args.families else None,
                canonicalize=args.canonicalize,
                tta_rotations=args.tta_rotations,
            )
        for r in rows:
            print(json.dumps(r))
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(rows, fh, indent=1)
        return
    if args.cmd == "recalibrate":
        import dataclasses as _dc

        from featurenet_tpu.train.checkpoint import (
            CheckpointManager,
            load_run_config,
        )
        from featurenet_tpu.train.loop import Trainer

        import os as _os

        if args.batches < 1:
            raise SystemExit(
                "recalibrate: --batches must be >= 1 (a 0-batch run would "
                "save an unchanged copy labeled as recalibrated)"
            )
        if (_os.path.realpath(args.out_dir)
                == _os.path.realpath(args.checkpoint_dir)):
            raise SystemExit(
                "recalibrate: --out-dir must differ from --checkpoint-dir "
                "(the source checkpoint is never modified)"
            )
        saved = load_run_config(args.checkpoint_dir)
        if saved is None:
            raise SystemExit(
                "recalibrate: no persisted config next to this checkpoint"
            )
        # Host-stream-only build: recalibration never runs a train step,
        # so skip the resident-split upload and fused-dispatch compiles.
        # augment=False: the stats must come from the CLEAN stream — for
        # streamed segment (and host-augmented classify) the dataset would
        # otherwise rotate every sample in the workers.
        cfg = _dc.replace(
            saved,
            checkpoint_dir=args.checkpoint_dir,
            hbm_cache=False,
            steps_per_dispatch=1,
            heartbeat_file=None,
            run_dir=None,
            restart_every_steps=None,
            inject_faults=None,
            # Recalibration restores from checkpoint_dir (resume wins over
            # warm start) — re-running the persisted init_from would pay
            # the warm-start restore for nothing, and crash outright when
            # that source dir has since moved (advisor r5).
            init_from=None,
            data_cache=args.rec_data_cache or saved.data_cache,
            augment=False,
            # A mixed-training run's affine config is irrelevant here (no
            # train step runs) but must not trip the validate-time guards
            # when augment_affine relied on hbm_cache for device_augment.
            augment_affine=False,
            augment_affine_prob=1.0,
            augment_ramp_steps=0,
            augment_affine_rotate=True,
            augment_scale_range=(0.7, 1.05),
            augment_translate_vox=0.0,
        ).validate()
        trainer = Trainer(cfg)
        at = trainer.resume_if_available()
        if not at:
            raise SystemExit("recalibrate: no checkpoint to restore")
        trainer.recalibrate_bn(args.batches)
        # Persist the ORIGINAL run config (not the host-stream eval build):
        # a later resume/fine-tune from out-dir must reconstruct the same
        # experiment (hbm/affine/dispatch settings), only with fresh stats.
        out = CheckpointManager(
            args.out_dir,
            config=_dc.replace(saved, checkpoint_dir=args.out_dir),
        )
        out.save(trainer.state)
        out.wait()
        out.close()
        print(json.dumps({
            "recalibrated": args.out_dir,
            "from_step": at,
            "batches": args.batches,
        }))
        return
    if args.cmd == "export-seg-data":
        from featurenet_tpu.data.offline import export_seg_cache

        index = export_seg_cache(
            args.out, num_parts=args.num_parts,
            resolution=args.resolution, num_features=args.num_features,
            seed=args.seed, label_order=args.label_order,
        )
        print(json.dumps({
            "exported": sum(s["count"] for s in index["shards"]),
            "shards": len(index["shards"]),
        }))
        return
    if args.cmd == "export-stl-data":
        if args.seg:
            from featurenet_tpu.data.voxel_to_mesh import export_seg_stl_tree

            index = export_seg_stl_tree(
                args.out, num_parts=args.num_parts,
                resolution=args.resolution,
                num_features=args.num_features, seed=args.seed,
                label_order=args.label_order,
            )
            print(json.dumps({"exported": index["num_parts"],
                              "kind": "segment_stl"}))
            return
        from featurenet_tpu.data.voxel_to_mesh import export_stl_tree

        index = export_stl_tree(
            args.out, per_class=args.per_class,
            resolution=args.resolution, seed=args.seed,
        )
        print(json.dumps({"exported": index["counts"]}))
        return
    if args.cmd == "build-cache":
        import os

        if args.run_dir:
            from featurenet_tpu import obs

            obs.init_run(args.run_dir, extra={"cmd": "build-cache"},
                         process_index=0)
        # A segmentation tree (index kind "segment_stl") takes the sidecar-
        # aware ingest; a classification class-dir tree takes build_cache.
        tree = {}
        idx_path = os.path.join(args.stl_root, "index.json")
        if os.path.exists(idx_path):
            with open(idx_path) as fh:
                tree = json.load(fh)
        if tree.get("kind") == "segment_stl":
            from featurenet_tpu.data.offline import build_seg_cache

            if (args.resolution is not None
                    and args.resolution != tree.get("resolution")):
                raise SystemExit(
                    f"--resolution {args.resolution} contradicts the "
                    f"segmentation tree's sidecar resolution "
                    f"{tree.get('resolution')} — per-voxel labels only "
                    "exist at the exported grid; drop the flag"
                )
            index = build_seg_cache(args.stl_root, args.out,
                                    workers=args.workers)
            print(json.dumps({
                "built": sum(s["count"] for s in index["shards"]),
                "kind": "segment",
            }))
            return
        from featurenet_tpu.data.offline import build_cache

        index = build_cache(args.stl_root, args.out,
                            resolution=args.resolution or 64,
                            workers=args.workers)
        print(json.dumps({"built": index["counts"]}))
        return
    if args.cmd == "infer":
        import os

        import numpy as np

        from featurenet_tpu.config import get_config
        from featurenet_tpu.infer import Predictor, SegPrediction
        from featurenet_tpu.train.checkpoint import load_run_config

        saved = load_run_config(args.checkpoint_dir)
        if saved is not None:
            cfg = _cfg_from_checkpoint(saved, args)
        else:
            over = (
                {"resolution": args.resolution} if args.resolution else {}
            )
            cfg = _apply_arch_overrides(
                get_config(args.config or "pod64", **over), args
            )
        if args.seg_out and cfg.task != "segment":
            raise SystemExit(
                "--seg-out only applies to segmentation checkpoints "
                f"(config {cfg.name!r} has task={cfg.task!r}); it would "
                "silently produce no label grids"
            )
        if args.alert_rules and not getattr(args, "run_dir", None):
            raise SystemExit(
                "infer: --alert-rules needs --run-dir (no run, no "
                "windows — the rules would silently gate nothing)"
            )
        if getattr(args, "run_dir", None):
            from featurenet_tpu import obs
            from featurenet_tpu.config import config_to_dict

            obs.init_run(args.run_dir, config=config_to_dict(cfg))
            if args.alert_rules:
                # Replace init_run's default-rule aggregator with the
                # operator's serving SLO spec: these rules drive the
                # exit code below.
                from featurenet_tpu.obs import windows as _windows
                from featurenet_tpu.obs.alerts import parse_rules

                try:
                    rules = parse_rules(args.alert_rules)
                except ValueError as e:
                    raise SystemExit(f"--alert-rules: {e}")
                _windows.install(_windows.WindowAggregator(rules=rules))
        # Compile batch sized to the request: padding 1 STL to the default
        # 32 would run 32x the needed FLOPs (felt hardest by the
        # full-resolution segmentation decoder). Construction is the AOT
        # warmup: the serving program builds (or loads from the exec
        # cache) before the first STL is voxelized.
        pred = Predictor.from_checkpoint(
            args.checkpoint_dir, cfg, batch=min(32, len(args.stl)),
            precision=args.precision,
        )
        if args.seg_out:
            os.makedirs(args.seg_out, exist_ok=True)
        used_names: set = set()
        for r in pred.predict_stl(args.stl):
            if isinstance(r, SegPrediction):
                row = {"path": r.path, "voxel_counts": r.voxel_counts}
                if args.seg_out:
                    stem = os.path.splitext(os.path.basename(r.path))[0]
                    # Same-stem inputs from different dirs must not
                    # overwrite each other's grids.
                    name, i = f"{stem}_seg.npz", 1
                    while name in used_names:
                        name = f"{stem}_{i}_seg.npz"
                        i += 1
                    used_names.add(name)
                    out_path = os.path.join(args.seg_out, name)
                    np.savez_compressed(out_path, labels=r.labels)
                    row["labels_path"] = out_path
                print(json.dumps(row))
            else:
                print(json.dumps(dataclasses.asdict(r)))
        if getattr(args, "run_dir", None):
            # Flush the serving-latency window summaries (a batch of STLs
            # rarely outlives the emit period), read the SLO verdict, and
            # release the sink. An unresolved serving alert at this drain
            # point exits 2 — the CI latency gate (carried-over SLO
            # follow-on): `infer --run-dir D --alert-rules
            # 'serving_p99_ms>20'` fails the pipeline when the tail blew.
            from featurenet_tpu import obs
            from featurenet_tpu.obs import windows as _windows
            from featurenet_tpu.obs.alerts import is_serving_metric

            _windows.flush()
            stuck = [
                m for m in _windows.active_alerts() if is_serving_metric(m)
            ]
            obs.close_run()
            if stuck:
                print(json.dumps({"serving_alerts_active": stuck}))
                raise SystemExit(2)
        return

    if args.cmd == "pin-quality":
        import os

        import numpy as np

        from featurenet_tpu.data.synthetic import CLASS_NAMES, generate_batch
        from featurenet_tpu.infer import Predictor
        from featurenet_tpu.obs import quality as _quality

        if args.n < 1:
            raise SystemExit(f"pin-quality: --n must be >= 1, got {args.n}")
        pred = Predictor.from_checkpoint(
            args.checkpoint_dir, args.config,
            batch=min(args.batch, args.n), precision=args.precision,
        )
        if pred.cfg.task != "classify":
            raise SystemExit(
                "pin-quality: a drift baseline is a predicted-CLASS "
                f"distribution — task={pred.cfg.task!r} has none"
            )
        rng = np.random.default_rng(args.seed)
        counts = [0] * len(CLASS_NAMES)
        remaining = args.n
        while remaining > 0:
            k = min(remaining, max(args.batch, 1) * 8)
            grids = generate_batch(rng, k, pred.cfg.resolution)["voxels"]
            labels, _probs = pred.predict_voxels(grids)
            for lab in labels.tolist():
                counts[int(lab)] += 1
            remaining -= k
        out = args.out or os.path.join(
            args.checkpoint_dir, _quality.BASELINE_FILENAME
        )
        rec = _quality.save_baseline(
            out, counts, class_names=list(CLASS_NAMES),
            source={"checkpoint_dir": args.checkpoint_dir,
                    "n": args.n, "seed": args.seed,
                    "precision": pred.precision},
        )
        top = sorted(range(len(rec["dist"])),
                     key=lambda i: -rec["dist"][i])[:5]
        print(json.dumps({"quality_baseline": {
            "path": out, "n": rec["n"],
            "top": [{"class": CLASS_NAMES[i], "p": rec["dist"][i]}
                    for i in top],
        }}))
        return

    if args.cmd == "replay":
        import shutil
        import tempfile

        from featurenet_tpu import obs
        from featurenet_tpu.config import get_config
        from featurenet_tpu.data.synthetic import CLASS_NAMES
        from featurenet_tpu.infer import Predictor
        from featurenet_tpu.serve.recorder import read_captures
        from featurenet_tpu.train.checkpoint import load_run_config

        if not (0.0 <= args.min_agreement <= 1.0):
            raise SystemExit(
                f"replay: --min-agreement must be in [0, 1], got "
                f"{args.min_agreement}"
            )
        # Only answered requests carry a recorded prediction to agree
        # with; rejection/error captures are evidence for humans, not
        # for the canary.
        recs = [r for r in read_captures(args.capture_dir)
                if r.get("label") is not None]
        if not recs:
            raise SystemExit(
                f"replay: no re-scorable capture records under "
                f"{args.capture_dir!r} — the ring is missing, empty, or "
                "holds only rejections/errors"
            )
        saved = load_run_config(args.checkpoint_dir)
        cfg = _apply_arch_overrides(
            saved if saved is not None
            else get_config(args.config or "pod64"),
            args,
        )
        # The replay sink: the verdict event needs a live stream and the
        # zero-compile evidence needs the sink's program_compile counter
        # — a throwaway run_dir serves both when the operator gave none.
        own_run = not getattr(args, "run_dir", None)
        run_dir = args.run_dir or tempfile.mkdtemp(prefix="replay_")
        obs.init_run(run_dir, extra={"cmd": "replay"})
        try:
            # Construction is the warmup: ONE program at the scoring
            # batch builds (or loads from the exec cache) here — every
            # compile after this point is a canary failure in itself.
            pred = Predictor.from_checkpoint(
                args.checkpoint_dir, cfg,
                batch=min(args.batch, len(recs)),
                precision=args.precision,
            )
            if pred.cfg.task != "classify":
                raise SystemExit(
                    "replay: capture rings hold classify traffic — the "
                    f"candidate is task={pred.cfg.task!r}"
                )
            recs, labels, probs, score_ms, compiles = _score_capture_ring(
                pred, args.capture_dir, recs=recs
            )

            def _cls(c: int) -> str:
                return CLASS_NAMES[c] if 0 <= c < len(CLASS_NAMES) \
                    else str(c)

            n = len(recs)
            agree = 0
            flips: dict = {}
            conf_deltas = []
            for i, r in enumerate(recs):
                old, new = int(r["label"]), int(labels[i])
                if old == new:
                    agree += 1
                else:
                    key = f"{_cls(old)}->{_cls(new)}"
                    flips[key] = flips.get(key, 0) + 1
                if r.get("confidence") is not None:
                    conf_deltas.append(
                        float(probs[i, new]) - float(r["confidence"])
                    )
            agreement = agree / n
            ok = agreement >= args.min_agreement
            obs.emit("replay_verdict", agreement=round(agreement, 6),
                     n=n, ok=ok, min_agreement=args.min_agreement,
                     flips=sum(flips.values()),
                     post_warmup_compiles=compiles)
            print(json.dumps({"replay": {
                "capture_dir": args.capture_dir,
                "candidate": {
                    "checkpoint_dir": args.checkpoint_dir,
                    "precision": pred.precision,
                    "conv_backend": pred.cfg.arch.conv_backend,
                },
                "n": n,
                "agreement": round(agreement, 6),
                "min_agreement": args.min_agreement,
                "ok": ok,
                "flips": dict(sorted(flips.items(),
                                     key=lambda kv: -kv[1])),
                "confidence_delta": {
                    "mean": round(sum(conf_deltas) / len(conf_deltas), 6),
                    "max_abs": round(max(abs(d) for d in conf_deltas), 6),
                } if conf_deltas else None,
                "score_ms": round(score_ms, 3),
                "per_request_ms": round(score_ms / n, 3),
                "post_warmup_compiles": compiles,
            }}))
        finally:
            obs.close_run()
            if own_run:
                shutil.rmtree(run_dir, ignore_errors=True)
        if not ok:
            raise SystemExit(2)
        return

    if args.cmd == "serve":
        import dataclasses as _dc
        import signal
        import threading

        from featurenet_tpu.config import get_config
        from featurenet_tpu.infer import Predictor
        from featurenet_tpu.serve.batcher import normalize_buckets
        from featurenet_tpu.serve.http import _ENDPOINTS, make_server
        from featurenet_tpu.serve.service import InferenceService
        from featurenet_tpu.train.checkpoint import load_run_config

        # Fail the ladder spec here, before the (expensive) checkpoint
        # load — but with the batcher's own validation, not a copy of it.
        try:
            buckets = normalize_buckets(
                [int(b) for b in args.buckets.split(",") if b.strip()]
            )
        except ValueError:
            raise SystemExit(
                f"serve: --buckets must be comma-separated batch sizes "
                f">= 1, got {args.buckets!r}"
            )
        saved = load_run_config(args.checkpoint_dir)
        if saved is not None:
            cfg = _cfg_from_checkpoint(saved, args)
        else:
            cfg = get_config(args.config or "pod64")
        if args.exec_cache_dir:
            cfg = _dc.replace(cfg, exec_cache_dir=args.exec_cache_dir)
        if getattr(args, "trace_sample", None) is not None:
            # Covers the no-sidecar path; with a sidecar the override
            # already flowed through _cfg_from_checkpoint (idempotent).
            cfg = _dc.replace(
                cfg, trace_sample=args.trace_sample
            ).validate()
        rules = None  # None → the service installs serve_rules(slo_p99_ms)
        if args.alert_rules:
            from featurenet_tpu.obs.alerts import parse_rules

            try:
                rules = parse_rules(args.alert_rules)
            except ValueError as e:
                raise SystemExit(f"--alert-rules: {e}")
        if getattr(args, "inject_faults", None):
            # The replica side of the fleet chaos specs (replica_slow
            # fires in InferenceService._forward); markers in run_dir
            # keep a respawned replica from re-firing a taken fault.
            from featurenet_tpu import faults

            try:
                faults.install(args.inject_faults,
                               state_dir=getattr(args, "run_dir", None))
            except ValueError as e:
                raise SystemExit(f"--inject-faults: {e}")
        if getattr(args, "run_dir", None):
            from featurenet_tpu import obs
            from featurenet_tpu.config import config_to_dict

            obs.init_run(args.run_dir, config=config_to_dict(cfg),
                         extra={"cmd": "serve"},
                         process_index=args.process_index)
        # Construction IS the warmup: one serve executable per bucket
        # builds (or loads from the exec cache) before the socket opens.
        pred = Predictor.from_checkpoint(
            args.checkpoint_dir, cfg, batch=max(buckets),
            precision=args.precision,
        )
        want_quality = args.quality or bool(args.quality_baseline)
        want_capture = (args.capture or bool(args.capture_dir)
                        or args.capture_sample is not None)
        if (want_quality or want_capture) and pred.cfg.task != "classify":
            raise SystemExit(
                "serve: --quality/--capture need a classify checkpoint "
                f"(task={pred.cfg.task!r}) — confidence and drift are "
                "class-probability notions"
            )
        quality = None
        if want_quality:
            from featurenet_tpu.data.synthetic import CLASS_NAMES
            from featurenet_tpu.obs.quality import (
                QualityTracker,
                load_baseline,
            )

            baseline = None
            if args.quality_baseline:
                try:
                    baseline = load_baseline(args.quality_baseline)["dist"]
                except (OSError, ValueError) as e:
                    raise SystemExit(f"--quality-baseline: {e}")
            quality = QualityTracker(len(CLASS_NAMES), baseline=baseline)
        recorder = None
        if want_capture:
            from featurenet_tpu.serve import recorder as _recorder

            root = args.capture_dir
            if not root:
                if not getattr(args, "run_dir", None):
                    raise SystemExit(
                        "serve: --capture needs --run-dir (or an "
                        "explicit --capture-dir) — the ring has to "
                        "live somewhere"
                    )
                root = _recorder.capture_dir(args.run_dir)
            try:
                recorder = _recorder.FlightRecorder(
                    root,
                    sample=(_recorder.DEFAULT_SAMPLE
                            if args.capture_sample is None
                            else args.capture_sample),
                    slo_ms=args.slo_p99_ms,
                )
            except ValueError as e:
                raise SystemExit(f"--capture-sample: {e}")
        service = InferenceService(
            pred, buckets=buckets, max_wait_ms=args.max_wait_ms,
            queue_limit=args.queue_limit, rules=rules,
            slo_p99_ms=args.slo_p99_ms,
            batch_queue_limit=args.batch_queue_limit,
            replica=args.replica_id,
            quality=quality, recorder=recorder,
            run_dir=getattr(args, "run_dir", None),
        )
        hb_stop = threading.Event()
        if args.heartbeat_file:
            # The fleet liveness protocol: beat once a second WHILE
            # ready — a wedged replica stops beating and the manager's
            # heartbeat monitor (the trainer's stall machine) kills it.
            from featurenet_tpu.train.heartbeat import touch_heartbeat

            def _beat():
                while not hb_stop.is_set():
                    # A mid-swap replica is cordoned (not ready) but
                    # alive and working — its liveness beat must not
                    # stop, or the manager would kill it as stalled
                    # halfway through a weight reload.
                    if service.ready() or service.reloading():
                        touch_heartbeat(args.heartbeat_file)
                    hb_stop.wait(1.0)

            threading.Thread(target=_beat, name="serve-heartbeat",
                             daemon=True).start()
        srv = make_server(service, host=args.host, port=args.port)
        server_thread = threading.Thread(
            target=srv.serve_forever, name="serve-http", daemon=True
        )
        server_thread.start()
        print(json.dumps({"serving": {
            "host": srv.server_address[0], "port": srv.server_address[1],
            "buckets": list(buckets), "max_wait_ms": args.max_wait_ms,
            "queue_limit": args.queue_limit, "precision": pred.precision,
            "trace_sample": cfg.trace_sample,
            "replica": args.replica_id,
            "quality": (None if quality is None
                        else {"baseline": quality.baseline is not None}),
            "capture": None if recorder is None else recorder.root,
            "endpoints": _ENDPOINTS,
        }}), flush=True)
        stop = threading.Event()
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(
                    sig, lambda *_: stop.set()
                )
            except ValueError:
                pass  # non-main thread (embedded use): duration still works
        try:
            stop.wait(timeout=args.duration_s)
        finally:
            for sig, h in prev_handlers.items():
                signal.signal(sig, h)
        hb_stop.set()
        srv.shutdown()
        st = service.drain()
        if getattr(args, "run_dir", None):
            from featurenet_tpu import obs

            obs.close_run()
        print(json.dumps({"serve_stats": st}))
        if args.drain and st["exit_code"]:
            raise SystemExit(st["exit_code"])
        return

    if args.cmd == "fleet" and getattr(args, "fleet_cmd", None) == \
            "rollout":
        _cmd_fleet_rollout(args)
        return

    if args.cmd == "fleet":
        import signal
        import threading

        from featurenet_tpu import faults, obs
        from featurenet_tpu.fleet.loadgen import replica_argv
        from featurenet_tpu.fleet.replica import Autoscaler, ReplicaManager
        from featurenet_tpu.fleet.router import FleetRouter
        from featurenet_tpu.fleet.scraper import (
            ROUTER_TARGET,
            MetricsScraper,
        )
        from featurenet_tpu.obs import alerts as _alerts
        from featurenet_tpu.obs import tsdb as _tsdb

        if not args.checkpoint_dir:
            raise SystemExit(
                "fleet: --checkpoint-dir is required to launch a fleet"
            )
        if args.replicas < 1:
            raise SystemExit(
                f"fleet: --replicas must be >= 1, got {args.replicas}"
            )
        max_replicas = (args.max_replicas if args.max_replicas is not None
                        else args.replicas + 2)
        if args.autoscale:
            if args.min_replicas < 1:
                raise SystemExit(
                    f"fleet: --min-replicas must be >= 1, got "
                    f"{args.min_replicas}"
                )
            if not (args.min_replicas <= args.replicas <= max_replicas):
                raise SystemExit(
                    f"fleet: --replicas {args.replicas} must sit inside "
                    f"[--min-replicas {args.min_replicas}, "
                    f"--max-replicas {max_replicas}]"
                )
        if not getattr(args, "run_dir", None):
            raise SystemExit(
                "fleet: --run-dir is required — the roster "
                "(membership.json), per-replica heartbeats, stdout "
                "banners, and the fleet event stream all live there"
            )
        if getattr(args, "inject_faults", None):
            # The router/manager process installs only its own sites
            # (replica_loss fires at the Nth routed request, spawn_fail
            # in the manager); child-side sites fire in the replicas,
            # which receive the full spec on their argv.
            try:
                faults.install(args.inject_faults, state_dir=args.run_dir,
                               only={"replica_loss", "spawn_fail"})
            except ValueError as e:
                raise SystemExit(f"--inject-faults: {e}")
        if getattr(args, "quality_baseline", None):
            # Config-time refusal, like --slos: a malformed baseline
            # must fail the launcher here, not every replica spawn.
            from featurenet_tpu.obs.quality import load_baseline

            try:
                load_baseline(args.quality_baseline)
            except (OSError, ValueError) as e:
                raise SystemExit(f"--quality-baseline: {e}")
        obs.init_run(args.run_dir, extra={"cmd": "fleet"},
                     process_index=0)

        def spawn(slot, hb):
            return replica_argv(
                args.checkpoint_dir, slot, hb, run_dir=args.run_dir,
                exec_cache_dir=args.exec_cache_dir,
                buckets=args.buckets, max_wait_ms=args.max_wait_ms,
                queue_limit=args.queue_limit,
                slo_p99_ms=args.slo_p99_ms, precision=args.precision,
                inject_faults=args.inject_faults,
                trace_sample=args.trace_sample,
                quality=args.quality,
                quality_baseline=args.quality_baseline,
                capture=args.capture,
                capture_sample=args.capture_sample,
            )

        manager = ReplicaManager(args.replicas, spawn, args.run_dir,
                                 host="127.0.0.1")
        # The telemetry plane rides the run_dir: the scraper's samples
        # land in <run_dir>/tsdb, which is what the router's burn-rate
        # fleet_scale verdicts, `cli dash`, and the report fleet
        # timeline all read. Config-time SLO validation: a malformed
        # --slos spec refuses here, not mid-serve.
        slos = None
        if getattr(args, "slos", None):
            try:
                slos = _alerts.parse_slos(args.slos)
            except ValueError as e:
                raise SystemExit(f"--slos: {e}")
        store = _tsdb.TimeSeriesStore.open(args.run_dir)
        # Mirror alert fire/resolve transitions into the store as
        # alerts_active{rule} 0/1 series so `cli dash` and post-mortems
        # can overlay alert state on the metric timelines.
        _alerts.set_store(store)
        router = FleetRouter(
            manager, slo_p99_ms=args.slo_p99_ms,
            batch_shed_depth=args.batch_shed_depth,
            store=store, slos=slos, run_dir=args.run_dir,
        )
        manager.start()
        # The ACTING half of the control loop (opt-in): a manager-owned
        # thread turns sustained burn verdicts into add_one/shed_one,
        # damped by hysteresis + a cooldown measured from the last
        # ACTION. Without --autoscale the verdicts stay advisory
        # (fleet_scale events), exactly as before.
        autoscaler = None
        if args.autoscale:
            autoscaler = Autoscaler(
                manager, router.scale_state,
                min_replicas=args.min_replicas,
                max_replicas=max_replicas,
                hysteresis=args.scale_hysteresis,
                cooldown_s=args.scale_cooldown_s,
            )
        srv = router.make_server(host=args.host, port=args.port)
        scraper = MetricsScraper(
            store, manager.pool,
            lambda: {
                **{str(s): p
                   for s, p in manager.stats()["ports"].items()},
                ROUTER_TARGET: srv.server_address[1],
            },
        )
        scraper.start()
        if autoscaler is not None:
            autoscaler.start()
        obs.emit("fleet_start", replicas=args.replicas,
                 host=srv.server_address[0], port=srv.server_address[1])
        threading.Thread(target=srv.serve_forever, name="fleet-http",
                         daemon=True).start()
        print(json.dumps({"fleet": {
            "host": srv.server_address[0], "port": srv.server_address[1],
            "replicas": args.replicas, "buckets": args.buckets,
            "batch_shed_depth": args.batch_shed_depth,
            "autoscale": (None if autoscaler is None
                          else autoscaler.stats()),
            "run_dir": args.run_dir,
        }}), flush=True)
        stop = threading.Event()
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(
                    sig, lambda *_: stop.set()
                )
            except ValueError:
                pass
        try:
            stop.wait(timeout=args.duration_s)
        finally:
            for sig, h in prev_handlers.items():
                signal.signal(sig, h)
        # Stop ACTING before anything drains (a scale action against a
        # half-torn-down fleet would be chaos of our own making), then
        # one final synchronous scrape before the replicas go away so
        # the store's tail covers the whole run, then stop the thread
        # before drain tears the pool's channels down.
        if autoscaler is not None:
            autoscaler.stop()
        scraper.stop()
        srv.shutdown()
        st = router.drain()
        manager.stop()
        st["scrape"] = scraper.stats()
        if autoscaler is not None:
            st["autoscale"] = autoscaler.stats()
        _alerts.set_store(None)
        store.close()
        obs.close_run()
        print(json.dumps({"fleet_stats": st}))
        if args.drain and st["exit_code"]:
            raise SystemExit(st["exit_code"])
        return

    if getattr(args, "debug_nans", False):
        import jax

        jax.config.update("jax_debug_nans", True)

    from featurenet_tpu.config import get_config
    from featurenet_tpu.train.checkpoint import load_run_config
    from featurenet_tpu.train.loop import Trainer

    saved = (
        load_run_config(args.checkpoint_dir)
        if getattr(args, "checkpoint_dir", None)
        else None
    )
    if saved is not None:
        # Resume/eval of a run that persisted its config: the sidecar is
        # the base; flags are policy overrides, identity contradictions are
        # hard errors.
        cfg = _cfg_from_checkpoint(saved, args)
    else:
        cfg = _apply_arch_overrides(
            get_config(args.config or "pod64", **_overrides(args)), args
        )
    print(json.dumps({"config": dataclasses.asdict(cfg)}, default=str))
    trainer = Trainer(cfg)
    if args.cmd == "train":
        trainer.run()
    else:
        if trainer.ckpt is None or trainer.ckpt.latest_step() is None:
            raise SystemExit(
                "eval: no checkpoint found — pass --checkpoint-dir pointing "
                "at a trained run (evaluating random weights is never useful)"
            )
        trainer.resume_if_available()
        print(json.dumps({"eval": trainer.evaluate()}))


if __name__ == "__main__":
    main()
