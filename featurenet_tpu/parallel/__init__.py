"""Mesh, shardings, and distributed initialization (the NCCL/DDP replacement).

The reference scaled with ``DistributedDataParallel`` over NCCL — a wrapper
object that hooks gradient buckets and calls ring-allreduce (SURVEY.md §2 C5).
This package contains *no* collective calls at all: parallelism is expressed
as data placement (``jax.sharding.NamedSharding`` over a ``Mesh``), and every
collective — gradient reduction, BatchNorm stat sync, halo exchange for
spatially-partitioned convs — is inserted by XLA's SPMD partitioner inside
the one compiled train step, where it can overlap with compute on ICI.
"""

from featurenet_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    param_shardings,
    replicated,
)

__all__ = ["make_mesh", "batch_sharding", "param_shardings", "replicated"]
