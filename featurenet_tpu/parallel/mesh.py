"""Device mesh and sharding-rule construction.

Axes:
  ``data``  — pure data parallelism: the global batch's leading dim is split
              here; gradients come back via an XLA-inserted reduce (the ICI
              analog of NCCL ring-allreduce, but fused into the step).
  ``model`` — intra-model parallelism. Two uses, composable:
              (a) channel/tensor parallelism: output channels of large Dense/
                  Conv kernels are sharded, so the flatten→FC matmul (the
                  parameter bulk of FeatureNet) is computed column-parallel;
              (b) spatial partitioning: the voxel grid's depth axis is split
                  across ``model``; XLA emits conv halo exchanges over ICI
                  (the TPU-native "sequence parallelism" of a 3D-CNN — there
                  is no sequence axis, the spatial grid is the long axis;
                  SURVEY.md §5 "long-context").

Multi-host: `jax.distributed.initialize()` (call before device queries) makes
``jax.devices()`` span hosts; the same mesh code then lays axes over
ICI-within-slice / DCN-across-slices. ``make_mesh`` orders ``data`` as the
outermost (slowest, DCN-friendly) axis and ``model`` innermost (ICI) for that
reason: model-parallel collectives are latency-bound and must ride ICI.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[list] = None,
) -> Mesh:
    """Build a ``('data', 'model')`` mesh over the available devices.

    ``data=None`` uses all devices not consumed by ``model``.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if model < 1 or n % model:
        raise ValueError(f"model axis {model} must divide device count {n}")
    if data is None:
        data = n // model
    if data * model > n:
        raise ValueError(f"mesh {data}x{model} exceeds {n} devices")
    grid = np.asarray(devices[: data * model]).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def mesh_summary(mesh: Mesh) -> dict:
    """JSON-able identity of a mesh: axis sizes plus the device/process
    footprint. Two consumers need more than ``mesh.shape``: the runtime
    registry's executable-cache fingerprint (the same axis sizes laid
    over a different process count compile different cross-host
    collectives — an elastic re-form must never be served the old
    world's executable) and the run log's ``loop_start`` (so the report
    can attribute each segment to the mesh shape that ran it across
    elastic generations)."""
    return {
        **{k: int(v) for k, v in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "processes": len({d.process_index for d in mesh.devices.flat}),
    }


def feed_shards(mesh: Mesh) -> tuple[int, int]:
    """How the *host data feed* shards the global batch on this process.

    Returns ``(num_shards, shard_id)`` for the dataset's host-sharding
    (``num_hosts``/``host_id``): the global batch splits into ``num_shards``
    equal row groups and this process generates group ``shard_id``.

    This is NOT always ``(process_count, process_index)``: batch rows live
    on the mesh's ``data`` axis, so a process must feed exactly the rows its
    devices touch. When the ``model`` axis spans processes (e.g. 4 hosts x
    2 chips with model=4), several processes share one data-row group and
    must feed *identical* rows — feeding per-process slices would
    mis-assemble the global array (the round-2 verdict's untested case).
    With the model axis inside each process this degenerates to the usual
    one-distinct-slice-per-process plan.
    """
    import jax

    p = jax.process_index()
    grid = mesh.devices  # [data, model]
    rows = [
        r for r in range(grid.shape[0])
        if any(d.process_index == p for d in grid[r].flat)
    ]
    k = len(rows)
    if not rows:
        raise ValueError(
            f"process {p} owns no devices in this mesh (shape "
            f"{dict(zip(mesh.axis_names, grid.shape))}); a feeding process "
            "must appear in the mesh — pass this process's devices to "
            "make_mesh or exclude it from the data feed"
        )
    if rows != list(range(rows[0], rows[0] + k)):
        raise ValueError(
            f"process {p}'s devices occupy non-contiguous data rows {rows}; "
            "the host feed needs a contiguous row block (use make_mesh's "
            "process-major device order)"
        )
    data = grid.shape[0]
    if data % k:
        raise ValueError(
            f"data axis {data} not divisible by process row-block {k}"
        )
    if rows[0] % k:
        raise ValueError(
            f"process {p}'s row block starts at {rows[0]}, not a multiple "
            f"of its size {k} — row groups would overlap"
        )
    return data // k, rows[0] // k


def clamp_model_axis(model: int, n_devices: int) -> int:
    """Largest divisor of ``n_devices`` that is ≤ ``model``.

    Presets carry their pod-scale mesh shape (abc128 ships ``mesh_model=2``);
    on hardware the axis doesn't divide — a single chip, a 6-device slice —
    the run should degrade to the widest feasible model axis, not crash
    (round-1 weak spot: the shipped stretch preset raised on the only chip
    this environment has). Callers log the downgrade.
    """
    if model < 1:
        raise ValueError(f"model axis must be >= 1, got {model}")
    m = min(model, n_devices)
    while n_devices % m:
        m -= 1
    return m


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, spatial: bool = False) -> NamedSharding:
    """Sharding for ``[B, D, H, W, C]`` voxel batches.

    Batch over ``data``; with ``spatial=True`` the depth axis is additionally
    split over ``model`` (XLA inserts conv halo exchanges — BASELINE config 5's
    path for 128³ grids that outgrow a chip's HBM).
    """
    if spatial:
        return NamedSharding(mesh, P("data", "model"))
    return NamedSharding(mesh, P("data"))


def label_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("data"))


# --- parameter sharding rules (channel tensor-parallelism) ------------------

# Kernels whose output-channel axis is at least this large get sharded over
# 'model'; smaller ones are replicated (collective latency would dominate).
_MIN_SHARD_DIM = 64


def _param_spec(path: tuple, x, model_axis_size: int) -> P:
    if model_axis_size <= 1 or x.ndim == 0:
        return P()
    out_dim = x.shape[-1]
    names = [getattr(k, "key", str(k)) for k in path]
    is_kernel = names and names[-1] == "kernel"
    if is_kernel and out_dim >= _MIN_SHARD_DIM and out_dim % model_axis_size == 0:
        # Dense [in, out] or Conv [k,k,k,in,out]: column-parallel on 'model'.
        return P(*([None] * (x.ndim - 1) + ["model"]))
    return P()


def param_shardings(params, mesh: Mesh):
    """A pytree of ``NamedSharding`` matching ``params``.

    Rule-based tensor parallelism: large kernel output channels go over
    ``model``; everything else (biases, BN scales/stats, small kernels) is
    replicated. With ``model=1`` this degenerates to full replication — the
    pure-DP pod64 config.
    """
    msize = mesh.shape["model"]
    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(mesh, _param_spec(path, x, msize)),
        params,
    )


def state_shardings(state, mesh: Mesh):
    """Shardings for a full ``TrainState`` pytree (params + opt_state + …).

    Optimizer moments (Adam's mu/nu) mirror the params tree structure, so the
    same path-based rule shards them identically to their parameter — the
    moment for a column-parallel kernel lives on the same shard as the kernel.
    Scalars (step, schedule counts) and BN state replicate.
    """
    msize = mesh.shape["model"]
    return jax.tree_util.tree_map_with_path(
        lambda path, x: NamedSharding(mesh, _param_spec(path, x, msize)),
        state,
    )


def batch_shardings(
    mesh: Mesh,
    spatial: bool = False,
    keys: tuple = ("voxels", "label", "seg", "mask"),
) -> dict:
    """Sharding dict for a wire batch (``data.synthetic.to_wire``).

    ``keys`` selects the entries present in the task's wire format — the
    classify wire carries no ``seg``, for instance. Volumetric entries
    (voxels/seg — packed or not, the depth axis is still dim 1) additionally
    shard depth over ``model`` when ``spatial`` is set.
    """
    vol = {
        "voxels": batch_sharding(mesh, spatial),
        "seg": NamedSharding(
            mesh, P("data", "model") if spatial else P("data")
        ),
    }
    return {
        k: vol.get(k, NamedSharding(mesh, P("data"))) for k in keys
    }
