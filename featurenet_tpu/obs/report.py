"""Post-hoc run analysis: fold the run's event stream(s) into answers.

``build_report`` turns a run directory's event log into the questions an
operator actually asks after a run (or a crash):

- **Where did the wall-clock go?** Step-time breakdown over the train
  loop's window(s): data-wait vs dispatch vs readback vs eval vs
  checkpoint seconds and fractions, with ``other`` as the explicit
  remainder so the fractions always account for 100% of loop wall time.
- **Did the input pipeline starve the device?** Prefetch queue-depth
  percentiles (a queue pinned at 0 = starved consumer) and producer
  batch-generation timing.
- **Was the run healthy?** Heartbeat count + max inter-beat age, the
  supervisor's restart/stall timeline, warning counts, and every
  ``run_start`` (each process (re)spawn) in order.
- **How fast is serving?** Per-batch ``infer_batch`` latency percentiles.
- **Which host is the problem?** Multi-process runs write one stream per
  host (``events.<i>.jsonl``); ``load_events`` discovers and merges them,
  tagging every record with its ``process_index``, and the report grows a
  per-host breakdown (data-wait fraction, heartbeat gaps, warnings) plus
  cross-host skew stats — the slowest host's data-wait is where a lockstep
  mesh actually spends its time.

``EventTail`` + ``follow_report`` are the live view: re-read the same
streams incrementally (seek to the last offset, parse only new complete
lines) and re-render while the run is hot.

Everything here is stdlib-only and never touches JAX — the report CLI
must run on a machine (or in a moment) where the backend that produced
the run is long gone.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Callable, Iterable, Optional

from featurenet_tpu.obs.events import EVENTS_FILENAME, MANIFEST_FILENAME

# Loop-attributed span names, in display order. "device" time is
# dispatch + readback: the dispatch call enqueues work, the readback is
# where the host actually blocks on device execution.
LOOP_CATEGORIES = ("data_wait", "dispatch", "readback", "eval", "checkpoint")

# Every span name the package emits. The report keys aggregations off
# these literals, so a renamed emit site would silently fall out of its
# section — the analysis layer's span-name-drift rule checks call sites
# against this registry (and LOOP_CATEGORIES coverage) both ways.
KNOWN_SPAN_NAMES = frozenset({
    *LOOP_CATEGORIES,
    # checkpoint internals (train/checkpoint.py): checkpoint_save is the
    # host-blocking enqueue into the double-buffer; checkpoint_write is
    # the background writer's Orbax write+finalize (where save_slow
    # latency lands — off the step path by construction).
    "checkpoint_save", "checkpoint_restore", "checkpoint_wait",
    "checkpoint_write",
    # serving (infer.py) and the metrics readback (utils/logging.py)
    "infer_batch",
    # the continuous batcher's compiled-forward dispatch (serve/batcher.py)
    "serve_dispatch",
    # offline export / ingest (data/offline.py, data/voxelize.py)
    "build_cache_class", "export_class", "export_seg_shard",
    "seg_cache_flush", "build_seg_cache", "voxelize",
})

_PER_HOST_RE = re.compile(r"events\.(\d+)\.jsonl\Z")


def discover_event_files(run_dir: str) -> list[tuple[str, int]]:
    """Every event stream in ``run_dir`` as ``(path, process_index)``,
    index-ordered. Accepts the legacy single-file layout (``events.jsonl``
    = host 0), the per-host layout (``events.<i>.jsonl``), and any mix —
    including a dir where host 0's file is missing (e.g. only non-zero
    hosts shared this filesystem)."""
    try:
        names = os.listdir(run_dir)
    except OSError:
        return []
    found: list[tuple[str, int]] = []
    for name in names:
        if name == EVENTS_FILENAME:
            found.append((os.path.join(run_dir, name), 0))
        else:
            m = _PER_HOST_RE.match(name)
            if m:
                found.append((os.path.join(run_dir, name), int(m.group(1))))
    return sorted(found, key=lambda pi: pi[1])


def _parse_lines(lines: Iterable[str], process_index: int,
                 events: list[dict]) -> int:
    """Parse JSONL lines into ``events`` (tagging each record with its
    stream's ``process_index``); returns the unparseable-line count (a
    torn line from a killed process must not take the report down with
    it — it is exactly the crashed run we are here to inspect)."""
    bad = 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if isinstance(e, dict) and "t" in e and "ev" in e:
            e.setdefault("process_index", process_index)
            events.append(e)
        else:
            bad += 1
    return bad


def load_events(run_dir: str) -> tuple[list[dict], int]:
    """All events from every discovered per-host stream, merged and
    time-ordered, each tagged with the ``process_index`` of the stream it
    came from; plus the count of unparseable lines across all streams.
    Raises ``FileNotFoundError`` when the directory holds no event stream
    at all (callers can render what *was* found)."""
    files = discover_event_files(run_dir)
    if not files:
        raise FileNotFoundError(
            f"no event stream ({EVENTS_FILENAME} or events.<i>.jsonl) "
            f"in {run_dir!r}"
        )
    events: list[dict] = []
    bad = 0
    for path, idx in files:
        with open(path, encoding="utf-8") as fh:
            bad += _parse_lines(fh, idx, events)
    events.sort(key=lambda e: e["t"])
    return events, bad


def load_manifest(run_dir: str) -> Optional[dict]:
    path = os.path.join(run_dir, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _pct(sorted_vals: list, q: float):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    i = int(round(q / 100.0 * (len(sorted_vals) - 1)))
    return sorted_vals[min(max(i, 0), len(sorted_vals) - 1)]


def _loop_windows(events: list[dict]) -> list[tuple[dict, dict]]:
    """(start, end) event pairs. A trailing start without an end is the
    run that was SIGKILLed mid-loop (a supervisor stall verdict skips the
    finally block) — exactly the segment worth diagnosing — so it is
    closed synthetically at the last event's timestamp instead of being
    dropped; the synthetic end carries ``truncated: True`` and the
    highest step any event in the window reported."""
    def close(start: dict, t_end: float) -> dict:
        last_step = max(
            (e["step"] for e in events
             if start["t"] <= e["t"] <= t_end
             and isinstance(e.get("step"), (int, float))),
            default=start.get("step", 0),
        )
        return {"t": t_end, "step": int(last_step),
                "wall_s": t_end - start["t"], "truncated": True}

    windows = []
    start = None
    for e in events:
        if e["ev"] == "loop_start":
            # A start while one is pending = the previous segment died
            # without its loop_end and a respawn began; close the dead
            # one at the respawn boundary so its spans stay attributed.
            if start is not None and e["t"] > start["t"]:
                windows.append((start, close(start, e["t"])))
            start = e
        elif e["ev"] == "loop_end" and start is not None:
            windows.append((start, e))
            start = None
    if start is not None and events and events[-1]["t"] > start["t"]:
        windows.append((start, close(start, events[-1]["t"])))
    return windows


def _loop_stats(events: list[dict]) -> tuple[dict, Optional[dict],
                                             Optional[float]]:
    """One host's loop section: ``(loop, breakdown, attributed_fraction)``
    — the latter two None when no loop wall was recorded. Shared by the
    main report body and the per-host summaries so both attribute span
    time the same way."""
    windows = _loop_windows(events)
    wall = sum(
        end.get("wall_s", end["t"] - start["t"]) for start, end in windows
    )
    steps = sum(
        end.get("step", 0) - start.get("step", 0) for start, end in windows
    )
    spans = [e for e in events if e["ev"] == "span" and "dur_s" in e]
    in_window = [
        s for s in spans
        if any(st["t"] <= s["t"] <= en["t"] for st, en in windows)
    ]
    cat_s = {c: 0.0 for c in LOOP_CATEGORIES}
    for s in in_window:
        if s.get("name") in cat_s:
            cat_s[s["name"]] += s["dur_s"]
    loop = {
        "windows": len(windows),
        "truncated_windows": sum(
            1 for _, end in windows if end.get("truncated")
        ),
        "wall_s": round(wall, 4),
        "steps": steps,
        "step_ms": round(wall / steps * 1e3, 2) if steps else None,
    }
    if wall <= 0:
        return loop, None, None
    attributed = sum(cat_s.values())
    breakdown = {
        c: {"seconds": round(v, 4), "fraction": round(v / wall, 4)}
        for c, v in cat_s.items()
    }
    other = max(wall - attributed, 0.0)
    breakdown["other"] = {
        "seconds": round(other, 4),
        "fraction": round(other / wall, 4),
    }
    return loop, breakdown, round(min(attributed / wall, 1.0), 4)


def _host_summary(events: list[dict]) -> dict:
    """Per-host digest for the multi-host section: where did THIS host's
    loop wall go, did its heartbeat gap (stall attribution — the host
    whose beats stopped is the one that hung), what did it warn about."""
    loop, breakdown, attributed = _loop_stats(events)
    out: dict = {
        "events": len(events),
        "wall_s": loop["wall_s"],
        "steps": loop["steps"],
        "step_ms": loop["step_ms"],
    }
    if breakdown is not None:
        out["fractions"] = {
            name: row["fraction"] for name, row in breakdown.items()
        }
        out["attributed_fraction"] = attributed
    starts = [e["t"] for e in events if e["ev"] == "loop_start"]
    if starts:
        out["t_first_loop_start"] = round(min(starts), 3)
    beat_ts = sorted(e["t"] for e in events if e["ev"] == "heartbeat")
    ages = [e.get("age_s") for e in events
            if e["ev"] == "heartbeat" and e.get("age_s") is not None]
    out["heartbeat"] = {
        "beats": len(beat_ts),
        "max_age_s": round(max(ages), 3) if ages else None,
        # Largest observed silence between consecutive beats, extended to
        # the host's last event: a host that stopped beating mid-run shows
        # the gap even though no later beat ever stamped an age.
        "max_gap_s": round(max(
            [b - a for a, b in zip(beat_ts, beat_ts[1:])]
            + ([events[-1]["t"] - beat_ts[-1]] if events else []),
        ), 3) if beat_ts else None,
    }
    n_warn = sum(1 for e in events if e["ev"] == "warning")
    if n_warn:
        out["warnings"] = n_warn
    # Latest window_summary per metric for THIS host: multi-host serving
    # skew (one host's p99 blowing while the fleet median looks fine) is
    # invisible in the merged headline — the host table is where it reads.
    wins: dict = {}
    for e in events:
        if e["ev"] == "window_summary" and e.get("metric"):
            wins[e["metric"]] = {
                k: e[k] for k in ("n", "p50", "p99", "seq") if k in e
            }
    if wins:
        out["windows"] = wins
    return out


def _host_skew(hosts: dict[int, dict]) -> dict:
    """Cross-host skew: how far apart the hosts' loops started, how
    unevenly the input pipeline starved them, and whether any host fell
    out of step (lockstep dispatch means the global step time is the
    slowest host's — a fat data-wait spread is free throughput)."""
    skew: dict = {}
    starts = [h["t_first_loop_start"] for h in hosts.values()
              if h.get("t_first_loop_start") is not None]
    if len(starts) >= 2:
        skew["loop_start_skew_s"] = round(max(starts) - min(starts), 3)
    walls = [h["wall_s"] for h in hosts.values() if h.get("wall_s")]
    if len(walls) >= 2:
        skew["wall_s_skew"] = round(max(walls) - min(walls), 4)
    dw = [h["fractions"]["data_wait"] for h in hosts.values()
          if h.get("fractions")]
    if len(dw) >= 2:
        skew["data_wait_fraction"] = {
            "min": round(min(dw), 4),
            "max": round(max(dw), 4),
            "spread": round(max(dw) - min(dw), 4),
        }
    steps = {i: h["steps"] for i, h in hosts.items()}
    if len(set(steps.values())) > 1:
        # Hosts run the same global loop; a step mismatch means a stream
        # is truncated (killed host) or a host diverged — surface it.
        skew["step_mismatch"] = steps
    return skew


def _report_rules(manifest: Optional[dict]) -> list:
    """The alert rules this run was configured with — read back from the
    manifest's persisted config so the post-hoc judge applies the same
    thresholds the live engine did; defaults when absent/garbled."""
    from featurenet_tpu.obs.alerts import DEFAULT_RULES, parse_rules

    spec = ((manifest or {}).get("config") or {}).get("alert_rules")
    try:
        return parse_rules(spec)
    except (ValueError, TypeError):
        return list(DEFAULT_RULES)


def _slo_section(events: list[dict], primary: list[dict]) -> dict:
    """Fold ``window_summary`` + ``alert`` events into the SLO view:
    latest window percentiles per metric (primary host — the canonical
    loop) and per-rule alert counts across every host (an alert on host 3
    must not be invisible in the headline). Alerts are hysteresis pairs:
    a rule is ACTIVE while its last transition on ANY host is an
    unresolved ``state="fire"``; ``count`` counts fires, ``resolves``
    their recoveries. Legacy streams (pre-hysteresis, no ``state``) fall
    back to the old heuristic — the last alert's ``window`` seq matching
    that host's latest summary."""
    out: dict = {}
    windows: dict = {}
    for e in primary:
        if e["ev"] == "window_summary":
            row = {
                k: e[k] for k in ("n", "p50", "p95", "p99", "mean", "max",
                                  "seq")
                if k in e
            }
            row["t"] = round(e["t"], 3)
            windows[e["metric"]] = row
    if windows:
        out["windows"] = windows
    latest_seq: dict[int, int] = {}
    for e in events:
        if e["ev"] == "window_summary" and isinstance(e.get("seq"), int):
            h = int(e.get("process_index") or 0)
            latest_seq[h] = max(latest_seq.get(h, 0), e["seq"])
    alerts: dict = {}
    last_per_host: dict[tuple, dict] = {}
    for e in events:
        if e["ev"] != "alert":
            continue
        r = alerts.setdefault(
            e["rule"], {"count": 0, "resolves": 0, "active": False}
        )
        if e.get("state") == "resolve":
            r["resolves"] += 1
        else:
            r["count"] += 1
        r["last_value"] = e.get("value")
        r["threshold"] = e.get("threshold")
        r["severity"] = e.get("severity")
        last_per_host[(e["rule"], int(e.get("process_index") or 0))] = e
    # Active = ANY host whose latest transition is an unresolved fire — a
    # rule still live on host 0 must not be masked by a later-timestamped
    # recovered firing on host 3.
    for (rule, h), e in last_per_host.items():
        if "state" in e:
            if e["state"] == "fire":
                alerts[rule]["active"] = True
        elif e.get("window") is not None \
                and e["window"] == latest_seq.get(h):
            alerts[rule]["active"] = True
    if alerts:
        out["alerts"] = alerts
    return out


def _perf_section(events: list[dict], slo: dict) -> dict:
    """Performance attribution (obs.perf): fold ``program_cost`` /
    ``program_compile`` / ``device_memory`` events plus the rolling
    ``mfu`` window into the per-program table. Stdlib-only: the peak
    table and the roofline verdict come from ``obs.perf``'s module-level
    data, never a live backend. Every column is honest-absence — a
    program whose backend reported no flops simply has no flops cell,
    and an unknown device kind renders MFU as its explicit unknown
    tier instead of a number."""
    from featurenet_tpu.obs import perf as _perf

    programs: dict[str, dict] = {}
    device_kind = None
    for e in events:
        if e["ev"] == "program_cost" and e.get("program"):
            row = programs.setdefault(str(e["program"]), {})
            # Latest capture wins (a rebuilt program re-reports itself).
            for k in ("flops", "bytes", "temp_bytes", "peak_bytes",
                      "argument_bytes", "output_bytes", "alias_bytes",
                      "optimal_seconds"):
                if isinstance(e.get(k), (int, float)):
                    row[k] = e[k]
            if e.get("precision"):
                # Weight-precision label (fp32 / bf16_master / int8):
                # the column that attributes a precision-rung delta —
                # this run's train counters belong to THIS policy's
                # executable, not a generic "train_step".
                row["precision"] = str(e["precision"])
            if e.get("device_kind"):
                device_kind = e["device_kind"]
        elif e["ev"] == "program_compile" and e.get("program"):
            row = programs.setdefault(str(e["program"]), {})
            row["compile_s"] = round(
                row.get("compile_s", 0.0) + float(e.get("dur_s") or 0.0), 3
            )
    out: dict = {}
    peaks = _perf.device_peaks(device_kind)
    for row in programs.values():
        fl, by = row.get("flops"), row.get("bytes")
        if fl and by:
            row["intensity_flops_per_byte"] = round(fl / by, 2)
        verdict = _perf.roofline(fl, by, peaks)
        if verdict is not None:
            row["roofline"] = verdict
    if programs:
        out["programs"] = dict(sorted(programs.items()))
    if device_kind is not None:
        out["device_kind"] = device_kind
        out["tier"] = peaks["tier"]
        if peaks.get("peak_flops"):
            out["peak_tflops"] = round(peaks["peak_flops"] / 1e12, 1)
    mfu = (slo.get("windows") or {}).get("mfu")
    if mfu:
        out["mfu"] = mfu
    bw = (slo.get("windows") or {}).get("achieved_bw_fraction")
    if bw:
        out["achieved_bw_fraction"] = bw
    # Device-memory watermark: last and peak bytes per polled device
    # (every host's stream counts — each host polls its own devices).
    mem: dict[str, dict] = {}
    for e in events:
        if e["ev"] != "device_memory" or "bytes_in_use" not in e:
            continue
        key = f"{int(e.get('process_index') or 0)}/{e.get('device', 0)}"
        d = mem.setdefault(key, {"samples": 0, "watermark_bytes": 0})
        d["samples"] += 1
        d["bytes_in_use"] = e["bytes_in_use"]
        d["watermark_bytes"] = max(
            d["watermark_bytes"], e["bytes_in_use"],
            e.get("peak_bytes_in_use") or 0,
        )
        if e.get("bytes_limit") is not None:
            d["bytes_limit"] = e["bytes_limit"]
    if mem:
        out["device_memory"] = dict(sorted(mem.items()))
    return out


def _traces_section(events: list[dict],
                    trace_sample: float = 1.0) -> dict:
    """Fold the sampled per-request timelines (obs.tracing) into the
    answers an operator asks of the tail: how many requests were
    sampled/forced, which ten were slowest (WITH their batch
    attribution — the bucket and dispatch they rode), and how far the
    client-observed latency sits above the server's own (``loadgen``
    summary vs the sampled ``request_done`` p99: the skew is queueing
    upstream of admission, measured on one clock). Every host's stream
    counts — a fleet's requests land wherever they were served.

    ``trace_sample`` is the rate the run was configured with (from the
    manifest): below 1.0 the sampled ``request_done`` set is tail-
    biased BY DESIGN (forced slow/failed requests stay, healthy ones
    drop), so its percentiles overstate the true server latency — they
    are labeled as sample-biased and the client-vs-server skew is
    suppressed rather than reported against a biased denominator."""
    done = [e for e in events if e["ev"] == "request_done"]
    rejects = [e for e in events if e["ev"] == "request_reject"]
    if not done and not rejects:
        return {}
    out: dict = {
        "sampled": len(done),
        "rejected": len(rejects),
        "forced": sum(1 for e in done if e.get("forced"))
        + len(rejects),
        "errors": sum(1 for e in done if e.get("outcome") == "error"),
    }
    # Batch attribution per trace: the dispatch event carries the seq /
    # bucket / pad the request rode (last one wins — retries don't exist
    # today, but a re-dispatched future would be the interesting one).
    disp: dict[str, dict] = {}
    for e in events:
        if e["ev"] == "request_dispatch" and e.get("trace"):
            disp[e["trace"]] = e
    slowest = sorted(
        done, key=lambda e: e.get("total_ms") or 0.0, reverse=True
    )[:10]
    out["slowest"] = [
        {
            "trace": e.get("trace"),
            "total_ms": e.get("total_ms"),
            "queue_wait_ms": e.get("queue_wait_ms"),
            "dispatch_ms": e.get("dispatch_ms"),
            "outcome": e.get("outcome"),
            "batch_seq": (disp.get(e.get("trace")) or {}).get("batch_seq"),
            "bucket": (disp.get(e.get("trace")) or {}).get("bucket"),
        }
        for e in slowest
    ]
    complete = trace_sample >= 1.0
    if not complete:
        out["sample_rate"] = trace_sample
        out["sample_biased"] = True
    totals = sorted(
        e["total_ms"] for e in done
        if isinstance(e.get("total_ms"), (int, float))
    )
    if totals:
        out["server_p50_ms"] = round(_pct(totals, 50), 3)
        out["server_p99_ms"] = round(_pct(totals, 99), 3)
    lg = [e for e in events if e["ev"] == "loadgen"]
    if lg:
        last = lg[-1]
        client = {
            k: last.get(k) for k in ("n", "client_p50_ms", "client_p99_ms")
        }
        if complete and totals \
                and isinstance(last.get("client_p99_ms"), (int, float)):
            client["skew_p99_ms"] = round(
                last["client_p99_ms"] - _pct(totals, 99), 3
            )
        out["client"] = client
    return out


def request_timeline(events: list[dict], trace_id: str) -> dict:
    """One request's admit→dispatch→done (or reject) timeline, merged
    across every host stream and time-ordered. Returns ``{"trace",
    "found", "events": [...]}`` where each row carries its host, the
    offset from the first event, and the kind-specific fields — the
    answer to "what happened to THIS request"."""
    rows = sorted(
        (e for e in events
         if e.get("ev") in REQUEST_EVENT_KINDS
         and e.get("trace") == trace_id),
        key=lambda e: e["t"],
    )
    if not rows:
        return {"trace": trace_id, "found": False, "events": []}
    t0 = rows[0]["t"]
    return {
        "trace": trace_id,
        "found": True,
        "events": [
            {
                "event": e["ev"],
                "t": round(e["t"], 6),
                "offset_ms": round((e["t"] - t0) * 1e3, 3),
                "host": int(e.get("process_index") or 0),
                **{k: v for k, v in e.items()
                   if k not in ("ev", "t", "trace", "pid",
                                "process_index", "thread")},
            }
            for e in rows
        ],
    }


def format_request_timeline(tl: dict) -> str:
    """Human rendering of ``request_timeline`` (the CLI's ``--request``
    output)."""
    if not tl["found"]:
        return (
            f"trace {tl['trace']}: no events in this run dir — the id "
            "may be wrong, or the request fell outside the sampling "
            "rate (rejections, errors, and SLO breaches are always "
            "sampled; healthy traffic at Config.trace_sample)"
        )
    lines = [f"trace {tl['trace']}"]
    for e in tl["events"]:
        detail = {k: v for k, v in e.items()
                  if k not in ("event", "t", "offset_ms", "host")}
        lines.append(
            f"  +{e['offset_ms']:>9.3f} ms  host {e['host']}  "
            f"{e['event']:<16} {detail or ''}"
        )
    return "\n".join(lines)


def build_report(events: list[dict], manifest: Optional[dict] = None,
                 bad_lines: int = 0) -> dict:
    by_host: dict[int, list[dict]] = {}
    for e in events:
        by_host.setdefault(int(e.get("process_index") or 0), []).append(e)
    # Host 0's stream carries the canonical loop (plus the supervisor's
    # events); a run dir holding only non-zero hosts' streams still
    # reports, anchored on the lowest index present.
    primary_idx = 0 if 0 in by_host or not by_host else min(by_host)
    primary = by_host.get(primary_idx, [])

    rep: dict = {"n_events": len(events), "bad_lines": bad_lines}
    if manifest:
        cfg = manifest.get("config") or {}
        rep["run"] = {
            "run_dir": manifest.get("run_dir"),
            "start_time": manifest.get("start_time"),
            "config_name": cfg.get("name"),
            "task": cfg.get("task"),
            "process_index": (manifest.get("jax") or {}).get("process_index"),
            "device_count": (manifest.get("jax") or {}).get("device_count"),
        }
    # Primary host only: this field is the RESPAWN counter (PR 1's restart
    # timeline), and every host's init_run emits one run_start — counting
    # across hosts would read a clean 4-host run as three restarts.
    rep["process_starts"] = sum(
        1 for e in primary if e["ev"] == "run_start"
    )

    # --- step-time breakdown over the primary host's loop window(s) ---------
    loop, breakdown, attributed = _loop_stats(primary)
    rep["loop"] = loop
    if breakdown is not None:
        rep["breakdown"] = breakdown
        rep["attributed_fraction"] = attributed
    spans = [e for e in primary if e["ev"] == "span" and "dur_s" in e]

    # --- per-host breakdown + cross-host skew (multi-process runs) ----------
    if len(by_host) > 1:
        rep["hosts"] = {
            i: _host_summary(evts) for i, evts in sorted(by_host.items())
        }
        rep["host_skew"] = _host_skew(rep["hosts"])

    # --- live SLOs: rolling-window summaries + alert firings ----------------
    slo = _slo_section(events, primary)
    # The one rule no single process can judge: cross-host data-wait
    # spread. The report is where the streams merge, so it is evaluated
    # here, with the thresholds the run was configured with.
    dwf = (rep.get("host_skew") or {}).get("data_wait_fraction")
    if dwf and dwf.get("spread") is not None:
        for rule in _report_rules(manifest):
            if rule.scope == "report" and rule.metric == "data_wait_spread" \
                    and rule.violated(dwf["spread"]):
                slo.setdefault("alerts", {})[rule.metric] = {
                    "count": 1,
                    "last_value": dwf["spread"],
                    "threshold": rule.threshold,
                    "severity": rule.severity,
                    "active": True,
                    "source": "report",
                }
    if slo:
        rep["slo"] = slo

    # --- input pipeline (primary host) --------------------------------------
    depths = sorted(
        e["value"] for e in primary
        if e["ev"] == "gauge" and e.get("name") == "prefetch_queue_depth"
    )
    if depths:
        rep["prefetch_queue_depth"] = {
            "n": len(depths),
            "p10": _pct(depths, 10),
            "p50": _pct(depths, 50),
            "p90": _pct(depths, 90),
            "max": depths[-1],
        }
    gen = sorted(
        e["value"] for e in primary
        if e["ev"] == "gauge" and e.get("name") == "producer_batch_s"
    )
    if gen:
        rep["producer_batch_s"] = {
            "n": len(gen),
            "mean": round(sum(gen) / len(gen), 4),
            "p90": round(_pct(gen, 90), 4),
            "max": round(gen[-1], 4),
        }

    # --- liveness / supervision --------------------------------------------
    # Heartbeats: primary host (per-host gaps live in rep["hosts"]); the
    # supervisor timeline spans every stream — it writes into host 0's
    # file, but synthetic/merged logs may carry it anywhere.
    beats = [e for e in primary if e["ev"] == "heartbeat"]
    if beats:
        ages = [e.get("age_s") for e in beats if e.get("age_s") is not None]
        rep["heartbeat"] = {
            "beats": len(beats),
            "max_age_s": round(max(ages), 3) if ages else None,
        }
    sup = [e for e in events if e["ev"] == "supervisor"]
    if sup:
        phases = [e.get("phase") for e in sup]
        rep["supervisor"] = {
            "stalls": phases.count("stall"),
            "restarts": phases.count("restart"),
            "planned_restarts": phases.count("planned_restart"),
            "backoffs": phases.count("backoff"),
            "gate_regressions": phases.count("gate_regression"),
            "timeline": [
                {"t": round(e["t"], 3), "phase": e.get("phase"),
                 **{k: v for k, v in e.items()
                    if k not in ("t", "ev", "phase")}}
                for e in sup
            ],
        }
    # Recovery events: preemptions drained to a checkpoint, restores
    # that fell back past a corrupt step, and the elastic membership
    # timeline (mesh re-forms with their per-slot leave/join
    # transitions) — every host's stream counts (a preempted host ≠
    # host 0 in general; the coordinator writes into host 0's).
    rec = [e for e in events
           if e["ev"] in ("preempt", "checkpoint_fallback", "mesh_reform",
                          "host_leave", "host_join")]
    if rec:
        rep["recovery"] = {
            "preempts": sum(e["ev"] == "preempt" for e in rec),
            "checkpoint_fallbacks": sum(
                e["ev"] == "checkpoint_fallback" for e in rec
            ),
            "mesh_reforms": sum(e["ev"] == "mesh_reform" for e in rec),
            "host_leaves": sum(e["ev"] == "host_leave" for e in rec),
            "host_joins": sum(e["ev"] == "host_join" for e in rec),
            "timeline": [
                {"t": round(e["t"], 3), "event": e["ev"],
                 **{k: v for k, v in e.items()
                    if k not in ("t", "ev", "pid")}}
                for e in rec
            ],
        }

    # --- runtime registry: compiles vs executable-cache verdicts ------------
    # Every host's stream counts (respawned children each pay their own
    # compiles — that is exactly the cost the cache exists to collapse).
    rts = [e for e in events
           if e["ev"] in ("program_compile", "cache_hit", "cache_miss",
                          "cache_reject")]
    if rts:
        compiles = [e for e in rts if e["ev"] == "program_compile"]
        rep["runtime"] = {
            "compiles": len(compiles),
            "compile_s": round(
                sum(e.get("dur_s", 0.0) for e in compiles), 3
            ),
            "cache_hits": sum(e["ev"] == "cache_hit" for e in rts),
            "cache_misses": sum(e["ev"] == "cache_miss" for e in rts),
            "cache_rejects": sum(e["ev"] == "cache_reject" for e in rts),
            "programs": sorted({
                e.get("program", "?") for e in rts
            }),
            "rejects": [
                {"program": e.get("program"), "reason": e.get("reason")}
                for e in rts if e["ev"] == "cache_reject"
            ],
        }

    # --- performance attribution (obs.perf) ---------------------------------
    perf = _perf_section(events, slo)
    if perf:
        rep["perf"] = perf

    # --- serving ------------------------------------------------------------
    lat = sorted(
        s["dur_s"] * 1e3 for s in spans if s.get("name") == "infer_batch"
    )
    if lat:
        rep["serving_latency_ms"] = {
            "batches": len(lat),
            "rows": sum(
                s.get("n", 0) for s in spans if s.get("name") == "infer_batch"
            ),
            "mean": round(sum(lat) / len(lat), 3),
            "p50": round(_pct(lat, 50), 3),
            "p90": round(_pct(lat, 90), 3),
            "p99": round(_pct(lat, 99), 3),
            "max": round(lat[-1], 3),
        }

    # --- serving front end (continuous batcher) -----------------------------
    # Every host's stream counts, like the runtime section: a serving
    # fleet is one service per host and each one's batches/overloads are
    # part of the answer.
    sb = [e for e in events if e["ev"] == "serve_batch"]
    n_over = sum(1 for e in events if e["ev"] == "overload")
    stops = [e for e in events if e["ev"] == "serve_stop"]
    if sb or n_over or stops:
        srows = sum(e.get("n", 0) for e in sb)
        scap = sum(e.get("bucket", 0) for e in sb)
        by_bucket: dict[str, int] = {}
        for e in sb:
            key = str(e.get("bucket", "?"))
            by_bucket[key] = by_bucket.get(key, 0) + 1
        serve: dict = {
            "batches": len(sb),
            "rows": srows,
            "occupancy": round(srows / scap, 4) if scap else None,
            "by_bucket": dict(sorted(
                by_bucket.items(),
                key=lambda kv: (not kv[0].isdigit(),
                                int(kv[0]) if kv[0].isdigit() else 0),
            )),
            "overloads": n_over,
        }
        if stops:
            serve["served"] = stops[-1].get("served")
            serve["rejected"] = stops[-1].get("rejected")
        rep["serve"] = serve

    # --- model quality (obs.quality / serve.recorder / cli replay) -----------
    # The quality plane's fold: confidence/entropy/drift windows (already
    # summarized by the SLO section — mirrored here so the model's story
    # reads in one place), the drift snapshots' trajectory, what the
    # flight recorder kept and why, and the latest replay-canary verdict.
    # Every host's stream counts: each replica tracks its own mix.
    quality: dict = {}
    qwin = slo.get("windows") or {}
    for metric, out_key in (("confidence", "confidence"),
                            ("confidence_margin", "margin"),
                            ("prediction_entropy", "entropy"),
                            ("quality_drift_score", "drift_score")):
        row = qwin.get(metric)
        if row:
            quality[out_key] = row
    qd = [e for e in events if e["ev"] == "quality_drift"]
    if qd:
        scores = [e.get("score") for e in qd
                  if isinstance(e.get("score"), (int, float))]
        quality["drift"] = {
            "snapshots": len(qd),
            "last_score": qd[-1].get("score"),
            "max_score": round(max(scores), 6) if scores else None,
        }
    caps = [e for e in events if e["ev"] == "capture"]
    if caps:
        by_reason: dict[str, int] = {}
        for e in caps:
            r = str(e.get("reason", "?"))
            by_reason[r] = by_reason.get(r, 0) + 1
        quality["captures"] = {
            "count": len(caps),
            "by_reason": dict(sorted(by_reason.items())),
        }
    rv = [e for e in events if e["ev"] == "replay_verdict"]
    if rv:
        last_v = rv[-1]
        quality["replay"] = {
            "runs": len(rv),
            "agreement": last_v.get("agreement"),
            "n": last_v.get("n"),
            "ok": last_v.get("ok"),
        }
    if quality:
        rep["quality"] = quality

    # --- persistent-connection data plane (fleet.pool) ------------------------
    # Channel lifecycle events, merged across streams: opened vs reused
    # is the pooling payoff (reuse_ratio — the bench gate pins the fleet
    # flavor), retired-by-reason is the churn story (a spike of "broken"
    # is replica loss; "max_age"/"idle_overflow" is policy working as
    # designed). Surfaced top-level and mirrored into the serve/fleet
    # sections so the fold reads next to the traffic it carried.
    conn_ev = [e for e in events
               if e["ev"] in ("conn_open", "conn_reuse", "conn_retire")]
    connections = None
    if conn_ev:
        retired: dict[str, int] = {}
        for e in conn_ev:
            if e["ev"] == "conn_retire":
                reason = str(e.get("reason", "?"))
                retired[reason] = retired.get(reason, 0) + 1
        opened = sum(e["ev"] == "conn_open" for e in conn_ev)
        reused = sum(e["ev"] == "conn_reuse" for e in conn_ev)
        connections = {
            "opened": opened,
            "reused": reused,
            "reuse_ratio": round(reused / (opened + reused), 4)
            if (opened + reused) else None,
            "retired": dict(sorted(retired.items())),
        }
        rep["connections"] = connections
        if rep.get("serve") is not None:
            rep["serve"]["connections"] = connections

    # --- serving fleet (featurenet_tpu.fleet) --------------------------------
    # Roster transitions + routing outcomes, merged across every stream
    # (the router owns stream 0; each replica writes its own). The
    # timeline is the mesh_reform-style roster history: who was lost
    # why, and when each respawn turned ready again.
    fl = [e for e in events
          if isinstance(e.get("ev"), str)
          and (e["ev"].startswith("fleet_")
               or e["ev"].startswith("rollout_") or e["ev"] == "swap")]
    if fl:
        starts = [e for e in fl if e["ev"] == "fleet_start"]
        stops = [e for e in fl if e["ev"] == "fleet_stop"]
        sheds: dict[str, int] = {}
        for e in fl:
            if e["ev"] == "fleet_shed":
                lane = str(e.get("lane", "?"))
                sheds[lane] = sheds.get(lane, 0) + 1
        verdicts: dict[str, int] = {}
        for e in fl:
            if e["ev"] == "fleet_scale":
                v = str(e.get("verdict", "?"))
                verdicts[v] = verdicts.get(v, 0) + 1
        # Actions actually TAKEN (fleet_autoscale), as opposed to the
        # advisory verdict changes counted above.
        autoscale: dict[str, int] = {}
        for e in fl:
            if e["ev"] == "fleet_autoscale":
                a = str(e.get("action", "?"))
                autoscale[a] = autoscale.get(a, 0) + 1
        fleet: dict = {
            "replicas": starts[-1].get("replicas") if starts else None,
            "ready_events": sum(
                e["ev"] == "fleet_replica_ready" for e in fl
            ),
            "losses": sum(
                e["ev"] == "fleet_replica_loss" for e in fl
            ),
            "spillovers": sum(e["ev"] == "fleet_spillover" for e in fl),
            "resubmits": sum(e["ev"] == "fleet_resubmit" for e in fl),
            "sheds": sheds,
            "scale_verdicts": verdicts,
            "timeline": [
                {"t": round(e["t"], 3), "event": e["ev"],
                 **{k: v for k, v in e.items()
                    if k not in ("t", "ev", "pid", "process_index")}}
                for e in fl
                if e["ev"] in ("fleet_start", "fleet_replica_ready",
                               "fleet_replica_loss", "fleet_scale",
                               "fleet_autoscale", "fleet_stop", "swap",
                               "rollout_start", "rollout_step",
                               "rollout_rollback", "rollout_done")
            ],
        }
        if autoscale:
            fleet["autoscale_actions"] = autoscale
        # The rollout arc, when one ran: the orchestrator's verdict plus
        # the per-replica swap attempts (the mixed-version window reads
        # off the timeline's model_version tags).
        swaps = [e for e in fl if e["ev"] == "swap"]
        dones = [e for e in fl if e["ev"] == "rollout_done"]
        rollbacks = [e for e in fl if e["ev"] == "rollout_rollback"]
        if swaps or dones or rollbacks:
            fleet["rollout"] = {
                "swaps_ok": sum(bool(e.get("ok")) for e in swaps),
                "swaps_refused": sum(not e.get("ok") for e in swaps),
                "rollbacks": len(rollbacks),
                "ok": bool(dones[-1].get("ok")) if dones else None,
                "version": dones[-1].get("version") if dones else None,
            }
        if stops:
            fleet["routed"] = stops[-1].get("routed")
            fleet["answered"] = stops[-1].get("answered")
            fleet["rejected"] = stops[-1].get("rejected")
            fleet["dropped"] = stops[-1].get("dropped")
        if connections is not None:
            fleet["connections"] = connections
        rep["fleet"] = fleet

    # --- request-level traces (obs.tracing) ----------------------------------
    ts_rate = ((manifest or {}).get("config") or {}).get("trace_sample")
    traces = _traces_section(
        events,
        trace_sample=ts_rate if isinstance(ts_rate, (int, float)) else 1.0,
    )
    if traces:
        rep["traces"] = traces

    # --- incident plane (obs.incidents) --------------------------------------
    # Folded from the event stream (open/capture/close across every
    # host); build_report_dir adds the on-disk bundle inventory, which
    # outlives the stream's tail.
    inc_open = [e for e in events if e["ev"] == "incident_open"]
    inc_close = [e for e in events if e["ev"] == "incident_close"]
    if inc_open or inc_close:
        closed_ids = {e.get("id") for e in inc_close}
        by_rule: dict[str, int] = {}
        for e in inc_open:
            r = str(e.get("rule", "?"))
            by_rule[r] = by_rule.get(r, 0) + 1
        rep["incidents"] = {
            "opened": len(inc_open),
            "closed": len(inc_close),
            "by_rule": by_rule,
            "still_open": sorted(
                str(e.get("id")) for e in inc_open
                if e.get("id") not in closed_ids
            ),
            "durations_s": [e.get("duration_s") for e in inc_close
                            if e.get("duration_s") is not None],
        }

    # --- warnings / metrics -------------------------------------------------
    # Warnings aggregate across every host (a warning on host 3 must not
    # be invisible in the headline); metrics records would be N-fold
    # duplicates of the same global values, so the primary host speaks.
    warns = [e for e in events if e["ev"] == "warning"]
    if warns:
        by_name: dict[str, int] = {}
        for e in warns:
            by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
        rep["warnings"] = by_name
    metrics = [e for e in primary if e["ev"] == "metrics"]
    if metrics:
        last: dict[str, dict] = {}
        for e in metrics:
            last[e.get("kind", "?")] = {
                k: v for k, v in e.items() if k not in ("ev",)
            }
        rep["metrics"] = {"count": len(metrics), "last": last}
    return rep


def fleet_timeline_section(run_dir: str, window_s: float = 3600.0,
                           now: Optional[float] = None) -> Optional[dict]:
    """The fleet timeline, from the time-series store ALONE: per target
    (each replica + the router), the scraped ``serving_ms{q=0.99}``
    history over the trailing look-back window — sample count,
    last/median/max, and a sparkline. This answers "what did p99 look
    like for the last hour, per replica" after every serving process has
    exited, which the event stream cannot (windows die with their
    process; the store is what the scraper built to outlive them). None
    when the run_dir has no store (no fleet ran, or no scraper was
    wired)."""
    # Local imports: tsdb/dash import this module's _pct at module
    # level — by call time report is fully loaded, so no cycle.
    from featurenet_tpu.obs import tsdb as _tsdb
    from featurenet_tpu.obs.dash import SPARK_SLOTS, _bucket, _spark

    if not os.path.isdir(_tsdb.store_dir(run_dir)):
        return None
    store = _tsdb.TimeSeriesStore.open(run_dir)
    targets: dict[str, dict] = {}
    series = store.series()
    if not series:
        return None
    if now is None:
        # A finished run's "now" is the store's last sample, not the
        # wall clock — a report rendered days later must still show the
        # hour the fleet actually served.
        now = max(
            (s[0] for m, lb in series
             for s in [store.latest(m, lb)] if s is not None),
            default=time.time(),
        )
    names = sorted({lb.get("replica") for _m, lb in series
                    if lb.get("replica") is not None})
    for target in names:
        samples = store.query("serving_ms",
                              {"q": "0.99", "replica": target},
                              since_s=window_s, now=now)
        if not samples:
            continue
        vals = sorted(v for _t, v in samples)
        targets[target] = {
            "samples": len(samples),
            "p99_ms_last": round(samples[-1][1], 3),
            "p99_ms_median": round(_pct(vals, 50), 3),
            "p99_ms_max": round(vals[-1], 3),
            "spark": _spark(_bucket(samples, now, window_s, SPARK_SLOTS)),
        }
    if not targets:
        return None
    fails = 0
    for metric, labels in series:
        if metric == "scrape_failures_total":
            last = store.latest(metric, labels)
            if last is not None:
                fails += int(last[1])
    return {
        "window_s": float(window_s),
        "t_end": round(now, 3),
        "targets": targets,
        "scrape_failures": fails,
    }


def build_report_dir(run_dir: str) -> dict:
    events, bad = load_events(run_dir)
    rep = build_report(events, load_manifest(run_dir), bad_lines=bad)
    timeline = fleet_timeline_section(run_dir)
    if timeline is not None:
        rep["fleet_timeline"] = timeline
    # Bundle inventory from disk: bundles outlive the event stream's
    # tail (and survive a dark sink), so the report lists them even when
    # no incident_* event made it into the log.
    from featurenet_tpu.obs import incidents as _incidents

    bundles = _incidents.list_incidents(run_dir)
    if bundles:
        rep.setdefault("incidents", {})["bundles"] = bundles
    return rep


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s" if v < 100 else f"{v:.1f}s"


def _skew_parts(skew: dict) -> list:
    """Human fragments of the cross-host skew stats — ONE renderer shared
    by the report body and the live tail's header, so the two views can
    never drift on formulas or labels."""
    parts = []
    if skew.get("loop_start_skew_s") is not None:
        parts.append(f"loop-start skew {skew['loop_start_skew_s']}s")
    if skew.get("wall_s_skew") is not None:
        parts.append(f"wall skew {skew['wall_s_skew']}s")
    dwf = skew.get("data_wait_fraction")
    if dwf:
        parts.append(
            f"data-wait spread {dwf['spread'] * 100:.1f}pp "
            f"({dwf['min'] * 100:.1f}%–{dwf['max'] * 100:.1f}%)"
        )
    return parts


def _perf_headline(pf: dict) -> str:
    """The perf section's one-line MFU readout — ONE renderer shared by
    the report body and the live tail (``follow_perf_line``), so the two
    views can never drift. The unknown peak tier is EXPLICIT: a device
    kind with no peak-table entry reads ``mfu: unknown (<kind>)``, never
    a number."""
    if pf.get("tier") == "known":
        head = f"perf: device {pf.get('device_kind')}"
        if pf.get("peak_tflops"):
            head += f" (peak {pf['peak_tflops']} TF/s)"
        mfu = pf.get("mfu")
        if mfu:
            head += (f"; mfu p50 {mfu.get('p50')} p99 {mfu.get('p99')} "
                     f"(n={mfu.get('n')})")
        else:
            head += "; mfu: no samples"
        bw = pf.get("achieved_bw_fraction")
        if bw:
            head += f"; bw fraction p50 {bw.get('p50')}"
        return head
    kind = pf.get("device_kind")
    return f"perf: mfu: unknown ({kind or 'no device kind recorded'})"


def format_report(rep: dict) -> str:
    """Human-readable rendering (the CLI's default output; --json gives
    the raw dict)."""
    lines = []
    run = rep.get("run") or {}
    head = "run"
    if run.get("config_name"):
        head += f" [{run['config_name']}/{run.get('task')}]"
    if run.get("start_time"):
        head += f" started {run['start_time']}"
    if run.get("device_count") is not None:
        head += f", {run['device_count']} device(s)"
    lines.append(head)
    lines.append(
        f"events: {rep['n_events']}"
        + (f" ({rep['bad_lines']} unparseable)" if rep.get("bad_lines") else "")
        + f", process starts: {rep.get('process_starts', 0)}"
    )
    loop = rep.get("loop", {})
    if loop.get("wall_s"):
        trunc = loop.get("truncated_windows", 0)
        lines.append(
            f"loop: {loop['steps']} step(s) over {loop['windows']} "
            f"window(s), wall {_fmt_s(loop['wall_s'])}"
            + (f", {loop['step_ms']} ms/step" if loop.get("step_ms") else "")
            + (f" ({trunc} window(s) truncated by a kill)" if trunc else "")
        )
    bd = rep.get("breakdown")
    if bd:
        lines.append("step-time breakdown (fractions of loop wall):")
        for name in (*LOOP_CATEGORIES, "other"):
            row = bd[name]
            lines.append(
                f"  {name:<11} {row['seconds']:>9.3f}s  "
                f"{row['fraction'] * 100:5.1f}%"
            )
        lines.append(
            f"  attributed (non-other): "
            f"{rep['attributed_fraction'] * 100:.1f}%"
        )
    hosts = rep.get("hosts")
    if hosts:
        lines.append(f"hosts: {len(hosts)} event stream(s)")
        lines.append(
            "  host   wall        steps  data_wait  beats  max_gap  warn"
        )
        for i in sorted(hosts):
            h = hosts[i]
            fr = h.get("fractions") or {}
            dw = fr.get("data_wait")
            hb = h.get("heartbeat") or {}
            gap = hb.get("max_gap_s")
            lines.append(
                f"  {i:<5}  {_fmt_s(h['wall_s']):>9}  {h['steps']:>5}  "
                + (f"{dw * 100:8.1f}%" if dw is not None else f"{'—':>9}")
                + f"  {hb.get('beats', 0):>5}  "
                + (f"{gap:>6.1f}s" if gap is not None else f"{'—':>7}")
                + f"  {h.get('warnings', 0):>4}"
            )
        if any(hosts[i].get("windows") for i in hosts):
            lines.append("  host windows (latest p50/p99):")
            for i in sorted(hosts):
                wins = hosts[i].get("windows")
                if not wins:
                    continue
                lines.append(
                    f"    {i}: " + ", ".join(
                        f"{m} {wins[m].get('p50')}/{wins[m].get('p99')}"
                        for m in sorted(wins)
                    )
                )
        skew = rep.get("host_skew") or {}
        parts = _skew_parts(skew)
        if parts:
            lines.append("host skew: " + ", ".join(parts))
        if skew.get("step_mismatch"):
            lines.append(
                "  STEP MISMATCH across hosts (truncated stream or "
                f"diverged host): {skew['step_mismatch']}"
            )
    slo = rep.get("slo") or {}
    sw = slo.get("windows")
    if sw:
        lines.append("SLO windows (latest):")
        for metric in sorted(sw):
            row = sw[metric]
            lines.append(
                f"  {metric:<16} n={row.get('n', 0):<4} "
                f"p50 {row.get('p50')}  p95 {row.get('p95')}  "
                f"p99 {row.get('p99')}  max {row.get('max')}"
            )
    sa = slo.get("alerts")
    if sa:
        lines.append("alerts:")
        for rule in sorted(sa):
            a = sa[rule]
            lines.append(
                f"  {'ACTIVE' if a.get('active') else 'fired '} "
                f"{rule:<22} ×{a['count']}"
                + (f" (resolved ×{a['resolves']})" if a.get("resolves")
                   else "")
                + f"  last {a.get('last_value')} "
                f"vs {a.get('threshold')} ({a.get('severity')})"
            )
    rt = rep.get("runtime")
    if rt:
        lines.append(
            f"runtime: {rt['compiles']} compile(s) "
            f"({rt['compile_s']}s XLA), cache {rt['cache_hits']} hit(s) / "
            f"{rt['cache_misses']} miss(es) / {rt['cache_rejects']} "
            f"reject(s)"
        )
        for r in rt["rejects"]:
            lines.append(
                f"  REJECT {r.get('program')}: {r.get('reason')}"
            )
    pf = rep.get("perf")
    if pf:
        lines.append(_perf_headline(pf))
        progs = pf.get("programs") or {}
        if progs:
            lines.append(
                "  program                 precision      gflops    acc MB"
                "   peak MB  roofline       compile"
            )

            def cell(v, scale, fmt):
                return format(v / scale, fmt) if v is not None else "—"

            for name in sorted(progs):
                row = progs[name]
                lines.append(
                    f"  {name:<22}  "
                    f"{row.get('precision') or '—':<11}  "
                    f"{cell(row.get('flops'), 1e9, '8.2f'):>8}  "
                    f"{cell(row.get('bytes'), 1e6, '8.1f'):>8}  "
                    f"{cell(row.get('peak_bytes'), 1e6, '8.1f'):>8}"
                    f"  {row.get('roofline') or '—':<13}"
                    + (f"  {row['compile_s']}s" if row.get("compile_s")
                       is not None else "  —")
                )
        dm = pf.get("device_memory")
        if dm:
            lines.append(
                "  device memory watermark: " + ", ".join(
                    f"host/dev {k}: {v['watermark_bytes'] / 1e6:.1f} MB"
                    + (f" of {v['bytes_limit'] / 1e6:.0f} MB"
                       if v.get("bytes_limit") else "")
                    for k, v in dm.items()
                )
            )
    q = rep.get("prefetch_queue_depth")
    if q:
        lines.append(
            f"prefetch queue depth: p10 {q['p10']} p50 {q['p50']} "
            f"p90 {q['p90']} max {q['max']} (n={q['n']})"
        )
    g = rep.get("producer_batch_s")
    if g:
        lines.append(
            f"producer batch gen: mean {g['mean'] * 1e3:.1f} ms "
            f"p90 {g['p90'] * 1e3:.1f} ms (n={g['n']})"
        )
    hb = rep.get("heartbeat")
    if hb:
        age = hb.get("max_age_s")
        lines.append(
            f"heartbeat: {hb['beats']} beat(s)"
            + (f", max age {age}s" if age is not None else "")
        )
    sup = rep.get("supervisor")
    if sup:
        lines.append(
            f"supervisor: {sup['stalls']} stall(s), {sup['restarts']} "
            f"restart(s), {sup['planned_restarts']} planned"
        )
        for e in sup["timeline"]:
            detail = {k: v for k, v in e.items() if k not in ("t", "phase")}
            lines.append(f"  t={e['t']:.3f} {e['phase']} {detail or ''}")
    rc = rep.get("recovery")
    if rc:
        lines.append(
            f"recovery: {rc['preempts']} preemption(s), "
            f"{rc['checkpoint_fallbacks']} checkpoint fallback(s)"
            + (f", {rc['mesh_reforms']} mesh re-form(s)"
               if rc.get("mesh_reforms") else "")
        )
        for e in rc["timeline"]:
            detail = {k: v for k, v in e.items() if k not in ("t", "event")}
            lines.append(f"  t={e['t']:.3f} {e['event']} {detail or ''}")
    sv = rep.get("serving_latency_ms")
    if sv:
        lines.append(
            f"serving latency: {sv['batches']} batch(es), {sv['rows']} "
            f"row(s); mean {sv['mean']} ms p50 {sv['p50']} ms "
            f"p90 {sv['p90']} ms p99 {sv['p99']} ms max {sv['max']} ms"
        )
    se = rep.get("serve")
    if se:
        occ = se.get("occupancy")
        lines.append(
            f"serve: {se['batches']} batch(es), {se['rows']} request(s)"
            + (f", occupancy {occ * 100:.1f}%" if occ is not None else "")
            + (f", overloads {se['overloads']}" if se.get("overloads")
               else "")
            + (f"; drained served={se['served']} rejected={se['rejected']}"
               if se.get("served") is not None else "")
        )
        if se.get("by_bucket"):
            lines.append(
                "  by bucket: " + ", ".join(
                    f"{k}×{v}" for k, v in se["by_bucket"].items()
                )
            )
    qa = rep.get("quality")
    if qa:
        conf = qa.get("confidence")
        dw = qa.get("drift_score")
        head = "quality:"
        if conf:
            head += (f" confidence p50 {conf.get('p50')} "
                     f"p99 {conf.get('p99')} (n={conf.get('n')})")
        if qa.get("entropy"):
            head += f", entropy p50 {qa['entropy'].get('p50')}"
        if dw:
            head += f", drift p50 {dw.get('p50')} max {dw.get('max')}"
        lines.append(head)
        dr = qa.get("drift")
        if dr:
            lines.append(
                f"  drift: {dr['snapshots']} snapshot(s), "
                f"last {dr.get('last_score')}, max {dr.get('max_score')}"
            )
        cp = qa.get("captures")
        if cp:
            lines.append(
                f"  captures: {cp['count']}"
                + (" (" + ", ".join(
                    f"{k}×{v}" for k, v in cp["by_reason"].items()
                   ) + ")" if cp.get("by_reason") else "")
            )
        rp = qa.get("replay")
        if rp:
            lines.append(
                f"  replay: {rp['runs']} run(s), last agreement "
                f"{rp.get('agreement')} over {rp.get('n')} request(s) "
                f"({'ok' if rp.get('ok') else 'BELOW GATE'})"
            )
    fl = rep.get("fleet")
    if fl:
        lines.append(
            f"fleet: {fl.get('replicas')} replica(s); "
            f"{fl['losses']} loss(es), {fl['ready_events']} ready "
            f"event(s), {fl['spillovers']} spillover(s), "
            f"{fl['resubmits']} re-submit(s)"
            + (", sheds " + ", ".join(
                f"{k}×{v}" for k, v in fl["sheds"].items()
               ) if fl.get("sheds") else "")
            + (f"; drained routed={fl['routed']} "
               f"answered={fl.get('answered')} "
               f"rejected={fl.get('rejected')} dropped={fl['dropped']}"
               if fl.get("routed") is not None else "")
        )
        if fl.get("scale_verdicts"):
            lines.append(
                "  scale verdicts: " + ", ".join(
                    f"{k}×{v}" for k, v in sorted(
                        fl["scale_verdicts"].items()
                    )
                ) + " (advisory)"
            )
        for e in fl.get("timeline", ()):
            detail = {k: v for k, v in e.items()
                      if k not in ("t", "event")}
            lines.append(f"  t={e['t']:.3f} {e['event']} {detail or ''}")
    ft = rep.get("fleet_timeline")
    if ft:
        lines.append(
            f"fleet timeline (tsdb, last {ft['window_s']:g}s): "
            f"{len(ft['targets'])} target(s), "
            f"{ft['scrape_failures']} scrape failure(s)"
        )
        for target, row in sorted(ft["targets"].items()):
            lines.append(
                f"  {target:<8} p99 {row['spark']} "
                f"last {row['p99_ms_last']} ms · "
                f"median {row['p99_ms_median']} ms · "
                f"max {row['p99_ms_max']} ms "
                f"({row['samples']} sample(s))"
            )
    cn = rep.get("connections")
    if cn:
        ratio = cn.get("reuse_ratio")
        lines.append(
            f"connections: {cn['opened']} opened, "
            f"{cn['reused']} reused"
            + (f" (reuse {ratio * 100:.1f}%)"
               if ratio is not None else "")
            + (", retired " + ", ".join(
                f"{k}×{v}" for k, v in cn["retired"].items()
               ) if cn.get("retired") else "")
        )
    tr = rep.get("traces")
    if tr:
        lines.append(
            f"traces: {tr['sampled']} sampled request(s) "
            f"({tr['forced']} forced: rejects/errors/SLO breaches)"
            + (f", {tr['rejected']} reject(s)" if tr.get("rejected")
               else "")
            + (f"; server p50/p99 {tr.get('server_p50_ms')}/"
               f"{tr.get('server_p99_ms')} ms"
               + (" (tail-biased sample — rate "
                  f"{tr['sample_rate']}, overstates the true tail)"
                  if tr.get("sample_biased") else "")
               if tr.get("server_p99_ms") is not None else "")
        )
        cl = tr.get("client")
        if cl:
            lines.append(
                f"  client (loadgen): p50 {cl.get('client_p50_ms')} ms "
                f"p99 {cl.get('client_p99_ms')} ms"
                + (f", p99 skew over server {cl['skew_p99_ms']} ms"
                   if cl.get("skew_p99_ms") is not None else "")
            )
        if tr.get("slowest"):
            lines.append(
                "  slowest    trace             total     queue  "
                "dispatch  batch  bucket  outcome"
            )
            for row in tr["slowest"]:
                lines.append(
                    f"    {str(row.get('trace')):<16}  "
                    f"{row.get('total_ms') or 0:>8.3f}  "
                    f"{row.get('queue_wait_ms') or 0:>8.3f}  "
                    f"{row.get('dispatch_ms') or 0:>8.3f}  "
                    f"{str(row.get('batch_seq') or '—'):>5}  "
                    f"{str(row.get('bucket') or '—'):>6}  "
                    f"{row.get('outcome')}"
                )
    inc = rep.get("incidents")
    if inc:
        head = (f"incidents: {inc.get('opened', 0)} opened, "
                f"{inc.get('closed', 0)} closed")
        if inc.get("by_rule"):
            head += " (" + ", ".join(
                f"{k}×{v}" for k, v in sorted(inc["by_rule"].items())
            ) + ")"
        if inc.get("still_open"):
            head += "; STILL OPEN: " + ", ".join(inc["still_open"])
        lines.append(head)
        for b in inc.get("bundles", ()):
            lines.append(
                f"  {b['id']}  rule={b.get('rule', '?')} "
                f"state={b.get('state', '?')}"
                + (f" duration={b['duration_s']}s"
                   if b.get("duration_s") is not None else "")
            )
    w = rep.get("warnings")
    if w:
        lines.append(
            "warnings: " + ", ".join(f"{k}×{v}" for k, v in sorted(w.items()))
        )
    m = rep.get("metrics")
    if m:
        lines.append(f"metrics records: {m['count']}")
        for kind in sorted(m["last"]):
            rec = m["last"][kind]
            keep = {
                k: rec[k]
                for k in ("step", "loss", "accuracy", "samples_per_sec")
                if k in rec
            }
            lines.append(f"  last {kind}: {json.dumps(keep)}")
    return "\n".join(lines)


# --- live tail ---------------------------------------------------------------

class EventTail:
    """Incremental reader over a run directory's event stream(s).

    Each ``poll()`` re-discovers the per-host files (a late host's stream
    appears mid-run), seeks every known file to its last consumed offset,
    and parses only the new COMPLETE lines — a partial trailing line (a
    writer mid-``write`` on a non-POSIX filesystem, or a reader racing the
    kernel) is left for the next poll rather than counted as corrupt.
    Nothing is ever re-parsed: the PARSING cost of a poll is only the
    bytes appended since the last one. (Each re-render still folds the
    full accumulated history — build_report is O(events) — which is fine
    for the runs this repo produces; a multi-day tail would want a
    windowed report.)
    """

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self.events: list[dict] = []
        self.bad = 0
        self._offsets: dict[str, int] = {}

    def poll(self) -> list[dict]:
        """Consume and return the newly appended events (also accumulated
        into ``self.events``, unsorted — sort before reporting)."""
        new: list[dict] = []
        for path, idx in discover_event_files(self.run_dir):
            offset = self._offsets.get(path, 0)
            try:
                size = os.path.getsize(path)
                if size <= offset:
                    continue
                with open(path, "rb") as fh:
                    fh.seek(offset)
                    chunk = fh.read(size - offset)
            except OSError:
                continue  # rotated/removed underneath us: re-poll later
            last_nl = chunk.rfind(b"\n")
            if last_nl < 0:
                continue  # no complete line yet
            self._offsets[path] = offset + last_nl + 1
            lines = chunk[:last_nl].decode("utf-8", "replace").splitlines()
            self.bad += _parse_lines(lines, idx, new)
        self.events.extend(new)
        return new


def is_terminal_event(e: dict) -> bool:
    """True when this event can mean the run is over: a ``run_end`` (one
    host completed its full step budget) or the supervisor's final verdict
    (``done`` / ``giving_up`` — restart budget exhausted). A supervisor
    verdict ends the whole run; a ``run_end`` ends only its own host's
    stream — ``follow_report`` waits for one per discovered stream, so a
    fast host finishing first doesn't declare a still-running mesh done."""
    return e.get("ev") == "run_end" or (
        e.get("ev") == "supervisor"
        and e.get("phase") in ("done", "giving_up")
    )


def follow_header(rep: dict, run_dir: str) -> str:
    """The live tail's one-line banner: where the mesh stands *right now* —
    host count, per-host loop-start skew, and the cross-host data-wait
    spread (a fat spread on a lockstep mesh is free throughput, worth
    noticing while the run is still hot, not in the post-mortem). Falls
    back to a single-host marker when only one stream exists."""
    parts = [f"following {run_dir}"]
    hosts = rep.get("hosts")
    if hosts:
        parts.append(f"{len(hosts)} hosts")
        parts.extend(_skew_parts(rep.get("host_skew") or {}))
    else:
        parts.append("single host")
    return "== " + " | ".join(parts)


def follow_slo_line(rep: dict) -> Optional[str]:
    """The live tail's second line: the latest window percentiles and the
    rules firing *right now* — degradation visible while it happens, not
    in the post-mortem. None when the run carries no SLO telemetry."""
    slo = rep.get("slo") or {}
    parts = []
    windows = slo.get("windows") or {}
    for metric in ("step_ms", "data_wait_ms", "queue_depth",
                   "heartbeat_age_s", "serving_ms", "queue_wait_ms"):
        row = windows.get(metric)
        if row:
            parts.append(
                f"{metric} p50 {row.get('p50')}/p99 {row.get('p99')}"
            )
    active = sorted(
        rule for rule, a in (slo.get("alerts") or {}).items()
        if a.get("active")
    )
    if active:
        parts.append("ALERTS: " + ", ".join(active))
    if not parts:
        return None
    return "== slo | " + " | ".join(parts)


def follow_perf_line(rep: dict) -> Optional[str]:
    """The live tail's perf readout next to the SLO line: the current
    rolling MFU (or its explicit unknown tier) and the device-memory
    watermark. None when the run carries no perf telemetry."""
    pf = rep.get("perf")
    if not pf:
        return None
    parts = [_perf_headline(pf)[len("perf: "):]]
    dm = pf.get("device_memory")
    if dm:
        top = max(v["watermark_bytes"] for v in dm.values())
        parts.append(f"device-memory watermark {top / 1e6:.1f} MB")
    return "== perf | " + " | ".join(parts)


def follow_report(
    run_dir: str,
    interval: float = 3.0,
    out: Callable[[str], None] = print,
    clock: Callable[[float], None] = time.sleep,
    max_polls: Optional[int] = None,
    clear: bool = True,
) -> None:
    """Live tail: re-render the report every ``interval`` seconds while the
    run is hot; return when a terminal event appears (``is_terminal_event``)
    or after ``max_polls`` polls (tests). Ctrl-C is the caller's concern —
    the CLI wraps this in a KeyboardInterrupt handler so ^C exits cleanly
    rather than with a stack trace."""
    tail = EventTail(run_dir)
    manifest = None
    polls = 0
    ended_hosts: set[int] = set()
    supervisor_verdict = False
    while True:
        new = tail.poll()
        if manifest is None:
            manifest = load_manifest(run_dir)
        if new or polls == 0:
            events = sorted(tail.events, key=lambda e: e["t"])
            rep = build_report(events, manifest, bad_lines=tail.bad)
            prefix = "\x1b[2J\x1b[H" if clear else ""
            slo_line = follow_slo_line(rep)
            perf_line = follow_perf_line(rep)
            out(
                prefix + follow_header(rep, run_dir) + "\n"
                + (slo_line + "\n" if slo_line else "")
                + (perf_line + "\n" if perf_line else "")
                + format_report(rep)
                + f"\n-- following {run_dir} ({len(events)} events, "
                f"re-render every {interval:g}s; Ctrl-C to stop)"
            )
        for e in new:
            if e.get("ev") == "run_end":
                ended_hosts.add(int(e.get("process_index") or 0))
            elif is_terminal_event(e):
                supervisor_verdict = True
        # The supervisor's verdict ends everything; run_end is per host —
        # exit only once every discovered stream has produced one, so the
        # slowest host's tail (and the final checkpoint it is writing)
        # still renders.
        streams = {idx for _, idx in discover_event_files(run_dir)}
        if supervisor_verdict or (streams and ended_hosts >= streams):
            out("-- run ended; follow exiting")
            return
        polls += 1
        if max_polls is not None and polls >= max_polls:
            return
        clock(interval)


# --- event-schema lint -------------------------------------------------------

KNOWN_EVENT_KINDS = frozenset({
    "run_start", "run_end", "span", "gauge", "metrics", "warning",
    "heartbeat", "supervisor", "loop_start", "loop_end",
    # Recovery events (the fault-tolerance layer): a SIGTERM drain that
    # checkpointed and exited for a planned respawn, and a restore that
    # fell back past a corrupt latest checkpoint.
    "preempt", "checkpoint_fallback",
    # Live-SLO events (obs.windows / obs.alerts): a rolling-window
    # percentile snapshot, and an alert rule crossing into violation
    # (state="fire") or recovering (state="resolve") — hysteresis pairs,
    # never per-cycle re-fires.
    "window_summary", "alert",
    # Runtime-registry events (featurenet_tpu.runtime): an XLA compile of
    # a named program, and the persistent executable cache's verdicts —
    # hit (deserialized, compile skipped), miss (no entry), reject (entry
    # present but corrupt/stale/probe-refused; degraded to fresh compile).
    "program_compile", "cache_hit", "cache_miss", "cache_reject",
    # Performance attribution (obs.perf): a built program's compiled
    # cost/memory counters (every field beyond `program` is capture-path-
    # optional — a backend without cost analysis emits an honestly
    # partial record), and one device's memory_stats() sample from the
    # opt-in heartbeat-cadence poller.
    "program_cost", "device_memory",
    # Serving front end (featurenet_tpu.serve): service came up with its
    # bucket ladder, one dispatched batch (bucket/fill/padding), one
    # admission fast-reject at the queue bound, and the drain record.
    "serve_start", "serve_batch", "overload", "serve_stop",
    # Elastic membership (featurenet_tpu.elastic): the coordinator
    # re-formed the mesh at a new world size (shrink on host loss, grow
    # on re-admission), and the per-slot transitions — a host charged as
    # lost, a recovered host re-admitted at a generation boundary.
    "mesh_reform", "host_leave", "host_join",
    # Request-level tracing (obs.tracing): the per-request serving
    # timeline — admitted into the queue, dispatched on a batch
    # (batch_seq ties it to its serve_dispatch span), completed with the
    # queue/device split, or fast-rejected at the admission bound.
    # Tail-biased sampled: rejections, errors, and SLO breaches are
    # always present; healthy traffic at the Config.trace_sample rate.
    "request_admit", "request_dispatch", "request_done", "request_reject",
    # The open-loop load generator's client-side summary: what the
    # CALLER observed (client p50/p99 vs the server's serving_ms windows
    # — the skew between them is real queueing, measured on one clock).
    "loadgen",
    # Serving fleet (featurenet_tpu.fleet): the router came up over N
    # replicas; a replica turned ready (first warmup or a respawn
    # rejoining the roster) / was charged lost (death, stall, startup
    # timeout); one overloaded replica's request spilled to the next
    # healthy one; one in-flight request was re-submitted to a survivor
    # after its replica died under it; a batch-lane request was shed at
    # the router; an advisory scaling verdict changed; the router's
    # drain record (routed / answered / rejected / dropped — dropped is
    # the gate-pinned zero).
    "fleet_start", "fleet_replica_ready", "fleet_replica_loss",
    "fleet_spillover", "fleet_resubmit", "fleet_shed", "fleet_scale",
    "fleet_stop",
    # The acting control loop (fleet.replica.Autoscaler): the roster
    # actually moved — an add or shed taken on a SUSTAINED verdict after
    # hysteresis + cooldown damping (fleet_scale above stays the
    # advisory verdict-change record).
    "fleet_autoscale",
    # Zero-downtime weight rollout: one replica's live hot-swap attempt
    # (ok either way — a refused swap is an event too), and the
    # orchestrator's arc — rollout began over N replicas, one replica
    # finished its canary+swap step, already-swapped replicas were
    # rolled back (canary failure / replica death mid-rollout), rollout
    # finished with its converged version.
    "swap", "rollout_start", "rollout_step", "rollout_rollback",
    "rollout_done",
    # Persistent-connection data plane (fleet.pool): a fresh channel
    # opened (carrying its connect_ms — the handshake cost pooling
    # amortizes), an idle keep-alive channel reused, and a channel
    # retired with its reason (broken / max_age / idle_overflow /
    # server_close / probe_failure / replica_loss / shutdown).
    "conn_open", "conn_reuse", "conn_retire",
    # Model-quality plane (obs.quality / serve.recorder / cli replay):
    # a rolling prediction-mix drift snapshot (TV score of the live
    # predicted-class histogram vs the pinned baseline), one request
    # captured into the flight-recorder ring (with the reason it was
    # kept — sampled, or forced: low_confidence / rejected /
    # slo_breach), and a replay canary's verdict (agreement of a
    # candidate against a recorded capture ring).
    "quality_drift", "capture", "replay_verdict",
    # Incident plane (obs.incidents): an alert firing opened a
    # diagnostic bundle (at most one per rule, flap-damped by a
    # post-close cooldown), its capture landed on disk (tsdb slice /
    # windows / roster / events tail / folded host stacks), and the
    # paired resolve closed it with its duration.
    "incident_open", "incident_capture", "incident_close",
})

# Fields (beyond t/ev) a record must carry for the report to fold it.
REQUIRED_EVENT_FIELDS = {
    "span": ("name", "dur_s"),
    "gauge": ("name", "value"),
    "warning": ("name", "msg"),
    "supervisor": ("phase",),
    "loop_start": ("step",),
    "loop_end": ("step",),
    "metrics": ("kind",),
    "preempt": ("step",),
    "checkpoint_fallback": ("from_step", "to_step"),
    "window_summary": ("metric", "n", "p50", "p95", "p99"),
    "alert": ("rule", "severity", "value", "threshold", "window", "state"),
    "program_compile": ("program", "dur_s"),
    # program_cost: only the program name is required — flops/bytes/
    # peak_bytes are honest-absence fields (a backend may answer none of
    # them), so the schema must not condemn a degraded capture.
    "program_cost": ("program",),
    "device_memory": ("device", "bytes_in_use"),
    "cache_hit": ("program",),
    "cache_miss": ("program",),
    "cache_reject": ("program", "reason"),
    "serve_start": ("buckets", "max_wait_ms", "queue_limit"),
    "serve_batch": ("bucket", "n", "batch_seq"),
    "overload": ("queue_depth", "limit"),
    "serve_stop": ("served", "rejected"),
    "mesh_reform": ("generation", "from_n", "to_n", "reason"),
    "host_leave": ("host", "generation", "reason"),
    "host_join": ("host", "generation"),
    "request_admit": ("trace",),
    "request_dispatch": ("trace", "batch_seq", "bucket", "pad"),
    "request_done": ("trace", "queue_wait_ms", "dispatch_ms", "total_ms",
                     "outcome"),
    "request_reject": ("trace", "queue_depth", "limit"),
    "loadgen": ("n", "client_p50_ms", "client_p99_ms"),
    "fleet_start": ("replicas",),
    "fleet_replica_ready": ("replica",),
    "fleet_replica_loss": ("replica", "reason"),
    "fleet_spillover": ("trace", "from_replica"),
    "fleet_resubmit": ("trace", "from_replica"),
    "fleet_shed": ("lane",),
    "fleet_scale": ("verdict",),
    "fleet_autoscale": ("action", "from_n", "to_n", "reason"),
    "fleet_stop": ("routed", "dropped"),
    "swap": ("ok", "from_version", "swap_ms"),
    "rollout_start": ("checkpoint_dir", "replicas"),
    "rollout_step": ("replica", "ok"),
    "rollout_rollback": ("reason", "rolled_back"),
    "rollout_done": ("ok", "swapped"),
    "conn_open": ("endpoint",),
    "conn_reuse": ("endpoint",),
    "conn_retire": ("endpoint", "reason"),
    "quality_drift": ("score", "n"),
    "capture": ("trace", "reason"),
    "replay_verdict": ("agreement", "n", "ok"),
    "incident_open": ("id", "rule", "severity", "value"),
    "incident_capture": ("id", "files"),
    "incident_close": ("id", "rule", "duration_s"),
}

# The event kinds that carry a per-request ``trace`` id — the timeline
# view (``cli report --request``) and the traces section key off this.
REQUEST_EVENT_KINDS = ("request_admit", "request_dispatch",
                       "request_done", "request_reject")

# Required at EMIT sites (the analysis linter holds new code to the full
# tuples above) but tolerated as absent by ``validate_events``: archived
# run dirs predate the field and must keep validating, mirroring the
# legacy fallbacks the report sections already implement.
LEGACY_OPTIONAL_FIELDS = {
    "alert": ("state",),  # pre-hysteresis streams re-fired with no state
    # pre-tracing serve streams carried no dispatch sequence number
    "serve_batch": ("batch_seq",),
}

# Wall-clock start stamps vs perf_counter durations: a parent records its
# start before the child does and emits after, so real nesting violates
# containment only by clock jitter — allow a small slack.
_NEST_EPS_S = 0.05


def validate_events(events: list[dict], bad_lines: int = 0) -> list[dict]:
    """Schema lint: unknown event kinds, missing required fields, negative
    durations, and non-monotonic span nesting (a span naming a ``parent``
    must fit inside some same-thread span of that name — a child interval
    escaping its parent means a torn/reordered stream or a broken clock).
    Returns finding dicts (``check`` / ``msg`` / optional ``event``);
    empty = clean. Malformed telemetry should fail fast in CI, not
    corrupt reports quietly."""
    findings: list[dict] = []
    if bad_lines:
        findings.append({
            "check": "parse",
            "msg": f"{bad_lines} unparseable line(s) in the stream(s)",
        })
    spans_by_thread: dict[tuple, list[dict]] = {}
    for e in events:
        ev = e.get("ev")
        if ev not in KNOWN_EVENT_KINDS:
            findings.append({
                "check": "unknown_kind",
                "msg": f"unknown event kind {ev!r}",
                "event": e,
            })
            continue
        legacy_ok = LEGACY_OPTIONAL_FIELDS.get(ev, ())
        missing = [
            f for f in REQUIRED_EVENT_FIELDS.get(ev, ())
            if f not in e and f not in legacy_ok
        ]
        if missing:
            findings.append({
                "check": "missing_fields",
                "msg": f"{ev!r} event missing required field(s) "
                       f"{missing}",
                "event": e,
            })
            continue
        if ev == "span":
            if e["dur_s"] < 0:
                findings.append({
                    "check": "negative_duration",
                    "msg": f"span {e.get('name')!r} has dur_s {e['dur_s']}",
                    "event": e,
                })
                continue
            key = (e.get("process_index", 0), e.get("pid"), e.get("thread"))
            spans_by_thread.setdefault(key, []).append(e)
    for group in spans_by_thread.values():
        for s in group:
            parent = s.get("parent")
            if not parent:
                continue
            candidates = [q for q in group if q.get("name") == parent]
            if not candidates:
                findings.append({
                    "check": "orphan_parent",
                    "msg": f"span {s.get('name')!r} names parent "
                           f"{parent!r} but no such span exists on its "
                           "thread",
                    "event": s,
                })
            elif not any(
                q["t"] - _NEST_EPS_S <= s["t"]
                and s["t"] + s["dur_s"] <= q["t"] + q["dur_s"] + _NEST_EPS_S
                for q in candidates
            ):
                findings.append({
                    "check": "span_nesting",
                    "msg": f"span {s.get('name')!r} "
                           f"[t={s['t']:.3f}, dur={s['dur_s']:.3f}] is not "
                           f"contained in any {parent!r} span on its "
                           "thread (non-monotonic nesting)",
                    "event": s,
                })
    return findings
