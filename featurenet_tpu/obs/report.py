"""Post-hoc run analysis: fold ``events.jsonl`` into answers.

``build_report`` turns a run directory's event log into the questions an
operator actually asks after a run (or a crash):

- **Where did the wall-clock go?** Step-time breakdown over the train
  loop's window(s): data-wait vs dispatch vs readback vs eval vs
  checkpoint seconds and fractions, with ``other`` as the explicit
  remainder so the fractions always account for 100% of loop wall time.
- **Did the input pipeline starve the device?** Prefetch queue-depth
  percentiles (a queue pinned at 0 = starved consumer) and producer
  batch-generation timing.
- **Was the run healthy?** Heartbeat count + max inter-beat age, the
  supervisor's restart/stall timeline, warning counts, and every
  ``run_start`` (each process (re)spawn) in order.
- **How fast is serving?** Per-batch ``infer_batch`` latency percentiles.

Everything here is stdlib-only and never touches JAX — the report CLI
must run on a machine (or in a moment) where the backend that produced
the run is long gone.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from featurenet_tpu.obs.events import EVENTS_FILENAME, MANIFEST_FILENAME

# Loop-attributed span names, in display order. "device" time is
# dispatch + readback: the dispatch call enqueues work, the readback is
# where the host actually blocks on device execution.
LOOP_CATEGORIES = ("data_wait", "dispatch", "readback", "eval", "checkpoint")


def load_events(run_dir: str) -> tuple[list[dict], int]:
    """All events, time-ordered, plus the count of unparseable lines (a
    torn line from a killed process must not take the report down with
    it — it is exactly the crashed run we are here to inspect)."""
    path = os.path.join(run_dir, EVENTS_FILENAME)
    events: list[dict] = []
    bad = 0
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(e, dict) and "t" in e and "ev" in e:
                events.append(e)
            else:
                bad += 1
    events.sort(key=lambda e: e["t"])
    return events, bad


def load_manifest(run_dir: str) -> Optional[dict]:
    path = os.path.join(run_dir, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def _pct(sorted_vals: list, q: float):
    """Nearest-rank percentile of an already-sorted list."""
    if not sorted_vals:
        return None
    i = int(round(q / 100.0 * (len(sorted_vals) - 1)))
    return sorted_vals[min(max(i, 0), len(sorted_vals) - 1)]


def _loop_windows(events: list[dict]) -> list[tuple[dict, dict]]:
    """(start, end) event pairs. A trailing start without an end is the
    run that was SIGKILLed mid-loop (a supervisor stall verdict skips the
    finally block) — exactly the segment worth diagnosing — so it is
    closed synthetically at the last event's timestamp instead of being
    dropped; the synthetic end carries ``truncated: True`` and the
    highest step any event in the window reported."""
    def close(start: dict, t_end: float) -> dict:
        last_step = max(
            (e["step"] for e in events
             if start["t"] <= e["t"] <= t_end
             and isinstance(e.get("step"), (int, float))),
            default=start.get("step", 0),
        )
        return {"t": t_end, "step": int(last_step),
                "wall_s": t_end - start["t"], "truncated": True}

    windows = []
    start = None
    for e in events:
        if e["ev"] == "loop_start":
            # A start while one is pending = the previous segment died
            # without its loop_end and a respawn began; close the dead
            # one at the respawn boundary so its spans stay attributed.
            if start is not None and e["t"] > start["t"]:
                windows.append((start, close(start, e["t"])))
            start = e
        elif e["ev"] == "loop_end" and start is not None:
            windows.append((start, e))
            start = None
    if start is not None and events and events[-1]["t"] > start["t"]:
        windows.append((start, close(start, events[-1]["t"])))
    return windows


def build_report(events: list[dict], manifest: Optional[dict] = None,
                 bad_lines: int = 0) -> dict:
    rep: dict = {"n_events": len(events), "bad_lines": bad_lines}
    if manifest:
        cfg = manifest.get("config") or {}
        rep["run"] = {
            "run_dir": manifest.get("run_dir"),
            "start_time": manifest.get("start_time"),
            "config_name": cfg.get("name"),
            "task": cfg.get("task"),
            "process_index": (manifest.get("jax") or {}).get("process_index"),
            "device_count": (manifest.get("jax") or {}).get("device_count"),
        }
    rep["process_starts"] = sum(1 for e in events if e["ev"] == "run_start")

    # --- step-time breakdown over the loop window(s) ------------------------
    windows = _loop_windows(events)
    wall = sum(
        end.get("wall_s", end["t"] - start["t"]) for start, end in windows
    )
    steps = sum(
        end.get("step", 0) - start.get("step", 0) for start, end in windows
    )
    spans = [e for e in events if e["ev"] == "span" and "dur_s" in e]
    in_window = [
        s for s in spans
        if any(st["t"] <= s["t"] <= en["t"] for st, en in windows)
    ]
    cat_s = {c: 0.0 for c in LOOP_CATEGORIES}
    for s in in_window:
        if s.get("name") in cat_s:
            cat_s[s["name"]] += s["dur_s"]
    rep["loop"] = {
        "windows": len(windows),
        "truncated_windows": sum(
            1 for _, end in windows if end.get("truncated")
        ),
        "wall_s": round(wall, 4),
        "steps": steps,
        "step_ms": round(wall / steps * 1e3, 2) if steps else None,
    }
    if wall > 0:
        attributed = sum(cat_s.values())
        breakdown = {
            c: {"seconds": round(v, 4), "fraction": round(v / wall, 4)}
            for c, v in cat_s.items()
        }
        other = max(wall - attributed, 0.0)
        breakdown["other"] = {
            "seconds": round(other, 4),
            "fraction": round(other / wall, 4),
        }
        rep["breakdown"] = breakdown
        rep["attributed_fraction"] = round(min(attributed / wall, 1.0), 4)

    # --- input pipeline -----------------------------------------------------
    depths = sorted(
        e["value"] for e in events
        if e["ev"] == "gauge" and e.get("name") == "prefetch_queue_depth"
    )
    if depths:
        rep["prefetch_queue_depth"] = {
            "n": len(depths),
            "p10": _pct(depths, 10),
            "p50": _pct(depths, 50),
            "p90": _pct(depths, 90),
            "max": depths[-1],
        }
    gen = sorted(
        e["value"] for e in events
        if e["ev"] == "gauge" and e.get("name") == "producer_batch_s"
    )
    if gen:
        rep["producer_batch_s"] = {
            "n": len(gen),
            "mean": round(sum(gen) / len(gen), 4),
            "p90": round(_pct(gen, 90), 4),
            "max": round(gen[-1], 4),
        }

    # --- liveness / supervision --------------------------------------------
    beats = [e for e in events if e["ev"] == "heartbeat"]
    if beats:
        ages = [e.get("age_s") for e in beats if e.get("age_s") is not None]
        rep["heartbeat"] = {
            "beats": len(beats),
            "max_age_s": round(max(ages), 3) if ages else None,
        }
    sup = [e for e in events if e["ev"] == "supervisor"]
    if sup:
        phases = [e.get("phase") for e in sup]
        rep["supervisor"] = {
            "stalls": phases.count("stall"),
            "restarts": phases.count("restart"),
            "planned_restarts": phases.count("planned_restart"),
            "timeline": [
                {"t": round(e["t"], 3), "phase": e.get("phase"),
                 **{k: v for k, v in e.items()
                    if k not in ("t", "ev", "phase")}}
                for e in sup
            ],
        }

    # --- serving ------------------------------------------------------------
    lat = sorted(
        s["dur_s"] * 1e3 for s in spans if s.get("name") == "infer_batch"
    )
    if lat:
        rep["serving_latency_ms"] = {
            "batches": len(lat),
            "rows": sum(
                s.get("n", 0) for s in spans if s.get("name") == "infer_batch"
            ),
            "mean": round(sum(lat) / len(lat), 3),
            "p50": round(_pct(lat, 50), 3),
            "p90": round(_pct(lat, 90), 3),
            "p99": round(_pct(lat, 99), 3),
            "max": round(lat[-1], 3),
        }

    # --- warnings / metrics -------------------------------------------------
    warns = [e for e in events if e["ev"] == "warning"]
    if warns:
        by_name: dict[str, int] = {}
        for e in warns:
            by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
        rep["warnings"] = by_name
    metrics = [e for e in events if e["ev"] == "metrics"]
    if metrics:
        last: dict[str, dict] = {}
        for e in metrics:
            last[e.get("kind", "?")] = {
                k: v for k, v in e.items() if k not in ("ev",)
            }
        rep["metrics"] = {"count": len(metrics), "last": last}
    return rep


def build_report_dir(run_dir: str) -> dict:
    events, bad = load_events(run_dir)
    return build_report(events, load_manifest(run_dir), bad_lines=bad)


def _fmt_s(v: float) -> str:
    return f"{v:.3f}s" if v < 100 else f"{v:.1f}s"


def format_report(rep: dict) -> str:
    """Human-readable rendering (the CLI's default output; --json gives
    the raw dict)."""
    lines = []
    run = rep.get("run") or {}
    head = "run"
    if run.get("config_name"):
        head += f" [{run['config_name']}/{run.get('task')}]"
    if run.get("start_time"):
        head += f" started {run['start_time']}"
    if run.get("device_count") is not None:
        head += f", {run['device_count']} device(s)"
    lines.append(head)
    lines.append(
        f"events: {rep['n_events']}"
        + (f" ({rep['bad_lines']} unparseable)" if rep.get("bad_lines") else "")
        + f", process starts: {rep.get('process_starts', 0)}"
    )
    loop = rep.get("loop", {})
    if loop.get("wall_s"):
        trunc = loop.get("truncated_windows", 0)
        lines.append(
            f"loop: {loop['steps']} step(s) over {loop['windows']} "
            f"window(s), wall {_fmt_s(loop['wall_s'])}"
            + (f", {loop['step_ms']} ms/step" if loop.get("step_ms") else "")
            + (f" ({trunc} window(s) truncated by a kill)" if trunc else "")
        )
    bd = rep.get("breakdown")
    if bd:
        lines.append("step-time breakdown (fractions of loop wall):")
        for name in (*LOOP_CATEGORIES, "other"):
            row = bd[name]
            lines.append(
                f"  {name:<11} {row['seconds']:>9.3f}s  "
                f"{row['fraction'] * 100:5.1f}%"
            )
        lines.append(
            f"  attributed (non-other): "
            f"{rep['attributed_fraction'] * 100:.1f}%"
        )
    q = rep.get("prefetch_queue_depth")
    if q:
        lines.append(
            f"prefetch queue depth: p10 {q['p10']} p50 {q['p50']} "
            f"p90 {q['p90']} max {q['max']} (n={q['n']})"
        )
    g = rep.get("producer_batch_s")
    if g:
        lines.append(
            f"producer batch gen: mean {g['mean'] * 1e3:.1f} ms "
            f"p90 {g['p90'] * 1e3:.1f} ms (n={g['n']})"
        )
    hb = rep.get("heartbeat")
    if hb:
        age = hb.get("max_age_s")
        lines.append(
            f"heartbeat: {hb['beats']} beat(s)"
            + (f", max age {age}s" if age is not None else "")
        )
    sup = rep.get("supervisor")
    if sup:
        lines.append(
            f"supervisor: {sup['stalls']} stall(s), {sup['restarts']} "
            f"restart(s), {sup['planned_restarts']} planned"
        )
        for e in sup["timeline"]:
            detail = {k: v for k, v in e.items() if k not in ("t", "phase")}
            lines.append(f"  t={e['t']:.3f} {e['phase']} {detail or ''}")
    sv = rep.get("serving_latency_ms")
    if sv:
        lines.append(
            f"serving latency: {sv['batches']} batch(es), {sv['rows']} "
            f"row(s); mean {sv['mean']} ms p50 {sv['p50']} ms "
            f"p90 {sv['p90']} ms p99 {sv['p99']} ms max {sv['max']} ms"
        )
    w = rep.get("warnings")
    if w:
        lines.append(
            "warnings: " + ", ".join(f"{k}×{v}" for k, v in sorted(w.items()))
        )
    m = rep.get("metrics")
    if m:
        lines.append(f"metrics records: {m['count']}")
        for kind in sorted(m["last"]):
            rec = m["last"][kind]
            keep = {
                k: rec[k]
                for k in ("step", "loss", "accuracy", "samples_per_sec")
                if k in rec
            }
            lines.append(f"  last {kind}: {json.dumps(keep)}")
    return "\n".join(lines)
