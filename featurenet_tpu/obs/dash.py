"""``cli dash``: the live terminal fleet dashboard.

Renders ONE source of truth — the run_dir time-series store the fleet
scraper populates (``obs.tsdb``) — into a terminal frame: per-replica
qps / p99 / queue-depth sparklines, the burn-rate gauges the scale
verdicts judge, connection-reuse, roster state, and scrape-failure
counts. Because every number comes off the store, the dashboard works
identically against a live fleet (the scraper is appending while we
read — torn tails are the store's problem, already solved) and against
a *finished* run_dir hours later: ``cli dash --once`` renders a single
frame for tests, CI artifacts, and post-mortems.

Stdlib-only, read-only, and render-pure: ``render_frame`` takes a
run_dir and returns a string; the CLI loop just reprints it. No curses —
ANSI clear + redraw keeps it dumb enough to pipe.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from featurenet_tpu.obs import alerts as _alerts
from featurenet_tpu.obs import incidents as _incidents
from featurenet_tpu.obs import tsdb as _tsdb

DEFAULT_WINDOW_S = 300.0
SPARK_SLOTS = 32

_BLOCKS = "▁▂▃▄▅▆▇█"

ROUTER_TARGET = "router"


def _spark(vals: list) -> str:
    """One sparkline: a list of per-slot values (None = no data → a
    space) scaled to the 8 block glyphs. All-equal non-zero data renders
    mid-height, honest absence renders as gaps."""
    present = [v for v in vals if v is not None]
    if not present:
        return " " * len(vals)
    lo, hi = min(present), max(present)
    span = hi - lo
    out = []
    for v in vals:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_BLOCKS[3] if hi else _BLOCKS[0])
        else:
            idx = int((v - lo) / span * (len(_BLOCKS) - 1))
            out.append(_BLOCKS[max(0, min(idx, len(_BLOCKS) - 1))])
    return "".join(out)


def _bucket(samples: list, now: float, window_s: float,
            slots: int = SPARK_SLOTS) -> list:
    """Slot the window's (t, v) samples into ``slots`` buckets, last
    value per bucket (gauges are scraped snapshots — last wins)."""
    out: list = [None] * slots
    t0 = now - window_s
    for t, v in samples:
        if t < t0 or t > now:
            continue
        i = min(int((t - t0) / window_s * slots), slots - 1)
        out[i] = v
    return out


def _rates(samples: list) -> list:
    """Consecutive-sample rates of a cumulative counter: (t, per-second
    increase). A counter reset (process restart) shows as a gap, not a
    negative spike."""
    out = []
    for (t1, v1), (t2, v2) in zip(samples, samples[1:]):
        dt = t2 - t1
        if dt <= 0 or v2 < v1:
            continue
        out.append((t2, (v2 - v1) / dt))
    return out


def _fmt(v, digits: int = 1) -> str:
    if v is None:
        return "-"
    return f"{v:.{digits}f}"


def _replica_targets(store) -> list[str]:
    """Every target the scraper has written samples for, replicas
    first (numeric order), router last."""
    targets = set()
    for _metric, labels in store.series():
        t = labels.get("replica")
        if t is not None:
            targets.add(t)
    reps = sorted((t for t in targets if t != ROUTER_TARGET),
                  key=lambda s: (not s.isdigit(), int(s) if s.isdigit()
                                 else 0, s))
    if ROUTER_TARGET in targets:
        reps.append(ROUTER_TARGET)
    return reps


def render_frame(run_dir: str, *, window_s: float = DEFAULT_WINDOW_S,
                 slos: Optional[str] = None,
                 fast_s: float = _alerts.DEFAULT_FAST_WINDOW_S,
                 slow_s: float = _alerts.DEFAULT_SLOW_WINDOW_S,
                 now: Optional[float] = None) -> str:
    """One dashboard frame from the store alone. ``now`` pins the frame
    time for tests; live use reads the wall clock (the store's axis)."""
    if now is None:
        now = time.time()
    store = _tsdb.TimeSeriesStore.open(run_dir)
    targets = _replica_targets(store)
    if not store.series():
        # Friendly empty state instead of a meaningless table: a brand
        # new run_dir, a typo'd path, and a finished-but-never-scraped
        # run all land here (the store absorbs the missing directory).
        where = _tsdb.store_dir(run_dir)
        why = ("no such directory" if not os.path.isdir(where)
               else "no samples yet")
        return (
            f"fleet dash · {run_dir} · 0 target(s)\n"
            f"no telemetry series under {where} ({why}) — the store is "
            f"populated by the fleet scraper (`cli fleet --run-dir`), "
            f"so point dash at a fleet run_dir or wait for the first "
            f"scrape round\n"
            f"{_incident_line(run_dir)}\n"
        )
    lines = [
        f"fleet dash · {run_dir} · window {window_s:g}s · "
        f"{len(targets)} target(s)",
        "",
    ]
    head = (f"{'replica':<8} {'qps':<{SPARK_SLOTS + 8}} "
            f"{'p99_ms':<{SPARK_SLOTS + 9}} {'queue':<{SPARK_SLOTS + 6}}")
    lines.append(head)
    for target in targets:
        if target == ROUTER_TARGET:
            served = store.query("fleet_requests_total",
                                 {"outcome": "answered",
                                  "replica": target},
                                 since_s=window_s + 60, now=now)
        else:
            served = store.query("requests_total",
                                 {"outcome": "served", "replica": target},
                                 since_s=window_s + 60, now=now)
        qps = _bucket(_rates(served), now, window_s)
        p99s = store.query("serving_ms", {"q": "0.99", "replica": target},
                           since_s=window_s, now=now)
        p99 = _bucket(p99s, now, window_s)
        depth = _bucket(
            store.query("serve_queue_depth", {"replica": target},
                        since_s=window_s, now=now),
            now, window_s,
        )
        last_qps = next((v for v in reversed(qps) if v is not None), None)
        last_p99 = next((v for v in reversed(p99) if v is not None), None)
        last_dep = next((v for v in reversed(depth) if v is not None),
                        None)
        lines.append(
            f"{target:<8} {_spark(qps)} {_fmt(last_qps):>6}  "
            f"{_spark(p99)} {_fmt(last_p99):>7}  "
            f"{_spark(depth)} {_fmt(last_dep, 0):>4}"
        )

    # Burn gauges: the same rules + math the router's verdicts use.
    lines.append("")
    rules = _alerts.parse_slos(slos, fast_s=fast_s, slow_s=slow_s)
    for rule in rules:
        sel = _alerts.burn_selector(rule.metric)
        if sel is None:
            continue
        samples = store.query(sel[0], sel[1], since_s=rule.slow_s,
                              now=now)
        fast = _alerts.burn_rate(samples, rule, rule.fast_s, now)
        slow = _alerts.burn_rate(samples, rule, rule.slow_s, now)
        firing = (fast is not None and slow is not None
                  and fast > rule.max_burn and slow > rule.max_burn)
        state = "FIRING" if firing else "ok"
        lines.append(
            f"burn {rule.metric} ({rule.op}{rule.threshold:g}@"
            f"{rule.objective * 100:g}%): fast {_fmt(fast, 2)}  "
            f"slow {_fmt(slow, 2)}  [{state}]"
        )

    # Model-quality panel: only when the quality plane is on (some
    # target exported confidence windows into the store). Confidence
    # p50 collapsing and the drift score rising are exactly the two
    # lines the quality alert rules watch.
    q_rows = []
    for target in targets:
        conf_s = store.query("confidence", {"q": "0.5", "replica": target},
                             since_s=window_s, now=now)
        drift_s = store.query("quality_drift_score",
                              {"q": "0.5", "replica": target},
                              since_s=window_s, now=now)
        if not conf_s and not drift_s:
            continue
        conf = _bucket(conf_s, now, window_s)
        drift = _bucket(drift_s, now, window_s)
        last_c = next((v for v in reversed(conf) if v is not None), None)
        last_d = next((v for v in reversed(drift) if v is not None), None)
        q_rows.append(
            f"{target:<8} {_spark(conf)} {_fmt(last_c, 3):>7}  "
            f"{_spark(drift)} {_fmt(last_d, 3):>7}"
        )
    if q_rows:
        lines.append("")
        lines.append(
            f"{'quality':<8} {'confidence p50':<{SPARK_SLOTS + 9}} "
            f"{'drift p50':<{SPARK_SLOTS + 9}}"
        )
        lines.extend(q_rows)

    # Fleet-level channel reuse (router counters) + roster + collection
    # health.
    opened = store.latest("connections_opened_total",
                          {"replica": ROUTER_TARGET})
    reused = store.latest("connections_reused_total",
                          {"replica": ROUTER_TARGET})
    if opened and reused and (opened[1] + reused[1]) > 0:
        ratio = reused[1] / (opened[1] + reused[1])
        lines.append(f"conn reuse: {ratio:.3f} "
                     f"(opened {opened[1]:g}, reused {reused[1]:g})")
    healthy = total = 0
    for target in targets:
        if target == ROUTER_TARGET:
            continue
        total += 1
        last = store.latest("ready", {"replica": target})
        if last is not None and last[1] > 0:
            healthy += 1
    fails = 0
    for metric, labels in store.series():
        if metric == "scrape_failures_total":
            last = store.latest(metric, labels)
            if last is not None:
                fails += int(last[1])
    lines.append(f"roster: {healthy}/{total} replicas ready · "
                 f"scrape failures: {fails}")
    lines.append(_incident_line(run_dir))
    return "\n".join(lines) + "\n"


def _incident_line(run_dir: str) -> str:
    """The incident plane's one-line dash summary, from the bundle
    directory alone: open/recent counts + the last incident's identity.
    A run with no incidents renders a friendly empty state (``--once``
    must stay CI-renderable on any run_dir)."""
    bundles = _incidents.list_incidents(run_dir)
    if not bundles:
        return "incidents: none recorded"
    n_open = sum(1 for b in bundles if b.get("state") == "open")
    last = bundles[-1]
    return (
        f"incidents: {n_open} open · {len(bundles)} recent · last "
        f"{last['id']} ({last.get('rule', '?')}, "
        f"{last.get('state', '?')})"
    )
